"""Flight-record schema round-trip + the crash/exit snapshot paths.

A flight record is only useful if a post-mortem six months later can
parse it blind: every record must be one self-contained JSON line with
the schema tag, the in-flight span naming, the bounded span/metric
history, and the registered resilience sections — asserted here by
writing records through every entry point and reading them back cold.
"""

import json
import os
import signal
import time

import pytest

from chainermn_tpu.observability import FLIGHT_SCHEMA, FlightRecorder
from chainermn_tpu.observability import flight as oflight
from chainermn_tpu.observability import tracing as otrace
from chainermn_tpu.resilience import PeerFailedError, RankDivergedError
from chainermn_tpu.resilience.guard import HealthEscalationInterrupt
from chainermn_tpu.resilience.preemption import PreemptionInterrupt

pytestmark = pytest.mark.tier1


def _read_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_record_schema_round_trip(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=3)
    path = rec.record("sigusr1", extra={"note": "hello"})
    assert path == str(tmp_path / "flight.rank3.jsonl")
    (entry,) = _read_records(path)
    assert entry["schema"] == FLIGHT_SCHEMA
    assert entry["reason"] == "sigusr1"
    assert entry["rank"] == 3
    assert entry["pid"] == os.getpid()
    for key in ("wall_time", "in_flight_span", "open_spans", "spans",
                "spans_evicted", "metrics", "metric_samples", "resilience"):
        assert key in entry
    assert entry["extra"] == {"note": "hello"}


def test_records_append_as_jsonl(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=0)
    rec.record("one")
    rec.record("two")
    entries = _read_records(rec.path)
    assert [e["reason"] for e in entries] == ["one", "two"]


def test_attributed_error_lifted_into_record(tmp_path):
    rec = FlightRecorder(str(tmp_path), rank=0)
    err = PeerFailedError(2, op="bcast_obj", rank=0,
                          reason="no heartbeat", kind="dead")
    rec.record("peer_failed", exc=err)
    (entry,) = _read_records(rec.path)
    e = entry["error"]
    assert e["type"] == "PeerFailedError"
    assert e["peer"] == 2 and e["op"] == "bcast_obj" and e["kind"] == "dead"


def test_in_flight_span_named_while_open(tmp_path, monkeypatch):
    # A record taken while an op is OPEN (SIGUSR1 on a blocked rank)
    # names it directly; one taken after an errored unwind (the crash
    # path) falls back to the last errored span.
    tr = otrace.tracer()
    rec = FlightRecorder(str(tmp_path), rank=0)
    with tr.span("allgather_obj"):
        rec.record("sigusr1")
    entries = _read_records(rec.path)
    assert entries[0]["in_flight_span"] == "allgather_obj"
    assert "allgather_obj" in [s["op"] for s in entries[0]["open_spans"]]


def test_provider_sections_and_provider_errors(tmp_path):
    oflight.register_provider("good", lambda: {"ok": 1})
    oflight.register_provider("bad", lambda: 1 / 0)
    try:
        rec = FlightRecorder(str(tmp_path), rank=0)
        rec.record("crash")
        (entry,) = _read_records(rec.path)
        assert entry["resilience"]["good"] == {"ok": 1}
        assert "ZeroDivisionError" in entry["resilience"]["bad"]["error"]
    finally:
        with oflight._providers_lock:
            oflight._providers.pop("good", None)
            oflight._providers.pop("bad", None)


def test_env_recorder_and_sigusr1(tmp_path, monkeypatch):
    monkeypatch.setenv("CMN_OBS_FLIGHT_DIR", str(tmp_path))
    oflight._reset_for_tests()
    try:
        rec = oflight.recorder()
        assert rec is not None and rec.directory == str(tmp_path)
        # SIGUSR1 handler installed as a side effect: poking ourselves
        # must append a record without killing the process.  The write
        # happens on a spawned thread (the handler itself must not take
        # registry/tracer locks on the interrupted thread) — poll.
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        entries = []
        while time.monotonic() < deadline and not entries:
            if os.path.exists(rec.path):
                entries = _read_records(rec.path)
            time.sleep(0.02)
        assert entries and entries[-1]["reason"] == "sigusr1"
    finally:
        oflight._reset_for_tests()
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)


def test_env_recorder_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("CMN_OBS_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("CMN_OBS_FLIGHT", "0")
    oflight._reset_for_tests()
    try:
        assert oflight.recorder() is None
    finally:
        oflight._reset_for_tests()


@pytest.mark.parametrize("exc,reason", [
    (PeerFailedError(1, op="recv_obj"), "peer_failed"),
    (RankDivergedError([1], 5), "rank_diverged"),
    (PreemptionInterrupt(7), "preemption_exit"),
    (HealthEscalationInterrupt("skip budget", 9), "health_escalation_exit"),
    (RuntimeError("anything"), "crash"),
])
def test_snapshot_on_crash_reason_taxonomy(tmp_path, monkeypatch,
                                           exc, reason):
    monkeypatch.setenv("CMN_OBS_FLIGHT_DIR", str(tmp_path))
    monkeypatch.delenv("CMN_OBS_FLIGHT", raising=False)
    oflight._reset_for_tests()
    try:
        path = oflight.snapshot_on_crash(exc)
        assert path is not None
        entry = _read_records(path)[-1]
        assert entry["reason"] == reason
        assert entry["error"]["type"] == type(exc).__name__
    finally:
        oflight._reset_for_tests()


def test_snapshot_on_crash_dormant_without_env(monkeypatch):
    monkeypatch.delenv("CMN_OBS_FLIGHT_DIR", raising=False)
    oflight._reset_for_tests()
    try:
        assert oflight.snapshot_on_crash(RuntimeError("x")) is None
    finally:
        oflight._reset_for_tests()


def test_record_survives_unserializable_extra(tmp_path):
    class Weird:
        def __repr__(self):
            return "<weird>"

    rec = FlightRecorder(str(tmp_path), rank=0)
    assert rec.record("crash", extra={"obj": Weird()}) is not None
    (entry,) = _read_records(rec.path)
    assert entry["extra"]["obj"] == "<weird>"


def test_retention_cap_prunes_oldest_first(tmp_path, monkeypatch):
    """CMN_OBS_FLIGHT_MAX (ISSUE 12 satellite): under a supervised
    relaunch loop with an explicit flight dir, every attempt appends to
    the same per-rank file forever — the recorder keeps only the newest
    N records, oldest pruned first."""
    monkeypatch.setenv("CMN_OBS_FLIGHT_MAX", "3")
    rec = FlightRecorder(str(tmp_path), rank=0)
    assert rec.max_records == 3
    for i in range(5):
        rec.record("sigusr1", extra={"i": i})
    entries = _read_records(rec.path)
    assert [e["extra"]["i"] for e in entries] == [2, 3, 4]
    # A FRESH recorder on the already-over-cap file (a relaunched
    # attempt) prunes on its first record too.
    monkeypatch.setenv("CMN_OBS_FLIGHT_MAX", "2")
    rec2 = FlightRecorder(str(tmp_path), rank=0)
    rec2.record("crash", extra={"i": 99})
    entries = _read_records(rec2.path)
    assert [e["extra"]["i"] for e in entries] == [4, 99]


def test_retention_cap_zero_disables(tmp_path, monkeypatch):
    monkeypatch.setenv("CMN_OBS_FLIGHT_MAX", "0")
    rec = FlightRecorder(str(tmp_path), rank=0)
    for i in range(6):
        rec.record("sigusr1", extra={"i": i})
    assert len(_read_records(rec.path)) == 6
