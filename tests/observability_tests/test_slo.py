"""SLO monitor: exact rolling quantiles, drift detection, merge contract.

The estimator's quantiles must equal an exact oracle recompute over the
same window (they are not approximations — the window holds raw values),
the drift detector must fire only when p95 actually leaves the envelope,
and the ``serve.slo.*`` histograms must keep the PR-3 exact cross-rank
merge property (fixed default edges).
"""

import json

import numpy as np
import pytest

import chainermn_tpu.observability as obs
from chainermn_tpu.observability import MetricsRegistry, merge_snapshots
from chainermn_tpu.observability.aggregate import MetricsAggregator
from chainermn_tpu.observability.metrics import (
    DEFAULT_MS_EDGES,
    histogram_quantile,
)
from chainermn_tpu.observability.slo import (
    STREAMS,
    SLOMonitor,
    rolling_quantile,
)

pytestmark = pytest.mark.tier1


def _oracle_quantile(values, q):
    """Independent nearest-rank recompute (the bench's _pct definition)."""
    xs = sorted(values)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def test_rolling_quantiles_match_exact_oracle():
    rng = np.random.RandomState(7)
    window = 64
    mon = SLOMonitor(registry=MetricsRegistry(), window=window,
                     min_samples=8)
    stream = []
    for v in rng.lognormal(1.0, 0.8, size=200):
        mon.observe("token", float(v))
        stream.append(float(v))
        tail = stream[-window:]
        for q in (0.0, 0.5, 0.95, 1.0):
            assert mon.quantile("token", q) == _oracle_quantile(tail, q)
    # check() reports the same numbers it publishes as gauges.
    rep = mon.check()["token"]
    assert rep["p50_ms"] == _oracle_quantile(stream[-window:], 0.5)
    assert rep["p95_ms"] == _oracle_quantile(stream[-window:], 0.95)
    assert rep["n"] == window


def test_rolling_quantile_empty_and_helper():
    mon = SLOMonitor(registry=MetricsRegistry())
    assert mon.quantile("ttft", 0.95) is None
    assert mon.check() == {}
    assert rolling_quantile([], 0.5) is None
    assert rolling_quantile([3.0], 0.95) == 3.0


def test_histograms_use_fixed_default_edges():
    reg = MetricsRegistry()
    mon = SLOMonitor(registry=reg, window=16, min_samples=4)
    for s in STREAMS:
        mon.observe(s, 1.0)
    snap = reg.snapshot()
    for s in STREAMS:
        rec = snap[f"serve.slo.{s}_ms"]
        assert rec["type"] == "histogram"
        assert tuple(rec["edges"]) == tuple(DEFAULT_MS_EDGES)
        assert rec["count"] == 1
    with pytest.raises(ValueError, match="unknown SLO stream"):
        mon.observe("nope", 1.0)
    with pytest.raises(ValueError, match=">= 1"):
        SLOMonitor(registry=reg, window=0)


def test_drift_detector_fires_on_shift_quiet_otherwise():
    reg = MetricsRegistry()
    mon = SLOMonitor(registry=reg, window=64, min_samples=16,
                     tolerance=0.5)
    rng = np.random.RandomState(0)
    # Calibration + steady state: ~10ms with mild jitter — no breach.
    for _ in range(48):
        mon.observe("token", float(rng.normal(10.0, 0.5)))
    rep = mon.check()["token"]
    assert rep["calibrated"] and rep["ref_p95_ms"] is not None
    assert rep["breached"] is False
    assert abs(rep["drift"]) < 0.5
    assert reg.snapshot()["serve.slo.token.breaches"]["value"] == 0
    # Regime shift: 4x the baseline — p95 leaves the envelope.
    for _ in range(64):
        mon.observe("token", float(rng.normal(40.0, 0.5)))
    rep = mon.check()["token"]
    assert rep["breached"] is True
    assert rep["drift"] > 0.5
    snap = reg.snapshot()
    assert snap["serve.slo.token.breaches"]["value"] >= 1
    assert snap["serve.slo.p95_drift"]["value"] > 0.5
    # The reference stays latched — a drifting run must not re-baseline.
    assert rep["ref_p95_ms"] == pytest.approx(
        mon.check()["token"]["ref_p95_ms"]
    )


def test_absolute_target_via_env(monkeypatch):
    monkeypatch.setenv("CMN_SLO_TOKEN_P95_MS", "20")
    reg = MetricsRegistry()
    mon = SLOMonitor(registry=reg, window=32, min_samples=4,
                     tolerance=0.25)
    for _ in range(8):
        mon.observe("token", 50.0)
    rep = mon.check()["token"]
    assert rep["ref_p95_ms"] == 20.0 and not rep["calibrated"]
    assert rep["breached"] is True  # 50 > 20 * 1.25
    # Inside the envelope: quiet.
    mon2 = SLOMonitor(registry=MetricsRegistry(), window=32,
                      min_samples=4, tolerance=0.25)
    for _ in range(8):
        mon2.observe("token", 22.0)
    assert mon2.check()["token"]["breached"] is False


def test_cross_rank_histogram_merge_is_exact():
    """Two ranks' serve.slo histograms merge to exactly the histogram a
    single observer of all values would have built."""
    rng = np.random.RandomState(3)
    a_vals = rng.lognormal(0.5, 1.0, size=120).tolist()
    b_vals = rng.lognormal(2.0, 0.7, size=80).tolist()
    reg_a, reg_b, reg_one = (MetricsRegistry() for _ in range(3))
    mon_a = SLOMonitor(registry=reg_a, window=32, min_samples=4)
    mon_b = SLOMonitor(registry=reg_b, window=32, min_samples=4)
    mon_one = SLOMonitor(registry=reg_one, window=32, min_samples=4)
    for v in a_vals:
        mon_a.observe("ttft", v)
        mon_one.observe("ttft", v)
    for v in b_vals:
        mon_b.observe("ttft", v)
        mon_one.observe("ttft", v)
    merged = merge_snapshots([reg_a.snapshot(), reg_b.snapshot()])
    one = reg_one.snapshot()["serve.slo.ttft_ms"]
    got = merged["serve.slo.ttft_ms"]
    assert got["counts"] == one["counts"]
    assert got["count"] == one["count"]
    assert got["sum"] == pytest.approx(one["sum"])
    assert got["min"] == one["min"] and got["max"] == one["max"]
    # Fleet quantile off the merged buckets == the single observer's
    # estimate (merging never degrades it).
    for q in (0.5, 0.95):
        assert histogram_quantile(got, q) == pytest.approx(
            histogram_quantile(one, q)
        )


def test_histogram_quantile_bounds():
    reg = MetricsRegistry()
    h = reg.histogram("x.ms")
    assert histogram_quantile(h.to_dict(), 0.95) is None
    for v in (1.0, 2.0, 3.0, 4.0, 120.0):
        h.observe(v)
    rec = h.to_dict()
    p50 = histogram_quantile(rec, 0.5)
    p95 = histogram_quantile(rec, 0.95)
    assert rec["min"] <= p50 <= p95 <= rec["max"]
    with pytest.raises(ValueError, match="quantile"):
        histogram_quantile(rec, 1.5)


def test_aggregator_quantiles_section(tmp_path):
    reg = MetricsRegistry()
    mon = SLOMonitor(registry=reg, window=16, min_samples=4)
    for v in (1.0, 2.0, 5.0, 9.0):
        mon.observe("token", v)
    agg = MetricsAggregator(out_dir=str(tmp_path), quantiles=(0.5, 0.95))
    line = agg.collect(0, {"rank": 0, "registry": reg.snapshot()})
    qs = line["quantiles"]["serve.slo.token_ms"]
    assert qs["p50"] is not None and qs["p95"] is not None
    assert qs["p50"] <= qs["p95"]
    # The feed line on disk carries the same section, strict JSON.
    on_disk = [json.loads(ln) for ln in
               open(agg.merged_path).read().splitlines()]
    assert on_disk[-1]["quantiles"]["serve.slo.token_ms"]["p95"] == \
        pytest.approx(qs["p95"])


def test_cmn_obs_off_skips_global_registry():
    """With the master switch off, a registry-less monitor publishes
    nothing into the global registry (estimator still works)."""
    from chainermn_tpu.observability.metrics import registry as global_reg

    def counts():
        return {
            k: v.get("count", v.get("value"))
            for k, v in global_reg().snapshot().items()
            if k.startswith("serve.slo.")
        }

    before = counts()
    obs.set_enabled(False)
    try:
        mon = SLOMonitor(window=8, min_samples=2)
        for _ in range(4):
            mon.observe("token", 5.0)
        rep = mon.check()["token"]
        assert rep["p95_ms"] == 5.0  # the window still answers
        assert counts() == before
    finally:
        obs.set_enabled(None)
