"""Device/compile plane: the watcher, blame diffs, budgets, roofline.

The load-bearing contracts (ISSUE 11):

* a watched program's ``compiles`` reads IDENTICALLY to the jit cache's
  ``_cache_size()`` (the hand-rolled counters the watcher replaced);
* an induced shape-change recompile yields a blame record naming the
  changed argument and axis, and flips ``compile.budget_exceeded``;
* the ``device.*`` MFU gauge agrees with ``bench.py``'s existing MFU
  arithmetic (``utils.mfu`` over the same compiled step) to < 0.1 %;
* the FLOP helpers hoisted out of ``utils`` stay importable from both
  homes and are the SAME objects (no forked accounting).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.observability import device as odev
from chainermn_tpu.observability.metrics import MetricsRegistry

pytestmark = pytest.mark.tier1


def _watch():
    return odev.CompileWatch(registry=MetricsRegistry())


# ------------------------------------------------------------ re-exports
def test_flop_helpers_hoisted_with_back_compat_reexports():
    import chainermn_tpu.utils as utils

    assert utils.PEAK_BF16_FLOPS is odev.PEAK_BF16_FLOPS
    assert utils.compiled_flops is odev.compiled_flops
    assert utils.attention_core_flops is odev.attention_core_flops
    # The package-level exports too.
    import chainermn_tpu.observability as obs

    assert obs.PEAK_BF16_FLOPS is odev.PEAK_BF16_FLOPS


def test_utils_mfu_delegates_to_device_formula():
    from chainermn_tpu.utils import _mfu_pct

    want = odev.mfu_pct(1e12, 0.1, 2, device_kind="TPU v5e")
    got = _mfu_pct(1e12, 0.1, 2, "TPU v5e")
    assert want is not None and got == want


# ---------------------------------------------------------- the watcher
def test_watched_function_counts_match_cache_size():
    w = _watch()
    f = w.wrap(jax.jit(lambda x: x * 2), "p")
    assert f.compiles == 0 == f._cache_size()
    f(jnp.ones((4,)))
    assert f.compiles == 1 == f._cache_size()
    f(jnp.ones((4,)))  # cache hit
    assert f.compiles == 1 == f._cache_size()
    f(jnp.ones((6,)))  # new variant
    assert f.compiles == 2 == f._cache_size()


def test_compile_records_carry_signature_and_time():
    w = _watch()
    f = w.wrap(jax.jit(lambda x, n: x + n), "sig")
    f(jnp.ones((3, 5), jnp.float32), 7)
    recs = [r for r in w.records() if r["program"] == "sig"]
    assert len(recs) == 1
    sig = recs[0]["signature"]
    arr = [v for v in sig.values() if v.get("shape") == [3, 5]]
    assert arr and arr[0]["dtype"] == "float32"
    # Python-int args record type only: their VALUE never retriggers a
    # compile, so recording it would pollute every later blame diff.
    assert {"py": "int"} in sig.values()
    assert recs[0]["compile_s"] >= 0.0


def test_induced_recompile_blames_changed_axis():
    w = _watch()
    f = w.wrap(jax.jit(lambda x: x.sum()), "blame", budget=1)
    f(jnp.ones((4, 8)))
    f(jnp.ones((4, 16)))  # axis 1 grows -> recompile
    blames = w.blames()
    assert len(blames) == 1
    rec = blames[0]
    assert rec["program"] == "blame" and rec["budget_exceeded"] is True
    (change,) = rec["diff"]
    assert change["axes"] == [1]
    assert change["before"]["shape"] == [4, 8]
    assert change["after"]["shape"] == [4, 16]
    assert "dtype_changed" not in change


def test_dtype_change_blamed_as_dtype():
    w = _watch()
    f = w.wrap(jax.jit(lambda x: x * 1), "dt")
    f(jnp.ones((4,), jnp.float32))
    f(jnp.ones((4,), jnp.int32))
    (change,) = w.blames()[-1]["diff"]
    assert change["dtype_changed"] is True and change["axes"] == []


def test_budget_gauge_flips_only_past_budget():
    reg = MetricsRegistry()
    w = odev.CompileWatch(registry=reg)
    f = w.wrap(jax.jit(lambda x: x + 1), "b", budget=2)
    f(jnp.ones((2,)))
    f(jnp.ones((3,)))  # 2 variants: at budget, not over
    assert reg.snapshot()["compile.budget_exceeded"]["value"] == 0
    assert w.budget_violations == 0 and not f.over_budget
    f(jnp.ones((5,)))  # third variant: over
    assert reg.snapshot()["compile.budget_exceeded"]["value"] == 1
    assert f.over_budget
    assert reg.snapshot()["compile.count"]["value"] == 3


def test_wrap_returns_raw_jit_when_obs_disabled():
    import chainermn_tpu.observability as obs

    obs.set_enabled(False)
    try:
        raw = jax.jit(lambda x: x)
        assert odev.watch().wrap(raw, "off") is raw
    finally:
        obs.set_enabled(None)


def test_wrapper_forwards_lower_and_attrs():
    w = _watch()
    f = w.wrap(jax.jit(lambda x: x * 3), "fwd")
    compiled = f.lower(jnp.ones((2, 2))).compile()
    cost = odev.cost_dict(compiled)
    assert cost and cost["flops"] > 0
    # Arbitrary attribute access forwards to the underlying jit object.
    assert callable(f.lower)


def test_ring_is_bounded():
    w = odev.CompileWatch(registry=MetricsRegistry(), ring=4)
    f = w.wrap(jax.jit(lambda x: x - 1), "ring")
    for n in range(2, 9):
        f(jnp.ones((n,)))
    assert len(w.records()) == 4
    assert w.total_compiles == 7


def test_flight_section_names_programs_and_blames():
    # The flight section reads the PROCESS watch — wrap through it, with
    # a private program name so parallel state never collides.
    w = odev.watch()
    f = w.wrap(jax.jit(lambda x: x / 2), "flighty")
    f(jnp.ones((2,)))
    sec = w.flight_section()
    mine = [p for p in sec["programs"] if p["program"] == "flighty"]
    assert mine == [{"program": "flighty", "compiles": 1, "budget": None,
                     "over_budget": False}]
    assert sec["total_compiles"] >= 1
    # Blame entries in the flight section elide the full signature.
    f(jnp.ones((9,)))
    sec = w.flight_section()
    mine = [b for b in sec["recent_blames"]
            if b["program"] == "flighty"]
    assert mine and "signature" not in mine[0] and mine[0]["diff"]


def test_flight_record_carries_compile_section(tmp_path):
    from chainermn_tpu.observability.flight import FlightRecorder

    odev.watch()  # ensure the provider is installed
    rec = FlightRecorder(str(tmp_path), rank=0)
    rec.record("test")
    import json

    with open(rec.path) as f:
        entry = json.loads(f.readline())
    sec = entry["resilience"]["compile"]
    assert "programs" in sec and "recent_blames" in sec


# ------------------------------------------------------------- roofline
def test_roofline_fields():
    cost = {"flops": 2e12, "bytes accessed": 1e10}
    r = odev.roofline(cost, 0.5, n_devices=1, device_kind="TPU v5e")
    assert r["tflops_per_device"] == pytest.approx(4.0)
    assert r["arithmetic_intensity"] == pytest.approx(200.0)
    # peak 197e12 -> mfu = 4/197*100
    assert r["mfu_pct"] == pytest.approx(100 * 4e12 / 197e12)
    assert r["roofline_gap_x"] == pytest.approx(100 / r["mfu_pct"])
    # Flash correction adds to the FLOPs but not to the AI (the kernel's
    # HBM traffic is equally invisible to the counter).
    r2 = odev.roofline(cost, 0.5, device_kind="TPU v5e",
                       extra_flops=2e12)
    assert r2["tflops_per_device"] == pytest.approx(8.0)
    assert r2["arithmetic_intensity"] == pytest.approx(200.0)
    # Unknown device kind: throughput still reported, MFU absent.
    r3 = odev.roofline(cost, 0.5, device_kind="???")
    assert r3["mfu_pct"] is None and r3["tflops_per_device"] > 0


def test_publish_roofline_sets_device_gauges():
    reg = MetricsRegistry()
    w = odev.CompileWatch(registry=reg)
    f = w.wrap(jax.jit(lambda a, b: a @ b), "mm")
    f(jnp.ones((64, 64)), jnp.ones((64, 64)))
    r = w.publish_roofline(f, 2.0, device_kind="TPU v5e")
    assert r is not None
    snap = reg.snapshot()
    assert snap["device.mm.tflops"]["value"] == pytest.approx(
        r["tflops_per_device"]
    )
    assert snap["device.mm.mfu_pct"]["value"] == pytest.approx(
        r["mfu_pct"]
    )
    assert snap["device.mm.ai"]["value"] == pytest.approx(
        r["arithmetic_intensity"]
    )
    assert snap["device.mm.roofline_gap_x"]["value"] == pytest.approx(
        100.0 / r["mfu_pct"]
    )


def test_cost_analysis_capture_false_never_compiles(monkeypatch):
    """``capture=False`` (the serving scheduler's on-cadence path) must
    never trigger the one-time extra lowering — a synchronous backend
    compile between decode iterations would stall live traffic."""
    w = _watch()
    f = w.wrap(jax.jit(lambda x: x + 1), "nocap")
    f(jnp.ones((4,)))
    monkeypatch.setattr(
        f, "lower",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("compiled"))
    )
    assert f.cost_analysis(capture=False) is None
    assert w.publish_roofline(f, 1.0, capture=False) is None
    monkeypatch.undo()
    assert f.cost_analysis() is not None  # the drain path captures
    assert f.cost_analysis(capture=False) is not None  # now cached


def test_cost_analysis_memoized_across_same_signature(monkeypatch):
    w = _watch()
    impl = lambda x: (x * 2).sum()  # noqa: E731
    f1 = w.wrap(jax.jit(impl), "memo")
    f2 = w.wrap(jax.jit(impl), "memo")
    f1(jnp.ones((8,)))
    f2(jnp.ones((8,)))
    c1 = f1.cost_analysis()
    assert c1 and c1["flops"] > 0
    # Same (program, signature): the second engine's capture is a memo
    # hit — prove it by forbidding further lowering.
    monkeypatch.setattr(
        f2, "lower",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("re-lowered"))
    )
    assert f2.cost_analysis() == c1


# --------------------------------------- the LM train-step MFU contract
def test_train_step_mfu_gauge_matches_bench_arithmetic(tmp_path):
    """The acceptance pin: ``device.train_step.mfu_pct`` published off
    the watcher's captured cost model agrees with ``bench.py``'s
    existing arithmetic (``utils.mfu`` over the AOT-compiled step) to
    < 0.1 % at the same step time / device kind."""
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import TransformerLM, lm_loss
    from chainermn_tpu.utils import mfu as utils_mfu

    comm = cmn.create_communicator("xla")
    model = TransformerLM(vocab=64, n_layers=1, d_model=32, n_heads=2,
                          d_ff=64, max_len=16)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16), np.int32)
    )["params"]
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
    step = opt.make_train_step(lm_loss(model), has_aux=True)
    assert isinstance(step, odev.WatchedFunction)
    assert step.program == "train_step"
    toks = np.random.RandomState(0).randint(
        0, 64, size=(8, 16)
    ).astype(np.int32)
    batch = comm.shard_batch((toks, toks))
    state = opt.init(params)
    state, _ = step(state, batch)
    assert step.compiles == 1

    # bench.py's side: utils.mfu over the compiled step (its own
    # lower().compile(), exactly like benchmarks/lm.py).
    step_time_s, n_dev, kind = 0.050, 1, "TPU v5e"
    compiled = step.lower(state, batch).compile()
    want = utils_mfu(compiled, step_time_s, n_dev, kind)
    assert want is not None and want > 0

    # Watcher's side: publish_roofline off the captured cost model.
    reg = MetricsRegistry()
    r = odev.watch().publish_roofline(
        step, step_time_s * 1e3, n_devices=n_dev, device_kind=kind,
        registry=reg,
    )
    got = reg.snapshot()["device.train_step.mfu_pct"]["value"]
    assert got == pytest.approx(r["mfu_pct"])
    assert abs(got - want) / want < 1e-3  # < 0.1 %
