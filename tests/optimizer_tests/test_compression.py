"""int8 error-feedback gradient compression (``grad_compression='int8_ef'``).

Beyond-parity tier over the reference's fp16 allreduce (SURVEY §2.3 gradient
compression row).  Contracts pinned here:

  * one-step algebra: the applied update is exactly the shared-scale int8
    dequantization of the mean gradient, and each device's residual is
    exactly its own code error ``c − q·s``;
  * error feedback: with constant gradients the residual re-injection makes
    the CUMULATIVE applied update track ``k · ḡ`` to within one quantization
    step — the compression bias does not accumulate;
  * end-to-end: compressed training converges next to the fp32 oracle.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.models import MLP, classification_loss
from chainermn_tpu.datasets import make_synthetic_classification


def _mean_loss(params, batch):
    # grad w.r.t. w is exactly batch.mean(axis=0) — a known, constant grad.
    x = batch[0] if isinstance(batch, (tuple, list)) else batch
    return jnp.mean(x @ params["w"])


def test_one_step_quantization_algebra(devices):
    comm = cmn.create_communicator("xla", devices=devices)
    n = comm.size
    w0 = np.zeros((4, 1), np.float32)
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(1.0), comm, grad_compression="int8_ef"
    )
    state = opt.init({"w": w0})
    assert state.ef_residual["w"].shape == (n, 4, 1)

    # Per-device rows: device d sees x-row full of (d+1), so its local grad
    # is (d+1)·ones(4); the mean grad is (n+1)/2 · ones.
    x = np.repeat(
        np.arange(1, n + 1, dtype=np.float32)[:, None], 4, axis=1
    ).reshape(n, 4)
    state, _ = opt.update(state, (x,), _mean_loss)

    # Shared scale: amax over devices = n, s = n/127; device d's code is
    # round(d·127/n); dequantized mean = sum(q)·s/n.
    s = n / 127.0
    qs = np.round(np.arange(1, n + 1) / s)
    want_mean = qs.sum() * s / n
    got_update = -np.asarray(state.params["w"])  # lr 1.0, sgd ⇒ −mean grad
    np.testing.assert_allclose(got_update, want_mean, rtol=1e-6)

    # Residuals: device d carries exactly (d+1) − q_d·s.
    resid = np.asarray(jax.device_get(state.ef_residual["w"]))
    for d in range(n):
        np.testing.assert_allclose(
            resid[d], (d + 1) - qs[d] * s, atol=1e-6
        )


def test_error_feedback_cancels_bias(devices):
    """Constant grads for k steps: cumulative applied update stays within
    one quantization step of k·ḡ per element (without EF the per-step code
    error would accumulate k times)."""
    comm = cmn.create_communicator("xla", devices=devices)
    n = comm.size
    k = 12
    w0 = np.zeros((8, 1), np.float32)
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(1.0), comm, grad_compression="int8_ef"
    )
    state = opt.init({"w": w0})
    rng = np.random.RandomState(3)
    rows = rng.uniform(0.2, 1.0, size=(n, 8)).astype(np.float32)
    gbar = rows.mean(axis=0)  # the true mean gradient, constant across steps
    for _ in range(k):
        state, _ = opt.update(state, (rows,), _mean_loss)
    got = -np.asarray(state.params["w"])[:, 0]  # cumulative update
    s = np.abs(rows).max() / 127.0  # scale is constant across steps
    np.testing.assert_array_less(np.abs(got - k * gbar), 1.5 * s + 1e-6)


def test_compressed_training_tracks_fp32(devices):
    """MLP classification: int8+EF training lands next to the fp32 run."""
    comm = cmn.create_communicator("xla", devices=devices)
    model = MLP(hidden=(32,), n_out=10)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16), np.float32)
    )["params"]
    loss_fn = classification_loss(model)
    ds = make_synthetic_classification(n=64 * 20, dim=16, seed=0)
    x, y = ds.arrays
    batches = [(x[i * 64:(i + 1) * 64], y[i * 64:(i + 1) * 64])
               for i in range(20)]

    finals = {}
    for mode in ("fp32", "int8_ef"):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05, momentum=0.9), comm,
            grad_compression=None if mode == "fp32" else "int8_ef",
        )
        state = opt.init(params)
        losses = []
        for b in batches:
            state, m = opt.update(state, b, loss_fn, has_aux=True)
            losses.append(float(m["loss"]))
        finals[mode] = losses[-1]
    # Converges, and lands within 10% of the uncompressed loss.
    assert finals["int8_ef"] < losses[0], finals
    assert finals["int8_ef"] < finals["fp32"] * 1.10 + 0.02, finals


def test_zero_int8_ef_matches_replicated_int8_ef(devices):
    """ZeRO + int8_ef (quantize → psum_scatter int32 codes → dequantize the
    owned shard) must produce the SAME numerics as the replicated int8_ef
    tier — the reduce-scatter is the scatter half of the identical
    allreduce, and padding zeros neither move the shared scale nor the
    codes."""
    comm = cmn.create_communicator("xla", devices=devices)
    model = MLP(hidden=(16,), n_out=5)
    params = model.init(
        jax.random.PRNGKey(1), np.zeros((1, 12), np.float32)
    )["params"]
    loss_fn = classification_loss(model)
    ds = make_synthetic_classification(n=64 * 4, dim=12, classes=5, seed=4)
    x, y = ds.arrays
    batches = [(x[i * 64:(i + 1) * 64], y[i * 64:(i + 1) * 64])
               for i in range(4)]

    ropt = cmn.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm, grad_compression="int8_ef"
    )
    rstate = ropt.init(params)
    zopt = cmn.create_zero_optimizer(
        optax.sgd(0.1, momentum=0.9), comm, grad_compression="int8_ef"
    )
    zstate = zopt.init(params)
    for s in zstate.ef_residual:
        # 1/N-sharded: every device holds exactly its own residual row
        for shard in s.addressable_shards:
            assert (
                int(np.prod(shard.data.shape))
                == int(np.prod(s.shape)) // comm.size
            ), (shard.data.shape, s.shape)

    for b in batches:
        rstate, _ = ropt.update(rstate, b, loss_fn, has_aux=True)
        zstate, _ = zopt.update(zstate, b, loss_fn, has_aux=True)
    zparams = zopt.materialize_params(zstate)
    for a, bb in zip(jax.tree_util.tree_leaves(rstate.params),
                     jax.tree_util.tree_leaves(zparams)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(bb)),
            atol=2e-6, rtol=2e-6,
        )


def _fuzz_setup(devices):
    comm = cmn.create_communicator("xla", devices=devices)
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(1.0), comm, grad_compression="int8_ef"
    )
    return comm, opt


def test_int8_ef_quantization_properties(devices):
    """Property fuzz (hypothesis): for arbitrary per-device gradients, one
    compressed step satisfies the quantization algebra —

      * |applied − mean(g)| ≤ s/2 (shared scale s = max|g|/127: each code
        rounds by ≤ 1/2, so the device-mean error is ≤ s/2),
      * every device's residual is exactly its own code error, i.e.
        g_d − r_d is an integer multiple of s in [−127s, 127s].
    """
    pytest.importorskip(
        "hypothesis", reason="property-fuzz tier needs hypothesis installed"
    )
    from hypothesis import given, settings
    from hypothesis import strategies as st
    from hypothesis.extra import numpy as hnp

    comm, opt = _fuzz_setup(devices)
    n = comm.size
    K = 16

    @settings(max_examples=40, deadline=None)
    @given(
        hnp.arrays(
            np.float32, (n, K),
            elements=st.floats(-50.0, 50.0, width=32,
                               allow_nan=False, allow_infinity=False),
        )
    )
    def check(rows):
        state = opt.init({"w": np.zeros((K, 1), np.float32)})
        state, _ = opt.update(state, (rows,), _mean_loss)
        applied = -np.asarray(state.params["w"])[:, 0]  # lr 1 sgd
        resid = np.asarray(jax.device_get(state.ef_residual["w"]))[..., 0]
        amax = np.abs(rows).max()
        if amax == 0.0:
            np.testing.assert_array_equal(applied, 0.0)
            return
        # Mirror the quantizer's scale clamp (optimizers/__init__.py:
        # `s = max(amax, 1e-30) / 127`): hypothesis can draw subnormal
        # gradients (~1e-38) whose unclamped scale would be denormal —
        # the product clamps there, so the error bound must use the
        # clamped scale too.
        s = max(amax, np.float32(1e-30)) / 127.0
        gbar = rows.mean(axis=0)
        assert np.all(np.abs(applied - gbar) <= s / 2 + 1e-5 * amax), (
            np.abs(applied - gbar).max(), s)
        codes = (rows - resid) / s  # must be integers in [-127, 127]
        np.testing.assert_allclose(codes, np.round(codes),
                                   atol=1e-3)
        assert np.all(np.abs(codes) <= 127.0 + 1e-3)

    check()


def test_compression_rejects_bad_mode(devices):
    comm = cmn.create_communicator("xla", devices=devices)
    with pytest.raises(ValueError):
        cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, grad_compression="int4"
        )
