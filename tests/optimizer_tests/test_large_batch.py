"""Large-batch tier tests: LARS/LAMB masks, trust-ratio behavior, schedules.

Oracle strategy: (a) the bias/BN exemption is checked against a plain
momentum-SGD oracle — exempt leaves must take *exactly* the unmasked update;
(b) the layer-wise trust ratio is checked by its defining property (update
magnitude scales with the layer's weight norm) rather than by re-deriving
optax's internals; (c) the 8-device DP run must match the single-device run
exactly, like every other optimizer tier.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.models import MLP, classification_loss
from chainermn_tpu.optimizers import (
    kernel_mask,
    lamb,
    lars,
    linear_scaled_lr,
    warmup_cosine_schedule,
)


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense": {
            "kernel": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
            "bias": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        },
        "bn": {"scale": jnp.ones((8,), jnp.float32)},
    }


def _grads(seed=1, like=None):
    rng = np.random.RandomState(seed)
    return jax.tree.map(
        lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), like
    )


def test_kernel_mask_is_rank_based():
    m = kernel_mask(_tree())
    assert m["dense"]["kernel"] is True or m["dense"]["kernel"] == True  # noqa: E712
    assert not m["dense"]["bias"]
    assert not m["bn"]["scale"]


def test_linear_scaled_lr():
    assert linear_scaled_lr(0.1, 8192, 256) == pytest.approx(3.2)
    assert linear_scaled_lr(0.1, 256, 256) == pytest.approx(0.1)
    with pytest.raises(ValueError):
        linear_scaled_lr(0.1, 0, 256)


def test_warmup_cosine_schedule_shape():
    s = warmup_cosine_schedule(2.0, warmup_steps=10, total_steps=110)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(10)) == pytest.approx(2.0)
    # Monotone ramp during warmup.
    ramp = [float(s(i)) for i in range(11)]
    assert all(b >= a for a, b in zip(ramp, ramp[1:]))
    # Cosine decays to ~0 at the end.
    assert float(s(110)) == pytest.approx(0.0, abs=1e-6)
    # Midpoint of decay is between the endpoints.
    assert 0.0 < float(s(60)) < 2.0
    # Degenerate forms.
    const = warmup_cosine_schedule(1.5, warmup_steps=0, total_steps=0)
    assert float(const(7)) == pytest.approx(1.5)
    flat = warmup_cosine_schedule(1.0, warmup_steps=5, total_steps=5)
    assert float(flat(5)) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        warmup_cosine_schedule(1.0, warmup_steps=10, total_steps=5)


def test_lars_exempt_leaves_take_plain_momentum_sgd_update():
    """Rank ≤ 1 leaves (bias, BN scale) must update exactly like momentum
    SGD — no trust ratio, no weight decay — across multiple steps (momentum
    state must also match)."""
    params = _tree()
    tx = lars(0.1, weight_decay=1e-2, momentum=0.9)
    oracle = optax.sgd(0.1, momentum=0.9)
    state, ostate = tx.init(params), oracle.init(params)
    p, op = params, params
    for step in range(3):
        g = _grads(seed=10 + step, like=params)
        u, state = tx.update(g, state, p)
        p = optax.apply_updates(p, u)
        ou, ostate = oracle.update(g, ostate, op)
        op = optax.apply_updates(op, ou)
    for path in (("dense", "bias"), ("bn", "scale")):
        a = p[path[0]][path[1]]
        b = op[path[0]][path[1]]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    # ... while the kernel leaf must NOT match plain SGD (trust ratio bites).
    assert not np.allclose(
        np.asarray(p["dense"]["kernel"]), np.asarray(op["dense"]["kernel"])
    )


def test_lars_trust_ratio_scales_with_weight_norm():
    """Defining LARS property: for the same gradient, a layer with 10× the
    weight norm takes ~10× the first-step update (trust ratio ∝ |w|, up to
    the weight-decay term's small contribution to the denominator)."""
    g = {"k": jnp.full((16, 16), 0.5, jnp.float32)}
    small = {"k": jnp.full((16, 16), 0.1, jnp.float32)}
    big = jax.tree.map(lambda p: 10.0 * p, small)
    tx = lars(1.0, weight_decay=0.0, momentum=0.0)
    u_small, _ = tx.update(g, tx.init(small), small)
    u_big, _ = tx.update(g, tx.init(big), big)
    r = float(
        jnp.linalg.norm(u_big["k"]) / jnp.linalg.norm(u_small["k"])
    )
    assert r == pytest.approx(10.0, rel=1e-4)


def test_lamb_weight_decay_masked_to_kernels():
    """With zero gradient, Adam moments stay zero, so any update comes from
    decoupled weight decay — which must touch kernels only."""
    params = _tree()
    tx = lamb(0.1, weight_decay=0.1)
    g = jax.tree.map(jnp.zeros_like, params)
    u, _ = tx.update(g, tx.init(params), params)
    assert float(jnp.abs(u["dense"]["bias"]).max()) == 0.0
    assert float(jnp.abs(u["bn"]["scale"]).max()) == 0.0
    assert float(jnp.abs(u["dense"]["kernel"]).max()) > 0.0


def test_dp_lars_matches_single_device_oracle(devices):
    """8-way DP LARS (with warmup schedule) == single-device optax run on
    the identical global batch stream — the standard tier-parity oracle."""
    comm = cmn.create_communicator("xla", devices=devices)
    model = MLP(hidden=(32,), n_out=10)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16), np.float32)
    )["params"]
    loss_fn = classification_loss(model)

    sched = warmup_cosine_schedule(
        linear_scaled_lr(0.05, global_batch=64, base_batch=64),
        warmup_steps=2,
        total_steps=6,
    )
    tx = lars(sched, weight_decay=1e-3, momentum=0.9)

    from chainermn_tpu.datasets import make_synthetic_classification

    ds = make_synthetic_classification(n=6 * 64, dim=16, seed=0)
    x, y = ds.arrays
    batches = [
        (x[i * 64 : (i + 1) * 64], y[i * 64 : (i + 1) * 64]) for i in range(6)
    ]

    oparams, oopt = params, tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        updates, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, updates)

    opt = cmn.create_multi_node_optimizer(tx, comm)
    state = opt.init(params)
    for b in batches:
        state, _ = opt.update(state, b, loss_fn, has_aux=True)

    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(oparams),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        )


def test_kernel_mask_on_flat_shards_disables_everything():
    """Documented sharp edge (large_batch module docstring): under ZeRO the
    inner transform sees flat 1-D shards, where ``kernel_mask`` is all-False
    — i.e. LARS/LAMB silently degrade.  Pin the behavior that motivates the
    'replicated tier only' guidance."""
    flat = [jnp.zeros((64,)), jnp.zeros((128,))]
    m = kernel_mask(flat)
    assert not any(jax.tree_util.tree_leaves(m))
