"""Gradient accumulation (``accum_steps``): a k-way microbatched step must
match the unsplit step on the identical global batch — for per-sample-mean
losses the accumulated mean gradient is mathematically the full-batch
gradient, so the trajectories agree to float tolerance (summation order is
the only difference)."""

import numpy as np
import optax
import pytest

import jax

import chainermn_tpu as cmn
from chainermn_tpu.datasets import make_synthetic_classification
from chainermn_tpu.models import MLP, classification_loss


def _setup(devices):
    comm = cmn.create_communicator("xla", devices=devices)
    model = MLP(hidden=(32,), n_out=10)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 16), np.float32)
    )["params"]
    return comm, model, params, classification_loss(model)


def _batches(n, bs, dim=16, seed=0):
    ds = make_synthetic_classification(n=n * bs, dim=dim, seed=seed)
    x, y = ds.arrays
    return [
        (x[i * bs : (i + 1) * bs], y[i * bs : (i + 1) * bs]) for i in range(n)
    ]


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_matches_unsplit(devices, accum):
    comm, model, params, loss_fn = _setup(devices)
    batches = _batches(6, 64 * len(devices))

    finals = []
    for k in (1, accum):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1, momentum=0.9),
                                              comm)
        state = opt.init(params)
        step = opt.make_train_step(loss_fn, has_aux=True, accum_steps=k)
        for b in batches:
            state, metrics = step(state, comm.shard_batch(b))
        finals.append((state.params, float(metrics["loss"]),
                       float(metrics["accuracy"])))
    for a, b in zip(jax.tree_util.tree_leaves(finals[0][0]),
                    jax.tree_util.tree_leaves(finals[1][0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    assert abs(finals[0][1] - finals[1][1]) < 1e-4  # mean loss
    assert abs(finals[0][2] - finals[1][2]) < 1e-6  # mean accuracy


def test_accum_zero_optimizer_matches_replicated(devices):
    """ZeRO with accumulation == replicated optimizer with accumulation
    (adam, so any grad-scale bug would surface in the trajectory)."""
    comm, model, params, loss_fn = _setup(devices)
    batches = _batches(5, 32 * len(devices))

    ropt = cmn.create_multi_node_optimizer(optax.adam(1e-2), comm)
    rstate = ropt.init(params)
    rstep = ropt.make_train_step(loss_fn, has_aux=True, accum_steps=4)

    zopt = cmn.create_zero_optimizer(optax.adam(1e-2), comm)
    zstate = zopt.init(params)
    zstep = zopt.make_train_step(loss_fn, has_aux=True, accum_steps=4)

    for b in batches:
        sb = comm.shard_batch(b)
        rstate, rm = rstep(rstate, sb)
        zstate, zm = zstep(zstate, sb)
    np.testing.assert_allclose(float(rm["loss"]), float(zm["loss"]),
                               atol=1e-5)
    for a, b_ in zip(jax.tree_util.tree_leaves(rstate.params),
                     jax.tree_util.tree_leaves(
                         zopt.materialize_params(zstate))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


def test_accum_stateful_bn_runs(devices):
    """accum_steps with stateful=True threads BN stats through the scan
    sequentially (each microbatch sees the previous one's running stats)."""
    import flax.linen as nn
    import jax.numpy as jnp

    from chainermn_tpu.links import MultiNodeBatchNormalization

    comm = cmn.create_communicator("xla", devices=devices)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool):
            x = nn.Dense(16)(x)
            x = MultiNodeBatchNormalization(
                features=16, axis_name=comm.axis_name,
                use_running_average=not train,
            )(x)
            return nn.Dense(4)(x)

    net = Net()
    variables = net.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.float32),
                         train=True)

    def loss_fn(params, model_state, batch):
        x, y = batch
        logits, mut = net.apply(
            {"params": params, "batch_stats": model_state}, x, train=True,
            mutable=["batch_stats"],
        )
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, y)
        )
        return loss, ({"loss_copy": loss}, mut["batch_stats"])

    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = opt.init(variables["params"],
                     model_state=variables["batch_stats"])
    step = opt.make_train_step(loss_fn, stateful=True, accum_steps=2)
    rng = np.random.RandomState(0)
    b = (rng.normal(size=(16 * len(devices), 8)).astype(np.float32),
         rng.randint(0, 4, size=(16 * len(devices),)).astype(np.int32))
    state, metrics = step(state, comm.shard_batch(b))
    assert np.isfinite(float(metrics["loss"]))
    # Running stats moved off their init values.
    mean_leaf = jax.tree_util.tree_leaves(state.model_state)[0]
    assert float(np.abs(np.asarray(mean_leaf)).sum()) > 0


def test_accum_validation(devices):
    comm, model, params, loss_fn = _setup(devices)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
    with pytest.raises(ValueError):
        opt.make_train_step(loss_fn, accum_steps=0)
    state = opt.init(params)
    step = opt.make_train_step(loss_fn, has_aux=True, accum_steps=3)
    b = _batches(1, 8 * len(devices))[0]  # 8 per device, not divisible by 3
    with pytest.raises(ValueError, match="not divisible"):
        step(state, comm.shard_batch(b))
