"""ZeRO sharded-state optimizer tests.

Oracle: reduce-scatter + local shard update + all-gather must equal the
replicated optimizer (and plain single-device optax) EXACTLY — same
contract as the DP oracle in ``test_multi_node_optimizer.py``, plus
layout assertions that the state really is sharded (the point of ZeRO).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.datasets import make_synthetic_classification
from chainermn_tpu.models import MLP, classification_loss


def _setup(devices, **comm_kw):
    comm = cmn.create_communicator("xla", devices=devices, **comm_kw)
    model = MLP(hidden=(32,), n_out=10)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 16), np.float32))[
        "params"
    ]
    return comm, model, params, classification_loss(model)


def _batches(n, bs, dim=16, seed=0):
    ds = make_synthetic_classification(n=n * bs, dim=dim, seed=seed)
    x, y = ds.arrays
    return [(x[i * bs : (i + 1) * bs], y[i * bs : (i + 1) * bs]) for i in range(n)]


@pytest.mark.parametrize("tx_name", ["sgd_momentum", "adam"])
def test_zero_matches_single_device_oracle(devices, tx_name):
    """Sharded-state DP == plain optax on the identical global batch."""
    comm, model, params, loss_fn = _setup(devices)
    tx = (
        optax.sgd(0.1, momentum=0.9)
        if tx_name == "sgd_momentum"
        else optax.adam(1e-2)
    )
    opt = cmn.create_zero_optimizer(tx, comm)
    state = opt.init(params)
    step = opt.make_train_step(loss_fn, has_aux=True)

    batches = _batches(5, 64)

    oparams, oopt = params, tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        up, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, up)

    for b in batches:
        state, metrics = step(state, comm.shard_batch(b))
        jax.block_until_ready(state)

    got = opt.materialize_params(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(oparams)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-5
        )
    assert np.isfinite(float(metrics["loss"]))


def test_zero_state_is_sharded(devices):
    """Optimizer-state leaves live 1/N per device (the memory claim)."""
    comm, model, params, loss_fn = _setup(devices)
    tx = optax.adam(1e-3)
    opt = cmn.create_zero_optimizer(tx, comm)
    state = opt.init(params)

    n = comm.size
    param_sizes = [
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
    ]
    total_padded = sum(-(-s // n) * n for s in param_sizes)

    flat_total = sum(
        int(np.prod(v.shape)) for v in state.flat_params
    )
    assert flat_total == total_padded
    for v in state.flat_params:
        shards = v.sharding.shard_shape(v.shape)
        assert shards[0] * n == v.shape[0]  # 1/N per device

    # adam: mu/nu sharded like params, count replicated scalar
    mu_leaves = [
        s for s in jax.tree_util.tree_leaves(state.opt_state)
        if getattr(s, "ndim", 0) == 1
    ]
    assert mu_leaves, "expected flat adam moment leaves"
    for s in mu_leaves:
        assert s.sharding.shard_shape(s.shape)[0] * n == s.shape[0]


def test_zero_wire_dtype_close_to_fp32(devices):
    """bf16 reduce-scatter wire stays within bf16 tolerance of fp32."""
    comm32, model, params, loss_fn = _setup(devices)
    comm16 = cmn.create_communicator(
        "xla", devices=devices, allreduce_grad_dtype=jnp.bfloat16
    )
    tx = optax.sgd(0.1)
    o32 = cmn.create_zero_optimizer(tx, comm32)
    o16 = cmn.create_zero_optimizer(tx, comm16)
    s32, s16 = o32.init(params), o16.init(params)
    st32 = o32.make_train_step(loss_fn, has_aux=True)
    st16 = o16.make_train_step(loss_fn, has_aux=True)
    for b in _batches(3, 64):
        s32, _ = st32(s32, comm32.shard_batch(b))
        jax.block_until_ready(s32)
        s16, _ = st16(s16, comm16.shard_batch(b))
        jax.block_until_ready(s16)
    for a, b in zip(
        jax.tree_util.tree_leaves(o32.materialize_params(s32)),
        jax.tree_util.tree_leaves(o16.materialize_params(s16)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2
        )


def test_zero_global_norm_clip_matches_oracle(devices):
    """zero_clip_by_global_norm under sharding == optax.clip_by_global_norm
    single-device (plain optax clip would use per-shard norms and diverge)."""
    comm, model, params, loss_fn = _setup(devices)
    max_norm = 0.05  # small enough that clipping actually engages
    tx_sharded = optax.chain(
        cmn.zero_clip_by_global_norm(max_norm, comm), optax.sgd(0.1)
    )
    tx_oracle = optax.chain(optax.clip_by_global_norm(max_norm), optax.sgd(0.1))

    opt = cmn.create_zero_optimizer(tx_sharded, comm)
    state = opt.init(params)
    step = opt.make_train_step(loss_fn, has_aux=True)

    batches = _batches(5, 64)
    oparams, oopt = params, tx_oracle.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        up, oopt = tx_oracle.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, up)

    for b in batches:
        state, _ = step(state, comm.shard_batch(b))
        jax.block_until_ready(state)

    for a, b in zip(
        jax.tree_util.tree_leaves(opt.materialize_params(state)),
        jax.tree_util.tree_leaves(oparams),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-6, rtol=3e-6
        )


def test_zero_schedule_bearing_transform(devices):
    """Transforms with non-param-shaped leaves (scale_by_schedule's scalar
    count) must work under ZeRO — the state mapping is structural
    (optax.tree_map_params), not param-periodic."""
    comm, model, params, loss_fn = _setup(devices)
    tx = optax.chain(
        optax.scale_by_adam(),
        optax.scale_by_schedule(lambda c: -0.05 / (1.0 + 0.1 * c)),
    )
    opt = cmn.create_zero_optimizer(tx, comm)
    state = opt.init(params)
    step = opt.make_train_step(loss_fn, has_aux=True)
    batches = _batches(3, 64)

    oparams, oopt = params, tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        up, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, up)

    for b in batches:
        state, _ = step(state, comm.shard_batch(b))
        jax.block_until_ready(state)

    got = opt.materialize_params(state)
    assert jax.tree_util.tree_structure(got) == jax.tree_util.tree_structure(
        oparams
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(oparams)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )


def test_optimizer_state_specs_structural():
    """Structural spec matching: param-shaped subtrees mirror param_specs;
    counters/scalars replicate — no param-periodic assumption."""
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.optimizers import optimizer_state_specs

    params = {
        "dense": {"kernel": np.zeros((8, 4)), "bias": np.zeros((4,))},
    }
    pspecs = {
        "dense": {"kernel": P("model", None), "bias": P(None)},
    }
    tx = optax.chain(
        optax.scale_by_adam(),
        optax.scale_by_schedule(lambda c: 0.1),
        optax.add_decayed_weights(1e-4),
    )
    opt_state = tx.init(jax.tree_util.tree_map(jnp.asarray, params))
    specs = optimizer_state_specs(opt_state, params, pspecs)

    adam_state = specs[0]
    assert adam_state.mu == pspecs and adam_state.nu == pspecs
    assert adam_state.count == P()
    sched_state = specs[1]
    assert sched_state.count == P()


@pytest.mark.slow  # ~60s: the single longest tier-1 straggler (r5 budget)
def test_zero_mixed_param_dtypes_bf16_storage(devices):
    """ZeRO over a MIXED-dtype param tree — the bf16-storage LM layout
    (`TransformerLM(param_dtype=bfloat16)`: bf16 leaves + the fp32 MoE
    router).  The flat-packing must keep each leaf's dtype through
    shard/update/materialize, and the sharded update must TRACK the
    replicated optax oracle — bounded, not exact: in bf16 the 8-shard
    gradient reduction sums in a different order than the oracle's
    single-device full-batch gradient, and adafactor's update clipping /
    parameter-scale multiply amplify that ~1-ulp noise over steps (the
    fp32 oracle above stays at 3e-5; this is a bf16 property, not a ZeRO
    one).  Also pins the adafactor regression: optax's factored transforms
    keep (1,)-shaped v_row/v_col placeholders for unfactored leaves, which
    are param-MARKED but must replicate, not shard (`_flat_shardable`).
    This is the combination a >2B multi-chip run uses (bf16 storage for
    HBM + ZeRO for state scaling)."""
    from chainermn_tpu.models import TransformerLM, lm_loss

    comm = cmn.create_communicator("xla", devices=devices)
    model = TransformerLM(vocab=128, n_layers=2, d_model=32, n_heads=4,
                          d_ff=64, max_len=32, n_experts=4,
                          param_dtype=jnp.bfloat16)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32), np.int32)
    )["params"]
    loss_fn = lm_loss(model)
    tx = optax.adafactor(1e-2)
    opt = cmn.create_zero_optimizer(tx, comm)
    state = opt.init(params)
    step = opt.make_train_step(loss_fn, has_aux=True)

    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, size=(16, 32)).astype(np.int32)
    tgts = np.concatenate(
        [toks[:, 1:], np.full((16, 1), -1, np.int32)], axis=1
    )
    batches = [(toks, tgts)] * 3

    oparams, oopt = params, tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        up, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, up)

    losses = []
    for b in batches:
        state, metrics = step(state, comm.shard_batch(b))
        jax.block_until_ready(state)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses  # it really trains

    got = opt.materialize_params(state)
    got_flat = jax.tree_util.tree_flatten_with_path(got)[0]
    want_flat = jax.tree_util.tree_flatten_with_path(oparams)[0]
    for (pa, a), (pb, b) in zip(got_flat, want_flat):
        assert a.dtype == b.dtype, (jax.tree_util.keystr(pa), a.dtype)
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=0.1, rtol=0.1,
        )
    dts = {
        jax.tree_util.keystr(p): a.dtype for p, a in got_flat
    }
    assert any("router" in k and v == jnp.float32 for k, v in dts.items())
    assert any(v == jnp.bfloat16 for v in dts.values())
    assert np.isfinite(float(metrics["loss"]))
