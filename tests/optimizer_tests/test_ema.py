"""EMA (Polyak) weight averaging on the multi-node optimizer: exact
recurrence against the params trajectory, init-to-params (no debias), and
eval through the averaged copy."""

import numpy as np

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu.models import MLP, classification_loss


def _setup(ema_decay):
    comm = cmn.create_communicator("xla")
    model = MLP(hidden=(16,), n_out=4)
    rng = np.random.RandomState(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.1), comm, ema_decay=ema_decay
    )
    state = opt.init(params)
    step = opt.make_train_step(classification_loss(model), has_aux=True)
    return comm, model, (x, y), state, step


def test_ema_matches_hand_recurrence():
    d = 0.9
    comm, model, batch, state, step = _setup(d)
    sharded = comm.shard_batch(batch)
    ema_ref = jax.tree_util.tree_map(np.asarray, state.params)
    np.testing.assert_allclose(  # init: ema == params (no debias needed)
        jax.tree_util.tree_leaves(state.ema_params)[0],
        jax.tree_util.tree_leaves(state.params)[0],
    )
    for _ in range(4):
        state, _ = step(state, sharded)
        ema_ref = jax.tree_util.tree_map(
            lambda e, p: e * d + np.asarray(p) * (1 - d),
            ema_ref, state.params,
        )
    for got, want in zip(jax.tree_util.tree_leaves(state.ema_params),
                         jax.tree_util.tree_leaves(ema_ref)):
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


def test_ema_params_evaluate():
    comm, model, (x, y), state, step = _setup(0.99)
    state, _ = step(state, comm.shard_batch((x, y)))
    logits = model.apply({"params": state.ema_params}, jnp.asarray(x))
    assert logits.shape == (16, 4)
    assert bool(jnp.isfinite(logits).all())


def test_no_ema_by_default():
    comm, model, batch, state, step = _setup(None)
    assert state.ema_params is None
    state, _ = step(state, comm.shard_batch(batch))
    assert state.ema_params is None


def test_ema_is_fp32_regardless_of_param_dtype():
    comm, model, batch, state, step = _setup(0.999)
    for leaf in jax.tree_util.tree_leaves(state.ema_params):
        assert leaf.dtype == jnp.float32
    state, _ = step(state, comm.shard_batch(batch))
    for leaf in jax.tree_util.tree_leaves(state.ema_params):
        assert leaf.dtype == jnp.float32


def test_enabling_ema_on_existing_checkpoint(tmp_path):
    # Snapshot written WITHOUT ema, restored WITH ema enabled: the retry
    # template drops the new leaf and the average seeds from the restored
    # params (the same init a fresh EMA run uses).
    from chainermn_tpu.extensions import create_multi_node_checkpointer

    comm, model, batch, state, step = _setup(None)
    sharded = comm.shard_batch(batch)
    state, _ = step(state, sharded)
    ckpt = create_multi_node_checkpointer("ema_mig", comm,
                                          path=str(tmp_path))
    ckpt.save(state)  # step taken from state.step (== 1 after one update)
    ckpt.finalize()

    comm2, model2, batch2, state2, step2 = _setup(0.9)
    ckpt2 = create_multi_node_checkpointer("ema_mig", comm2,
                                           path=str(tmp_path))
    restored, _ = ckpt2.maybe_load(state2)
    # (the loop iteration is 0 — no trainer was attached; the STATE is the
    # restored step-1 snapshot)
    assert int(restored.step) == 1
    seeded = [np.asarray(e) for e in
              jax.tree_util.tree_leaves(restored.ema_params)]
    for e, p in zip(seeded, jax.tree_util.tree_leaves(restored.params)):
        assert e.dtype == np.float32
        np.testing.assert_allclose(e, np.asarray(p, np.float32))
    # ...and training continues, updating the seeded average (snapshot
    # taken above — the train step donates `restored`).
    restored2, _ = step2(restored, comm2.shard_batch(batch2))
    changed = any(
        not np.allclose(np.asarray(a), b)
        for a, b in zip(jax.tree_util.tree_leaves(restored2.ema_params),
                        seeded)
    )
    assert changed


def test_ema_decay_validated():
    import pytest

    comm = cmn.create_communicator("xla")
    with pytest.raises(ValueError, match="ema_decay"):
        cmn.create_multi_node_optimizer(optax.sgd(0.1), comm, ema_decay=1.5)
