"""Multi-node optimizer tests.

Oracle strategy mirrors the reference
(``tests/chainermn_tests/optimizer_tests``): data-parallel training across the
8-device mesh must match a single-device run on the identical global batch
stream; double buffering must converge with 1-step-stale grads.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.datasets import make_synthetic_classification
from chainermn_tpu.iterators import SerialIterator
from chainermn_tpu.models import MLP, classification_loss


def _setup(devices, **opt_kw):
    comm = cmn.create_communicator("xla", devices=devices, **opt_kw.pop("comm_kw", {}))
    model = MLP(hidden=(32,), n_out=10)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 16), np.float32))["params"]
    loss_fn = classification_loss(model)
    return comm, model, params, loss_fn


def _batches(n, bs, dim=16, seed=0):
    ds = make_synthetic_classification(n=n * bs, dim=dim, seed=seed)
    x, y = ds.arrays
    return [(x[i * bs : (i + 1) * bs], y[i * bs : (i + 1) * bs]) for i in range(n)]


def test_dp_matches_single_device_oracle(devices):
    """8-way DP on the global batch == single-device SGD on the same batch."""
    comm, model, params, loss_fn = _setup(devices)
    tx = optax.sgd(0.1)
    opt = cmn.create_multi_node_optimizer(tx, comm)
    state = opt.init(params)

    batches = _batches(5, 64)

    # Oracle: plain single-device optax on the full global batch.
    oparams = params
    oopt = tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        updates, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, updates)

    for b in batches:
        state, metrics = opt.update(state, b, loss_fn, has_aux=True)

    flat_a = jax.tree_util.tree_leaves(state.params)
    flat_b = jax.tree_util.tree_leaves(oparams)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5)


def test_adafactor_dp_matches_single_device_oracle(devices):
    """The low-memory tier (factored second moments — the optimizer that
    put 1.5B-param training on one 16 GB chip, result/lm_tpu_1558m.json)
    through the multi-node step == plain single-device optax.adafactor on
    the identical global batch stream."""
    comm, model, params, loss_fn = _setup(devices)
    tx = optax.adafactor(1e-3)
    opt = cmn.create_multi_node_optimizer(tx, comm)
    state = opt.init(params)

    batches = _batches(5, 64)

    oparams = params
    oopt = tx.init(params)
    for b in batches:
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(oparams, b)
        updates, oopt = tx.update(grads, oopt, oparams)
        oparams = optax.apply_updates(oparams, updates)

    for b in batches:
        state, metrics = opt.update(state, b, loss_fn, has_aux=True)

    flat_a = jax.tree_util.tree_leaves(state.params)
    flat_b = jax.tree_util.tree_leaves(oparams)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_loss_decreases(devices):
    comm, model, params, loss_fn = _setup(devices)
    opt = cmn.create_multi_node_optimizer(optax.adam(1e-2), comm)
    state = opt.init(params)
    losses = []
    for b in _batches(20, 64):
        state, metrics = opt.update(state, b, loss_fn, has_aux=True)
        losses.append(metrics["loss"])
    assert float(losses[-1]) < float(losses[0]) * 0.7, losses[:3] + losses[-3:]


def test_wire_dtype_close_to_fp32(devices):
    comm32, model, params, loss_fn = _setup(devices)
    comm16 = cmn.create_communicator(
        "xla", devices=devices, allreduce_grad_dtype="bfloat16"
    )
    tx = optax.sgd(0.1)
    o32 = cmn.create_multi_node_optimizer(tx, comm32)
    o16 = cmn.create_multi_node_optimizer(tx, comm16)
    s32, s16 = o32.init(params), o16.init(params)
    for b in _batches(3, 64):
        s32, _ = o32.update(s32, b, loss_fn, has_aux=True)
        s16, _ = o16.update(s16, b, loss_fn, has_aux=True)
    for a, b in zip(
        jax.tree_util.tree_leaves(s32.params), jax.tree_util.tree_leaves(s16.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2)


def test_double_buffering_one_step_stale(devices):
    """First update must be a no-op (zero pending grads), second applies the
    first batch's grads — the reference's _DoubleBufferingOptimizer contract."""
    comm, model, params, loss_fn = _setup(devices)
    tx = optax.sgd(0.1)
    opt = cmn.create_multi_node_optimizer(tx, comm, double_buffering=True)
    state = opt.init(params)
    b0, b1 = _batches(2, 64)

    state, _ = opt.update(state, b0, loss_fn, has_aux=True)
    # after one update params unchanged (applied zeros)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)

    # second update applies b0's grads -> equals one oracle step on b0
    state, _ = opt.update(state, b1, loss_fn, has_aux=True)
    (_, _), g0 = jax.value_and_grad(loss_fn, has_aux=True)(params, b0)
    updates, _ = tx.update(g0, tx.init(params), params)
    oracle = optax.apply_updates(params, updates)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(oracle)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5)


def test_double_buffering_converges(devices):
    comm, model, params, loss_fn = _setup(devices)
    opt = cmn.create_multi_node_optimizer(optax.adam(1e-2), comm, double_buffering=True)
    state = opt.init(params)
    losses = []
    for b in _batches(25, 64):
        state, metrics = opt.update(state, b, loss_fn, has_aux=True)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[1] * 0.8


def test_dummy_communicator_skips_allreduce(devices):
    comm, model, params, loss_fn = _setup(devices)
    dummy = cmn.create_communicator("dummy", devices=devices)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), dummy)
    state = opt.init(params)
    state, metrics = opt.update(state, _batches(1, 64)[0], loss_fn, has_aux=True)
    assert np.isfinite(float(metrics["loss"]))
