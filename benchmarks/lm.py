"""Transformer-LM training throughput: tokens/sec/chip + flash-vs-XLA ablation.

The second headline workload (the reference's seq2seq/lm family at modern
scale): full DP training step of the decoder-only :class:`TransformerLM` —
bf16 compute, flash attention — measured in tokens/sec/chip with an MFU
estimate from XLA's compiled flop count, plus the same model with
materialized-scores XLA attention to quantify the Pallas kernel's
end-to-end contribution.

    python benchmarks/lm.py --out result/lm_tpu.json        # real chip
    JAX_PLATFORMS=cpu python benchmarks/lm.py --smoke ...   # plumbing check
"""

from __future__ import annotations

import argparse
import json
import time


def artifact_disposition(measured, oom_recorded, retryable, accept_oom):
    """Should this run's --out artifact land?  (The watcher-wedge contract,
    unit-tested in tests/examples_tests/test_benchmarks_smoke.py.)

    * any arm measured, no transient → land (the honest partial record);
    * all arms OOM'd deterministically → land ONLY under --accept-oom
      (fit-probe stanzas, where the OOM is the answer — withholding would
      wedge the watcher's file-existence gate into re-running a doomed
      bench every window);
    * any transient (non-OOM) failure → withhold, so the watcher retries
      and a mis-wrapped transient never freezes in as a permanent
      error-only artifact.
    """
    return bool(measured or (oom_recorded and accept_oom)) and not retryable


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation microbatches per step")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize decoder blocks (jax.checkpoint)")
    ap.add_argument("--ce-chunk", type=int, default=0,
                    help="stream the LM head in vocab chunks of this size "
                         "(chunked_softmax_cross_entropy) instead of "
                         "materializing (B,T,vocab) logits")
    ap.add_argument("--pos-enc", default="learned",
                    choices=("learned", "rope"),
                    help="positional scheme (rope = rotary q/k, no table)")
    ap.add_argument("--arms", default="flash,xla",
                    help="comma-joined subset of flash,xla to measure — "
                         "e.g. --arms flash for geometries where the "
                         "materialized-scores arm is a known OOM "
                         "(longcontext_tpu.json: XLA cannot run T>=8192; "
                         "the T=4096 1.5B tier is borderline) so a doomed "
                         "arm never costs the measured one its artifact")
    ap.add_argument("--optimizer", default="adamw",
                    choices=("adamw", "adafactor"),
                    help="adafactor = factored second moments, no fp32 "
                         "momentum tensors — the low-memory tier that fits "
                         "GPT-2-XL-scale (1.5B) training on one 16 GB chip "
                         "where adamw's moments alone need ~12 GB")
    ap.add_argument("--param-dtype", default="float32",
                    choices=("float32", "bfloat16"),
                    help="parameter STORAGE dtype. bfloat16 halves the "
                         "persistent params+grads bytes (adafactor stats "
                         "follow) — the storage lever for >2B configs, "
                         "where fp32 params OOM on the 15.75 GB chip")
    ap.add_argument("--lora", type=int, default=0, metavar="RANK",
                    help="LoRA fine-tuning step instead of full training: "
                         "frozen base params (in --param-dtype storage), "
                         "rank-RANK adapters on the attention projections, "
                         "optimizer state on the adapters only. Measures "
                         "the fine-tuning step time/MFU and records the "
                         "trainable-param fraction — the fits-where-full-"
                         "training-can't tier for >6B on one chip")
    ap.add_argument("--accept-oom", action="store_true",
                    help="an all-arms-OOM run still writes --out (the OOM "
                         "is the answer for a does-this-geometry-fit "
                         "stanza). Off by default so a mis-wrapped "
                         "transient at a known-good geometry can never "
                         "land a permanent error-only artifact")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config for CPU plumbing checks")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import TransformerLM, lm_loss, lm_loss_chunked

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if platform != "tpu" and not args.smoke:
        # Same policy as flash_tpu.py / bench.py: never let a CPU-fallback
        # number land in the TPU artifact slot (--out is skipped too).
        print(json.dumps({
            "error": f"lm bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        # batch 8 divides any of the test meshes (1 device or the forced
        # 8-device CPU pool).
        args.batch, args.seq, args.layers = 8, 256, 2
        args.d_model, args.heads, args.d_ff, args.vocab = 128, 4, 256, 1024
        args.iters = 2
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "batch": args.batch, "seq": args.seq, "layers": args.layers,
            "d_model": args.d_model, "heads": args.heads, "d_ff": args.d_ff,
            "vocab": args.vocab, "accum": args.accum, "remat": args.remat,
            "ce_chunk": args.ce_chunk, "optimizer": args.optimizer,
            "param_dtype": args.param_dtype,
            # Recorded so a deliberately single-arm artifact (--arms
            # flash at a known-XLA-OOM geometry) is distinguishable from
            # a full run whose other arm was lost.
            "arms": args.arms,
        },
    }

    comm = cmn.create_communicator("xla", allreduce_grad_dtype=jnp.bfloat16)
    tokens_per_step = args.batch * args.seq

    rng = np.random.RandomState(0)
    toks = rng.randint(0, args.vocab, size=(args.batch, args.seq)).astype(np.int32)
    batch = comm.shard_batch((toks, toks))

    def run_arm(impl):
        model = TransformerLM(
            vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
            n_heads=args.heads, d_ff=args.d_ff, max_len=args.seq,
            attention=impl, remat=args.remat, pos_enc=args.pos_enc,
            param_dtype=getattr(jnp, args.param_dtype),
        )
        base_opt = (
            optax.adafactor(3e-4)
            if args.optimizer == "adafactor"
            else optax.adamw(3e-4)
        )
        opt = cmn.create_multi_node_optimizer(base_opt, comm)
        # Jit both inits: an eager flax/optax init is hundreds of op-by-op
        # dispatches, each a round trip over the axon tunnel (observed to
        # stall real-chip runs for 10+ minutes before any compute).
        params = jax.jit(
            lambda r: model.init(r, jnp.zeros((1, args.seq), jnp.int32))
        )(jax.random.PRNGKey(0))["params"]
        base_params = None
        inner_loss = (
            lm_loss_chunked(model, chunk_size=args.ce_chunk)
            if args.ce_chunk
            else lm_loss(model)
        )
        if args.lora:
            # Fine-tuning tier: the optimizer's tree is the ADAPTER tree;
            # the frozen base stays alive as a closure constant of the
            # loss (so no donation / no drop — it must survive every
            # step).  Persistent memory: base params + rank-sized
            # adapters + adapter-sized opt state.
            from chainermn_tpu.models import (
                lora_init,
                lora_param_count,
                make_lora_loss,
            )

            base_params = params
            lora = jax.block_until_ready(jax.jit(
                lambda r: lora_init(r, base_params, rank=args.lora)
            )(jax.random.PRNGKey(1)))
            out["lora"] = {
                "rank": args.lora,
                "trainable_params": lora_param_count(lora),
                "total_params": sum(
                    int(x.size)
                    for x in jax.tree_util.tree_leaves(base_params)
                ),
            }
            # Same multi-host rule as the full-training path below:
            # opt.init goes through make_array_from_callback there, which
            # cannot run under a trace.
            state = (
                opt.init(lora)
                if jax.process_count() > 1
                else jax.block_until_ready(jax.jit(opt.init)(lora))
            )
            params = None
            loss_fn = make_lora_loss(inner_loss, base_params)
        elif jax.process_count() > 1:
            # Multi-host placement goes through make_array_from_callback,
            # which cannot run under a trace.
            state = opt.init(params)
        else:
            # DONATE the params into the jitted init: without donation the
            # init peak holds params TWICE (argument + the state's own copy
            # of them) plus the optimizer stats — params (fp32) + params +
            # stats ≈ 19.3 GB at 2.08B, an OOM before the first step even
            # though the steady-state step fits (the r5 fp32-2.08B attempt,
            # result/lm_2085m_stdout.log).  With donation XLA aliases the
            # argument buffers into the state and the peak is one params
            # copy + stats.  The params binding is dead afterwards either
            # way (donated; the state carries its own buffers) — dropping
            # it is the r4 dead-copy fix.  Not done on the multi-host path,
            # where opt.init may alias the caller's arrays into the state.
            state = jax.block_until_ready(
                jax.jit(opt.init, donate_argnums=0)(params)
            )
            params = None
        if not args.lora:
            loss_fn = inner_loss
        step = opt.make_train_step(loss_fn, has_aux=True,
                                   accum_steps=args.accum)

        # One shared flops/MFU implementation (utils.compiled_flops / mfu):
        # a local copy once drifted (stale `from bench import` silently
        # dropped mfu_pct from the artifact) — never again.
        from chainermn_tpu.utils import compiled_flops, mfu

        compiled = None
        try:
            compiled = step.lower(state, batch).compile()
            step = compiled
        except Exception as e:
            # A ResourceExhausted compile is a real property of the geometry
            # (note it, fall through to the per-call jit); anything else is
            # transient — re-raise so the outer handler withholds the
            # artifact and the watcher retries.
            if not any(s in str(e) for s in (
                    "RESOURCE_EXHAUSTED", "Ran out of memory")):
                raise
            out[f"{impl}_compile_note"] = f"{type(e).__name__}: {str(e)[:150]}"
        flops = compiled_flops(compiled) if compiled is not None else None

        for _ in range(2):  # warmup
            state, metrics = step(state, batch)
            _ = float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(args.iters):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])  # sequential dependency bounds the chain
        dt = time.perf_counter() - t0

        step_ms = dt / args.iters * 1000.0
        tps = tokens_per_step * args.iters / dt / n_dev
        rec = {"step_ms": round(step_ms, 2),
               "tokens_per_sec_per_chip": round(tps, 1)}
        if flops:
            rec["tflops_per_step"] = round(flops / 1e12, 3)
            m = mfu(compiled, dt / args.iters, n_dev, out["device_kind"])
            if m is not None:
                rec["mfu_pct"] = round(m, 2)
            if impl == "flash" and m is not None:
                # XLA's cost analysis cannot see inside Pallas custom
                # calls, so the flash arm's attention-core FLOPs are
                # missing from mfu_pct (a lower bound).  Add the analytic
                # core count (utils.attention_core_flops) and emit the
                # inclusive number alongside, clearly labeled.
                from chainermn_tpu.utils import (
                    attention_core_flops,
                    flash_mfu_fields,
                )

                extra = args.layers * attention_core_flops(
                    args.batch, args.heads, args.seq,
                    args.d_model // args.heads, causal=True,
                    n_forward=2 if args.remat else 1,
                )
                rec.update(flash_mfu_fields(
                    flops, extra, dt / args.iters, n_dev,
                    out["device_kind"],
                ))
        # Free this arm's HBM before the next arm compiles: at 774M the
        # fp32 params + adamw moments are ~9 GB — two arms alive at once
        # exceeded the 15.75 GB chip (RESOURCE_EXHAUSTED at the second
        # opt.init, 2026-08-01), killing the run after the flash number
        # had already been measured.
        held = jax.tree.leaves((params, state, base_params))
        del params, state, step, compiled, base_params
        for a in held:
            try:
                a.delete()
            except Exception:
                pass
        jax.clear_caches()
        return rec

    arms = tuple(a for a in args.arms.split(",") if a)
    if not arms or any(a not in ("flash", "xla") for a in arms):
        raise SystemExit(f"--arms {args.arms!r}: subset of flash,xla")
    retryable = False
    for impl in arms:
        try:
            out[impl] = run_arm(impl)
        except Exception as e:
            # An OOM'd ablation arm must not cost the measured arm(s): the
            # artifact lands with what succeeded plus an honest error record.
            # ONLY ResourceExhausted is a recordable outcome (a real property
            # of the geometry on this chip) — anything else (tunnel drop,
            # coordination error) is transient and must not be baked into an
            # artifact the watcher's file-existence gate would then treat as
            # done forever.
            out[impl] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            if not any(s in str(e) for s in (
                    "RESOURCE_EXHAUSTED", "Ran out of memory")):
                # "Ran out of memory": the tunnel's remote-compile helper
                # wraps compile OOMs in a generic INTERNAL error whose text
                # (when detailed) says this instead of RESOURCE_EXHAUSTED.
                retryable = True
            jax.clear_caches()
        print(json.dumps({impl: out[impl]}), flush=True)
        if retryable:
            # The run is already doomed to be withheld — don't burn minutes
            # of a scarce tunnel window compiling the remaining arm(s).
            break

    if "step_ms" in out.get("flash", {}) and "step_ms" in out.get("xla", {}):
        out["flash_speedup"] = round(
            out["xla"]["step_ms"] / out["flash"]["step_ms"], 3
        )
    print(json.dumps({k: v for k, v in out.items() if k != "config"}))
    measured = [k for k in ("flash", "xla") if "step_ms" in out.get(k, {})]
    oom_recorded = [
        k for k in ("flash", "xla") if "error" in out.get(k, {})
    ]
    # Only ResourceExhausted reaches oom_recorded without setting
    # `retryable`; see artifact_disposition for the landing contract.
    complete = artifact_disposition(
        measured, oom_recorded, retryable, args.accept_oom
    )
    if args.out:
        if complete:
            from chainermn_tpu.utils import atomic_json_dump

            atomic_json_dump(out, args.out)
        else:
            # Withheld: an arm died to a transient (non-OOM) error — leave
            # --out unwritten so the watcher's file-existence gate retries
            # on the next tunnel window instead of permanently accepting a
            # degraded artifact.
            print(json.dumps({"error": "incomplete run; artifact withheld"}))
    if not complete:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
