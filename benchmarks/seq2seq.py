"""Seq2seq (NMT family) training throughput: target tokens/sec/chip.

The third reference workload family (``examples/seq2seq`` — SURVEY §2.9)
measured at modern scale: full DP training step of the flash-kernel
:class:`TransformerSeq2Seq` on bucketed/padded variable-length batches
(the reference's ragged-batch story under XLA's static shapes), reported
in NON-PAD target tokens/sec/chip with the padding overhead stated, plus
the same model on materialized-scores XLA attention.

    python benchmarks/seq2seq.py --out result/seq2seq_tpu.json   # real chip
    JAX_PLATFORMS=cpu python benchmarks/seq2seq.py --smoke       # plumbing
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--src-len", type=int, default=512)
    ap.add_argument("--tgt-len", type=int, default=512)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--d-ff", type=int, default=2048)
    ap.add_argument("--enc", type=int, default=6)
    ap.add_argument("--dec", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--nonpad", type=float, default=0.87,
                    help="simulated non-pad fraction (the bucketing tier's "
                         "measured 0.87 at bucket_width=4)")
    ap.add_argument("--enc-attention", default=None,
                    choices=("flash", "xla", "auto"),
                    help="encoder-only attention override applied to BOTH "
                         "ablation arms (e.g. --enc-attention flash makes "
                         "the 'xla' arm the encoder-flash hybrid) — probes "
                         "the segment-masked non-causal encoder category "
                         "separately from the decoder's causal/cross rows")
    ap.add_argument("--packed", action="store_true",
                    help="train PACKED rows (datasets.pack_pairs: several "
                         "pairs per row, per-pair segment isolation) "
                         "instead of the bucketed/padded tier — non-pad "
                         "fraction rises from the bucketing 0.87 to the "
                         "measured packing efficiency (~0.95+)")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import TransformerSeq2Seq, seq2seq_loss
    from chainermn_tpu.models.seq2seq import PAD

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"seq2seq bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        args.batch, args.src_len, args.tgt_len = 8, 64, 64
        args.d_model, args.heads, args.d_ff = 64, 4, 128
        args.enc, args.dec, args.vocab, args.iters = 1, 1, 512, 2
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "config": {k: getattr(args, k.replace("-", "_")) for k in
                   ("batch", "src_len", "tgt_len", "d_model", "heads",
                    "d_ff", "enc", "dec", "vocab")},
        "enc_attention_override": args.enc_attention,
        "nonpad_fraction": None if args.packed else args.nonpad,
        "packed": args.packed,
    }

    comm = cmn.create_communicator("xla", allreduce_grad_dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    if args.packed:
        # Packed rows: draw sentence pairs from a plausible NMT length
        # distribution and best-fit pack them (datasets.pack_pairs) until
        # `batch` rows exist.  Throughput is reported on non-pad target
        # tokens, so the packing efficiency directly becomes tokens/sec.
        from chainermn_tpu.datasets import pack_pairs, packing_efficiency

        def draw(mean, cap):
            L = int(np.clip(rng.normal(mean, 0.25 * mean), 4, cap))
            return rng.randint(3, args.vocab, size=L).astype(np.int32)

        pairs = []
        while True:
            pairs.extend(
                (draw(0.4 * args.src_len, args.src_len),
                 draw(0.4 * args.tgt_len, args.tgt_len))
                for _ in range(args.batch * 2)
            )
            src, tgt, sseg, tseg = pack_pairs(
                pairs, args.src_len, args.tgt_len
            )
            if src.shape[0] >= args.batch:
                break
        src, tgt = src[:args.batch], tgt[:args.batch]
        sseg, tseg = sseg[:args.batch], tseg[:args.batch]
        out["packing_efficiency"] = round(packing_efficiency(tseg), 4)
        batch = comm.shard_batch((src, tgt, sseg, tseg))
        real_tgt_tokens = int((tseg != 0).sum())
    else:
        # Bucketed/padded batch shape with the measured non-pad fraction:
        # the tail of each row is PAD (id 0), what bucket_batches emits.
        def make(lenq):
            toks = rng.randint(3, args.vocab,
                               size=(args.batch, lenq)).astype(np.int32)
            n_real = max(1, int(round(lenq * args.nonpad)))
            toks[:, n_real:] = PAD
            return toks
        batch = comm.shard_batch((make(args.src_len), make(args.tgt_len)))
        real_tgt_tokens = int(
            (np.asarray(jax.device_get(batch[1])) != PAD).sum()
        )

    def _transient(e):
        # Same classifier as bench.py._is_transient (not imported: bench's
        # module level probes the device).  Transient tunnel errors must
        # ABORT the run with no artifact so the watcher's missing-file gate
        # retries on the next window — recording one would freeze a
        # recoverable outage in as a permanent "measurement".
        return any(t in str(e) for t in ("UNAVAILABLE", "DEADLINE_EXCEEDED"))

    for impl in ("flash", "xla"):
        if args.enc_attention == impl:
            # The override makes this arm identical to the uniform
            # configuration already captured elsewhere — don't spend half
            # a scarce tunnel window re-measuring known data.
            continue
        # Resolved arm name, shared by success AND failure records — a bare
        # 'xla_error' under --enc-attention flash would misattribute the
        # hybrid arm's failure to the pure-XLA configuration.
        key = (
            f"enc_{args.enc_attention}_dec_{impl}"
            if args.enc_attention and args.enc_attention != impl
            else impl
        )
        model = TransformerSeq2Seq(
            vocab_src=args.vocab, vocab_tgt=args.vocab,
            d_model=args.d_model, n_heads=args.heads, d_ff=args.d_ff,
            n_enc=args.enc, n_dec=args.dec,
            max_len=max(args.src_len, args.tgt_len),
            dtype=jnp.bfloat16, attention=impl,
            enc_attention=args.enc_attention,
        )
        opt = cmn.create_multi_node_optimizer(optax.adamw(3e-4), comm)
        params = jax.jit(
            lambda r: model.init(
                r,
                jnp.zeros((1, args.src_len), jnp.int32),
                jnp.zeros((1, args.tgt_len), jnp.int32),
            )
        )(jax.random.PRNGKey(0))["params"]
        if jax.process_count() > 1:
            # Multi-host placement goes through make_array_from_callback,
            # which cannot run under a trace (same guard as lm.py).
            state = opt.init(params)
        else:
            state = jax.block_until_ready(jax.jit(opt.init)(params))
        step = opt.make_train_step(seq2seq_loss(model), has_aux=True)

        # Shared flops/MFU implementation (see lm.py's note on drift).
        from chainermn_tpu.utils import compiled_flops, mfu

        compiled = None
        try:
            compiled = step.lower(state, batch).compile()
            step = compiled
        except Exception as e:
            if _transient(e):
                raise
            out[f"{key}_compile_note"] = f"{type(e).__name__}: {str(e)[:150]}"
        flops = compiled_flops(compiled) if compiled is not None else None
        if compiled is None and any(
            s in out.get(f"{key}_compile_note", "")
            for s in ("Ran out of memory", "RESOURCE_EXHAUSTED")
        ):
            # Permanent compile OOM: the eager-jit fallback would recompile
            # for minutes over the tunnel and fail identically — the note
            # IS this arm's result.
            out[f"{key}_error"] = out[f"{key}_compile_note"]
            continue

        # A deterministic arm failure (e.g. the materialized-scores XLA arm
        # OOMs at T=2048 — 26.2G for B=16·H=8·T² decoder score tensors)
        # must not take the OTHER arm's finished measurement down with it:
        # record the failure as this arm's result and keep going.  Same
        # story the longcontext sweep tells — flash proceeding where XLA
        # cannot run at all IS the measurement.
        try:
            for _ in range(2):
                state, metrics = step(state, batch)
                _ = float(metrics["loss"])  # device->host sync (tunnel-safe)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                state, metrics = step(state, batch)
                _ = float(metrics["loss"])
            dt = time.perf_counter() - t0
        except Exception as e:
            if _transient(e):
                raise
            out[f"{key}_error"] = f"{type(e).__name__}: {str(e)[:300]}"
            print(json.dumps({f"{key}_error": out[f"{key}_error"]}),
                  flush=True)
            continue

        rec = {
            "step_ms": round(dt / args.iters * 1000.0, 2),
            "nonpad_tgt_tokens_per_sec_per_chip": round(
                real_tgt_tokens * args.iters / dt / n_dev, 1
            ),
        }
        if flops:
            rec["tflops_per_step"] = round(flops / 1e12, 3)
            m = mfu(compiled, dt / args.iters, n_dev, out["device_kind"])
            if m is not None:
                rec["mfu_pct"] = round(m, 2)
                # Pallas flash kernels are invisible to XLA's FLOP
                # counter (mfu_pct is a lower bound for flash arms): add
                # the analytic attention-core count per component that
                # RESOLVES to flash.  'auto' components resolve the same
                # way the model does (segment-masked/causal rows use the
                # causal crossover — the packed tiers always carry
                # segment ids).
                from chainermn_tpu.ops import resolve_attention
                from chainermn_tpu.utils import (
                    attention_core_flops,
                    flash_mfu_fields,
                )

                dh = args.d_model // args.heads
                enc_impl = resolve_attention(
                    args.enc_attention or impl, args.src_len
                )
                dec_impl = resolve_attention(impl, args.tgt_len)
                cross_impl = resolve_attention(
                    impl, args.tgt_len, args.src_len
                )
                extra = 0.0
                if enc_impl == "flash":
                    extra += args.enc * attention_core_flops(
                        args.batch, args.heads, args.src_len, dh,
                        causal=False
                    )
                if dec_impl == "flash":
                    extra += args.dec * attention_core_flops(
                        args.batch, args.heads, args.tgt_len, dh,
                        causal=True
                    )
                if cross_impl == "flash":
                    extra += args.dec * attention_core_flops(
                        args.batch, args.heads, args.tgt_len, dh,
                        kv_len=args.src_len, causal=False
                    )
                rec.update(flash_mfu_fields(
                    flops, extra, dt / args.iters, n_dev,
                    out["device_kind"],
                ))
        out[key] = rec
        print(json.dumps({key: rec}), flush=True)

    if "flash" in out and "xla" in out:
        out["flash_speedup"] = round(
            out["xla"]["step_ms"] / out["flash"]["step_ms"], 3
        )
    print(json.dumps(out))
    if args.out and platform == "tpu":
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)


if __name__ == "__main__":
    main()
