"""Double-buffering overlap evidence (VERDICT r1 item 9).

The reference's ``_DoubleBufferingOptimizer`` existed to overlap the gradient
allreduce with the next step's compute (SURVEY.md §2.6/§3.3, side CUDA
stream).  Our port reproduces the 1-step-stale *semantics* in the jitted step
(contract-tested); this harness quantifies the *overlap*: with
``double_buffering=True`` the applied update uses the PREVIOUS step's reduced
grads, so this step's allreduce result is not needed until the next step and
the scheduler is free to run it concurrently with the optimizer update and —
under async dispatch — the next step's forward.

Method: a deliberately comm-bound config (wide MLP → large gradient pytree,
small per-chip batch → little compute) on whatever mesh is present; measure
steady-state step time for sync vs double-buffered variants.  Optionally
writes a ``jax.profiler`` trace for timeline inspection.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/overlap.py --out result/overlap_cpu.json
"""

from __future__ import annotations

import argparse
import json


def measure(dim: int = 2048, batch_per_chip: int = 8, iters: int = 20,
            trace_dir: str | None = None):
    import numpy as np

    import jax
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import MLP, classification_loss
    from chainermn_tpu.utils import sync

    comm = cmn.create_communicator("xla")
    n = comm.size
    B = batch_per_chip * n
    rng = np.random.RandomState(0)
    x = rng.normal(size=(B, dim)).astype(np.float32)
    y = rng.randint(0, 10, size=(B,)).astype(np.int32)

    import time

    out = {"devices": n, "dim": dim, "global_batch": B, "iters": iters,
           "platform": jax.devices()[0].platform}
    for dbuf in (False, True):
        model = MLP([dim, dim], 10)
        params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1, momentum=0.9), comm, double_buffering=dbuf
        )
        state = opt.init(params)
        step = opt.make_train_step(classification_loss(model), has_aux=True)
        batch = comm.shard_batch((x, y))
        # Warmup/compile, then time the chain with ONE final materialization
        # (sequential state dependency bounds all steps).
        for _ in range(3):
            state, m = step(state, batch)
        sync(m)
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, batch)
        sync(m)
        dt = time.perf_counter() - t0
        key = "dbuf" if dbuf else "sync"
        out[f"{key}_step_ms"] = round(dt / iters * 1000, 3)
        if trace_dir and dbuf:
            import os

            os.makedirs(trace_dir, exist_ok=True)
            with jax.profiler.trace(trace_dir):
                for _ in range(3):
                    state, m = step(state, batch)
                sync(m)
    out["overlap_gain_pct"] = round(
        100.0 * (1.0 - out["dbuf_step_ms"] / out["sync_step_ms"]), 1
    )
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=2048)
    ap.add_argument("--batch-per-chip", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    # NB: async dispatch stays ON — overlap across steps is the thing being
    # measured.  Single repeated program; the conftest deadlock concerns
    # multiple interleaved compiled programs.

    res = measure(args.dim, args.batch_per_chip, args.iters, args.trace_dir)
    print(json.dumps(res), flush=True)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(res, args.out)


if __name__ == "__main__":
    main()
