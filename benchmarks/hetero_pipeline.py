"""HeteroPipelineChain vs compute-replicated MultiNodeChainList.

VERDICT r2 item 4: heterogeneous chains (different layer types/widths per
rank — the reference's VGG/parallel-convnet model-parallel examples) had no
distributed-speedup path: under GSPMD, ``MultiNodeChainList`` replicates
every stage's compute on every device.  :class:`HeteroPipelineChain` fixes
that with a per-device ``lax.switch`` over a flat activation buffer — device
``s`` computes ONLY stage ``s`` — plus GPipe microbatching.

This harness measures both on an identical heterogeneous tanh-MLP chain
(per-stage widths differ, so no homogeneous stacking is possible) and on a
stage-partitioned VGG-11, fwd+bwd per step.  On the shared-core CPU mesh
total work is what shows up in wall-clock: replicated does S stage
computations per device (S× the work), the hetero pipeline does
(S+M-1) microbatch stage computations ≈ S/M of one device's work.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/hetero_pipeline.py
"""

from __future__ import annotations

import argparse
import json


def measure(B: int = 128, M: int = 4, iters: int = 3, width_base: int = 256):
    """Heterogeneous MLP chain: stage widths cycle through
    ``width_base * {1, 1.5, 0.75, 1.25}`` so no two adjacent stages match."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as cmn
    from chainermn_tpu.links import HeteroPipelineChain, MultiNodeChainList
    from chainermn_tpu.utils import benchmark

    comm = cmn.create_communicator("xla")
    S = comm.size
    mults = [1.0, 1.5, 0.75, 1.25]
    dims = [width_base] + [
        int(width_base * mults[s % len(mults)]) for s in range(S)
    ]
    rng = np.random.RandomState(0)
    params = [
        (rng.normal(size=(dims[s], dims[s + 1])) * (0.5 / np.sqrt(dims[s])))
        .astype(np.float32)
        for s in range(S)
    ]
    x = rng.normal(size=(B, dims[0])).astype(np.float32)
    stage = lambda p, h: jnp.tanh(h @ p)

    # --- compute-replicated chain (API-parity tier) ----------------------
    chain = MultiNodeChainList(comm)
    for s in range(S):
        chain.add_link(
            stage,
            rank=s,
            rank_in=s - 1 if s > 0 else None,
            rank_out=s + 1 if s < S - 1 else None,
        )

    def chain_loss(params_list, xx):
        def body(*args):
            *ps, b = args
            y = chain(list(ps), b)
            y = cmn.functions.bcast(comm, y, root=S - 1)
            return jnp.sum(y**2)

        return comm.spmd(
            body,
            in_specs=tuple([P()] * S) + (P(),),
            out_specs=P(),
            check_vma=False,
        )(*params_list, xx)

    chain_step = jax.jit(jax.grad(chain_loss))
    rep = benchmark(lambda: chain_step(params, x), warmup=2,
                    iters=iters)["mean_s"]

    # --- hetero pipeline tier --------------------------------------------
    io = [((dims[s],), (dims[s + 1],)) for s in range(S)]
    pipe = HeteroPipelineChain(comm, [stage] * S, io, n_microbatches=M)

    def pipe_loss(params_list, xx):
        f = comm.spmd(
            lambda pl, b: jnp.sum(pipe(pl, b) ** 2),
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return f(params_list, xx)

    pipe_step = jax.jit(jax.grad(pipe_loss))
    pip = benchmark(lambda: pipe_step(params, x), warmup=2,
                    iters=iters)["mean_s"]

    # --- stage-SHARDED params tier (round 4: the 1/S-memory path) --------
    # Same pipeline, but the ravel/pad/stack happens once outside the step
    # and each device holds only its own row — the per-step stack and its
    # gradient disappear from the program.
    stacked = pipe.shard_params(params)  # bare-array leaves shard fine
    sspmd = comm.spmd(
        lambda st, b: jnp.sum(pipe.apply_sharded(st, b) ** 2),
        in_specs=(P(comm.axes), P()),
        out_specs=P(),
        check_vma=False,
    )
    shard_step = jax.jit(jax.grad(sspmd))
    shd = benchmark(lambda: shard_step(stacked, x), warmup=2,
                    iters=iters)["mean_s"]

    return {
        "devices": S,
        "stages": S,
        "widths": dims,
        "B": B,
        "M": M,
        "replicated_s": round(rep, 4),
        "pipeline_s": round(pip, 4),
        "speedup": round(rep / pip, 3),
        "sharded_params_s": round(shd, 4),
        "sharded_vs_replicated_params_speedup": round(pip / shd, 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--out", default=None)
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    import os

    if args.force_cpu or os.environ.get("JAX_PLATFORMS") == "cpu":
        # The axon sitecustomize preselects the TPU platform via
        # jax.config — the env var alone does not switch (and a wedged
        # tunnel then hangs backend init).  See .claude/skills/verify.
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        from jax.extend import backend as _backend

        _backend.clear_backends()
    res = measure(B=args.batch, M=args.micro, iters=args.iters,
                  width_base=args.width)
    line = json.dumps(res)
    print(line)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(res, args.out)


if __name__ == "__main__":
    main()
