"""On-chip Pallas flash attention: Mosaic-compile smoke + block-size sweep.

VERDICT r1 item 3: the flagship kernel (`chainermn_tpu/ops/flash_attention.py`)
was verified for numerics in interpret mode but never compiled by Mosaic on
real hardware.  This harness, run on the TPU:

  1. compiles the kernel fwd+bwd NON-interpret and checks numerics against
     the XLA attention oracle (the compile itself is half the test),
  2. sweeps (block_q, block_k) at a realistic shape and times fwd / fwd+bwd,
  3. times XLA's own attention (jitted softmax(QKᵀ)V) as the baseline.

    python benchmarks/flash_tpu.py --out result/flash_tpu.json

Refuses to run on CPU unless ``--interpret-smoke`` (plumbing check only —
interpret-mode timings are meaningless).
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--causal", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--blocks", default="128x128,256x256,128x512,512x128,256x512")
    ap.add_argument("--interpret-smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.ops import flash_attention, reference_attention
    from chainermn_tpu.utils import sync

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.interpret_smoke:
        print(json.dumps({
            "error": f"flash sweep needs a TPU (got {platform}); "
                     "pass --interpret-smoke for a plumbing check"
        }))
        return
    interpret = platform != "tpu"

    B, T, H, D = args.batch, args.seq, args.heads, args.head_dim
    if interpret:  # keep the smoke tiny
        B, T, H, D = 1, 256, 2, 64
    dtype = jnp.dtype(args.dtype)

    # Synthesize ON device: host->device transfers of tens of MB have been
    # observed to kill runs over the axon tunnel (UNAVAILABLE mid-put).
    @jax.jit
    def _mk_qkv(key):
        ks = jax.random.split(key, 3)
        return tuple(
            jax.random.normal(kk, (B, T, H, D), jnp.float32).astype(dtype)
            for kk in ks
        )

    q, k, v = jax.block_until_ready(_mk_qkv(jax.random.PRNGKey(0)))

    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "shape": {"B": B, "T": T, "H": H, "D": D},
        "dtype": str(dtype),
        "causal": bool(args.causal),
        "compiled_non_interpret": not interpret,
        "configs": [],
    }

    # ---- numerics vs XLA oracle (fwd and grads), compiled ----------------
    def flash_loss(q, k, v, bq, bk):
        return jnp.sum(
            flash_attention(q, k, v, causal=args.causal, block_q=bq,
                            block_k=bk, interpret=interpret).astype(jnp.float32)
            ** 2
        )

    def xla_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, args.causal).astype(jnp.float32) ** 2)

    gf = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)), static_argnums=(3, 4))
    gx = jax.jit(jax.grad(xla_loss, argnums=(0, 1, 2)))
    o_f = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=args.causal, block_q=128, block_k=128,
            interpret=interpret,
        )
    )(q, k, v)
    o_x = jax.jit(lambda q, k, v: reference_attention(q, k, v, args.causal))(q, k, v)
    fwd_err = float(
        jnp.max(jnp.abs(o_f.astype(jnp.float32) - o_x.astype(jnp.float32)))
    )
    g_f = gf(q, k, v, 128, 128)
    g_x = gx(q, k, v)
    bwd_err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(g_f, g_x)
    )
    out["fwd_max_abs_err_vs_xla"] = fwd_err
    out["bwd_max_abs_err_vs_xla"] = bwd_err
    # Gate on BOTH directions — a Mosaic-compiled backward with wrong
    # dq/dk/dv is exactly the failure this harness exists to catch.  The
    # grads of sum(o²) scale with values ~O(1)·T-ish accumulations, so the
    # bwd tolerance is relative to the oracle grad magnitude.
    g_scale = max(
        float(jnp.max(jnp.abs(g.astype(jnp.float32)))) for g in g_x
    )
    fwd_tol = 0.05 if dtype == jnp.bfloat16 else 2e-3
    bwd_tol = (0.05 if dtype == jnp.bfloat16 else 2e-3) * max(g_scale, 1.0)
    out["numerics_ok"] = bool(fwd_err < fwd_tol and bwd_err < bwd_tol)

    def bench(fn, *a):
        fn(*a)  # compile
        sync(fn(*a))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            r = fn(*a)
        sync(r)
        return (time.perf_counter() - t0) / args.iters * 1000.0

    # ---- XLA baseline ----------------------------------------------------
    xla_fwd_ms = bench(jax.jit(lambda q, k, v: reference_attention(q, k, v, args.causal)), q, k, v)
    xla_bwd_ms = bench(gx, q, k, v)
    out["xla_fwd_ms"] = round(xla_fwd_ms, 3)
    out["xla_fwdbwd_ms"] = round(xla_bwd_ms, 3)

    # ---- block sweep -----------------------------------------------------
    for spec in args.blocks.split(","):
        bq, bk = (int(x) for x in spec.split("x"))
        if T % bq or T % bk:
            continue
        try:
            f = jax.jit(
                lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=args.causal, block_q=bq, block_k=bk,
                    interpret=interpret,
                )
            )
            fwd_ms = bench(f, q, k, v)
            bwd_ms = bench(
                jax.jit(
                    jax.grad(
                        lambda q, k, v, bq=bq, bk=bk: flash_loss(q, k, v, bq, bk),
                        argnums=(0, 1, 2),
                    )
                ),
                q, k, v,
            )
            out["configs"].append({
                "block_q": bq, "block_k": bk,
                "fwd_ms": round(fwd_ms, 3),
                "fwdbwd_ms": round(bwd_ms, 3),
                "fwd_vs_xla": round(xla_fwd_ms / fwd_ms, 2),
            })
        except Exception as e:  # Mosaic rejection IS a result worth recording
            out["configs"].append({
                "block_q": bq, "block_k": bk,
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            })
        print(json.dumps(out["configs"][-1]), flush=True)

    print(json.dumps({k: v for k, v in out.items() if k != "configs"}),
          flush=True)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)


if __name__ == "__main__":
    main()
