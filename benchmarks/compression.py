"""Gradient-wire ablation: fp32 vs bf16 wire dtype vs int8+error-feedback.

A deliberately comm-bound config (wide MLP → big gradient pytree, tiny
per-chip batch → little compute) so the gradient collective dominates the
step; the int8_ef tier moves 4× fewer bytes than fp32 (2× fewer than bf16)
at the cost of the quantize/dequantize elementwise work.  NOTE the expected
CPU-mesh outcome (committed in ``result/compression_cpu.json``): int8_ef is
SLOWER there (~0.45× of fp32) — the in-process "collective" is a memcpy
with no bandwidth to save, so only the added elementwise work registers.
The byte reduction pays on bandwidth-bound interconnects (ICI/DCN), which
this harness measures whenever a multi-chip mesh is present.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/compression.py --out result/compression_cpu.json
"""

from __future__ import annotations

import argparse
import json


def measure(dim: int = 2048, batch_per_chip: int = 8, iters: int = 20):
    import time

    import numpy as np

    import jax
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import MLP, classification_loss
    from chainermn_tpu.utils import sync

    comm_plain = cmn.create_communicator("xla")
    n = comm_plain.size
    B = batch_per_chip * n
    rng = np.random.RandomState(0)
    x = rng.normal(size=(B, dim)).astype(np.float32)
    y = rng.randint(0, 10, size=(B,)).astype(np.int32)

    out = {"devices": n, "dim": dim, "global_batch": B, "iters": iters,
           "platform": jax.devices()[0].platform}
    modes = {
        "fp32": dict(comm=comm_plain, compression=None),
        "bf16_wire": dict(
            comm=cmn.create_communicator(
                "xla", allreduce_grad_dtype="bfloat16"
            ),
            compression=None,
        ),
        "int8_ef": dict(comm=comm_plain, compression="int8_ef"),
    }
    final_losses = {}
    for name, cfg in modes.items():
        model = MLP([dim, dim], 10)
        params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1, momentum=0.9), cfg["comm"],
            grad_compression=cfg["compression"],
        )
        state = opt.init(params)
        step = opt.make_train_step(classification_loss(model), has_aux=True)
        batch = cfg["comm"].shard_batch((x, y))
        for _ in range(3):
            state, m = step(state, batch)
        sync(m)
        # Numerics cross-check EARLY (step 3), before this overfit config
        # saturates every mode to 0.0: a mis-scaled wire (e.g. a stray
        # 1/size) visibly diverges here.
        final_losses[name] = float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, batch)
        sync(m)
        dt = time.perf_counter() - t0
        out[f"{name}_step_ms"] = round(dt / iters * 1000, 3)
    out["loss_at_step3"] = {k: round(v, 6) for k, v in final_losses.items()}
    out["int8_vs_fp32_speedup"] = round(
        out["fp32_step_ms"] / out["int8_ef_step_ms"], 3
    )
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=2048)
    ap.add_argument("--batch-per-chip", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    res = measure(args.dim, args.batch_per_chip, args.iters)
    line = json.dumps(res)
    print(line)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(res, args.out)


if __name__ == "__main__":
    main()
