"""Training goodput under chaos: peer fast-restore vs orbax-only, plus
the replication plane's steady-state overhead.

Three questions, one artifact (ISSUE 18):

1. **Goodput** — the same LM workload driven to a target step through a
   SEEDED crash schedule (:func:`chaos_schedule` /
   :class:`TrainingChaosHarness`), once with the replication plane as the
   restore tier and once with orbax-only checkpoints at the SAME cadence.
   Headline = useful-steps/wall-clock, reported as the peer/orbax ratio.
2. **Recovery latency** — ``recovery_ms`` p50 per arm: the replication
   restore is a local spill read + install; the orbax restore pays full
   checkpoint-manager I/O.  The acceptance bar is peer < orbax.
3. **Overhead** — the obs A/B discipline on the replication plane itself:
   identical train steps with the replicator attached vs absent, each arm
   with its own optimizer (compile lands in that arm's warmup, never the
   timed window).  Contract: < 1% of step time (docs/resilience.md).

Single-process honesty: in-process restores report
``restore_source=local`` (this process holds its own spill); the PEER
serve path is proven end-to-end across OS ranks by
``tests/multiprocess_tests/test_replicate_multiprocess.py``.  The
recovery-latency comparison is unaffected — both tiers restore the same
snapshot bytes.

    python benchmarks/resilience.py --out result/resilience_tpu.json
    JAX_PLATFORMS=cpu python benchmarks/resilience.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import tempfile
import time


class _RepeatIterator:
    """Yields the same global batch forever (the bench stops on
    iteration count)."""

    def __init__(self, batch):
        self._batch = batch
        self.epoch = 0

    def __next__(self):
        return self._batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--target-step", type=int, default=40)
    ap.add_argument("--cadence", type=int, default=8)
    ap.add_argument("--failures", type=int, default=2)
    ap.add_argument("--seed", type=int, default=18)
    ap.add_argument("--overhead-iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.extensions import create_multi_node_checkpointer
    from chainermn_tpu.models import TransformerLM, lm_loss
    from chainermn_tpu.resilience.consistency import tree_digest
    from chainermn_tpu.resilience.replicate import (
        ShardReplicator,
        TrainingChaosHarness,
        chaos_schedule,
        negotiate_restore,
    )
    from chainermn_tpu.training import Trainer

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"resilience bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        args.batch, args.seq, args.layers = 8, 128, 2
        args.d_model, args.heads, args.d_ff, args.vocab = 128, 4, 256, 1024
        args.target_step, args.cadence, args.failures = 16, 4, 2
        args.overhead_iters, args.warmup = 8, 4
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    comm = cmn.create_communicator("xla")
    model = TransformerLM(
        vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
        n_heads=args.heads, d_ff=args.d_ff, max_len=args.seq,
    )
    params = jax.jit(
        lambda r: model.init(r, np.zeros((1, args.seq), np.int32))
    )(jax.random.PRNGKey(0))["params"]
    loss_fn = lm_loss(model)
    rng = np.random.RandomState(0)
    toks = rng.randint(
        0, args.vocab, size=(args.batch, args.seq)
    ).astype(np.int32)
    batch = (toks, toks)
    # ONE optimizer for every chaos attempt and the oracle: each attempt
    # replays the identical jitted step from the cache — a recompile
    # inside an attempt would masquerade as recovery cost.
    opt = cmn.create_multi_node_optimizer(optax.adamw(3e-4), comm)
    state0 = opt.init(params)
    import jax.numpy as jnp

    def fresh_trainer(stop):
        return Trainer(
            opt, jax.tree_util.tree_map(jnp.array, state0), loss_fn,
            _RepeatIterator(comm.shard_batch(batch)),
            stop=(stop, "iteration"), has_aux=True,
        )

    # ---- unfaulted oracle (also the compile warmup) --------------------
    t_oracle0 = time.perf_counter()
    oracle_tr = fresh_trainer(args.target_step)
    oracle_tr.run()
    oracle_wall = time.perf_counter() - t_oracle0
    oracle_digest = tree_digest(oracle_tr.state.params)

    work_dir = tempfile.mkdtemp(prefix="cmn_resilience_bench_")
    schedule = chaos_schedule(
        seed=args.seed, failures=args.failures,
        target_step=args.target_step, cadence=args.cadence,
        kinds=("crash",),
    )

    def run_mode(mode: str) -> dict:
        """One full chaos run to the target step; ``mode`` picks the
        restore tier: ``"rep"`` (ShardReplicator + negotiate_restore, no
        orbax anywhere) or ``"orbax"`` (MultiNodeCheckpointer at the SAME
        cadence, maybe_load on relaunch)."""
        tag_dir = os.path.join(work_dir, mode)

        def run_attempt(attempt, event):
            trainer = fresh_trainer(args.target_step)
            if mode == "rep":
                rep = ShardReplicator(
                    comm if comm.size > 1 else None,
                    every=args.cadence, spill_dir=tag_dir,
                    _use_process_injector=False,
                )
                trainer.extend(rep)
            else:
                ckpt = create_multi_node_checkpointer(
                    "bench", comm, path=tag_dir,
                    trigger=(args.cadence, "iteration"), async_save=False,
                )
                trainer.extend(ckpt)
            restored, source, recovery_ms = 0, None, None
            if attempt > 0:
                t0 = time.perf_counter()
                if mode == "rep":
                    new_state, it, rpt = negotiate_restore(
                        rep, trainer.state, trainer=trainer)
                    source, recovery_ms = rpt["source"], rpt["recovery_ms"]
                else:
                    new_state, it = ckpt.maybe_load(trainer.state, trainer)
                    source = "orbax"
                    recovery_ms = (time.perf_counter() - t0) * 1000.0
                trainer.state, trainer.iteration = new_state, it
                restored = int(it)
            # The "crash": the attempt ends at the event iteration (the
            # teardown/relaunch cost is the launcher's, identical for
            # both tiers — what differs, and what this measures, is the
            # restore path and the work replayed).
            if event is not None:
                trainer.stop_n = int(event["iter"])
            trainer.run()
            crashed = event is not None and \
                trainer.iteration < args.target_step
            if mode == "orbax":
                ckpt.finalize()
                ckpt.close()
            return {
                "rc": 1 if crashed else 0,
                "final_step": int(trainer.iteration),
                "restored_step": restored,
                "restore_source": source,
                "recovery_ms": recovery_ms,
                "digest": (
                    tree_digest(trainer.state.params)
                    if not crashed else None
                ),
            }

        result = TrainingChaosHarness(run_attempt, schedule).run()
        result["verdict"] = TrainingChaosHarness.verify(
            result, oracle_digest if mode == "rep" else None)
        return result

    rep = run_mode("rep")
    orbax = run_mode("orbax")

    def p50(xs):
        return round(statistics.median(xs), 3) if xs else None

    # ---- steady-state overhead A/B (replication on vs off) -------------
    def overhead_arm(on: bool) -> float:
        # Per-arm optimizer: the jitted step is born (and compiled)
        # inside this arm's warmup — the same compile-pinning discipline
        # as benchmarks/observability.py.
        arm_opt = cmn.create_multi_node_optimizer(optax.adamw(3e-4), comm)
        trainer = Trainer(
            arm_opt, jax.tree_util.tree_map(jnp.array, state0), loss_fn,
            _RepeatIterator(comm.shard_batch(batch)),
            stop=(args.warmup, "iteration"), has_aux=True,
        )
        trainer.run()  # warmup: compile out of the timed window
        if on:
            trainer.extend(ShardReplicator(
                None, every=args.cadence,
                spill_dir=os.path.join(work_dir, "overhead"),
                _use_process_injector=False,
            ))
        trainer.stop_n = args.warmup + args.overhead_iters
        t0 = time.perf_counter()
        trainer.run()
        _ = float(np.asarray(trainer.last_metrics["loss"]))
        return (time.perf_counter() - t0) / args.overhead_iters * 1000.0

    off_ms = overhead_arm(False)
    on_ms = overhead_arm(True)
    overhead_pct = (on_ms - off_ms) / off_ms * 100.0

    goodput_ratio = (
        rep["goodput_steps_per_s"] / orbax["goodput_steps_per_s"]
        if orbax["goodput_steps_per_s"] else None
    )
    payload = {
        "metric": "train_chaos_goodput",
        "value": round(goodput_ratio, 3) if goodput_ratio else None,
        "unit": "peer-restore goodput / orbax-only goodput (same seeded "
                "crash schedule)",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
        "seed": args.seed,
        "target_step": args.target_step,
        "cadence": args.cadence,
        "failures": len(schedule["events"]),
        "oracle_wall_s": round(oracle_wall, 3),
        "rep": {
            "goodput_steps_per_s": round(rep["goodput_steps_per_s"], 3),
            "wall_s": round(rep["wall_s"], 3),
            "recovery_ms_p50": p50(rep["recovery_ms"]),
            "lost_steps_per_failure": rep["lost_steps_per_failure"],
            "bit_exact_vs_oracle": rep["final_digest"] == oracle_digest,
            "invariant_holds": rep["verdict"]["holds"],
        },
        "orbax": {
            "goodput_steps_per_s": round(orbax["goodput_steps_per_s"], 3),
            "wall_s": round(orbax["wall_s"], 3),
            "recovery_ms_p50": p50(orbax["recovery_ms"]),
            "lost_steps_per_failure": orbax["lost_steps_per_failure"],
        },
        "recovery_ms_peer_p50": p50(rep["recovery_ms"]),
        "recovery_ms_orbax_p50": p50(orbax["recovery_ms"]),
        "rep_overhead_pct": round(overhead_pct, 3),
        "step_ms_rep_off": round(off_ms, 3),
        "step_ms_rep_on": round(on_ms, 3),
        "restore_note": "single-process restores report source=local; "
                        "the peer serve path is proven by "
                        "tests/multiprocess_tests/"
                        "test_replicate_multiprocess.py",
        "contract": "peer recovery_ms p50 < orbax p50; replication "
                    "overhead < 1% of step time (docs/resilience.md)",
        "config": {"batch": args.batch, "seq": args.seq,
                   "layers": args.layers, "d_model": args.d_model,
                   "heads": args.heads, "d_ff": args.d_ff,
                   "vocab": args.vocab},
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(payload))
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(payload, args.out)


if __name__ == "__main__":
    main()
