"""Autoregressive decode throughput: generated tokens/sec with the KV cache.

The inference-side counterpart of the LM training bench: one ``lax.scan``
decode program (``models.lm_generate``), measured end to end — prefill plus
``n_new`` generated tokens — at a batch of concurrent sequences.  Decode is
memory-bound (each step reads the whole cache + params for a (B, D) matvec
set), so tokens/sec tracks HBM bandwidth, not MXU flops.

    python benchmarks/decode.py --out result/decode_tpu.json    # real chip
    JAX_PLATFORMS=cpu python benchmarks/decode.py --smoke       # plumbing
"""

from __future__ import annotations

import argparse
import json
import time


def _divergence_stats(spec_toks, plain_toks):
    """Per-row first-divergence positions between two greedy generations.

    A logic bug diverges at step ~0 on every row; a finite-precision
    argmax tie-flip diverges at a random depth per row (and rows can stay
    exact).  ``None`` in the list = that row matched exactly.
    """
    import numpy as np

    spec = np.asarray(spec_toks)
    plain = np.asarray(plain_toks)
    firsts = []
    for b in range(spec.shape[0]):
        mm = spec[b] != plain[b]
        firsts.append(int(np.argmax(mm)) if mm.any() else None)
    diverged = [f for f in firsts if f is not None]
    return {
        "rows_exact": len(firsts) - len(diverged),
        "rows": len(firsts),
        "first_divergence_per_row": firsts,
        "min_first_divergence": min(diverged) if diverged else None,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=128)
    ap.add_argument("--new", type=int, default=512)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--window", type=int, default=0,
                    help="sliding-window model; with --rolling, decode "
                         "through the O(window) ring cache")
    ap.add_argument("--rolling", action="store_true",
                    help="ring-buffer KV cache (needs --window); also "
                         "times the full-cache baseline for comparison")
    ap.add_argument("--rope", action="store_true",
                    help="rotary positions (required to stream past "
                         "max_len; pairs naturally with --rolling)")
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention: kv heads (0 = classic "
                         "MHA) — shrinks the KV cache, decode's dominant "
                         "bandwidth term, by n_heads/kv_heads")
    ap.add_argument("--decode-attention", default=None,
                    choices=("einsum", "fused"),
                    help="also time the chosen decode-attention impl as "
                         "an arm against the model default: 'fused' runs "
                         "every generation step through the Pallas "
                         "decode kernel over the kv-head-major cache "
                         "(ops.fused_decode_attention), 'einsum' the "
                         "classic XLA path.  Output-equivalent (greedy "
                         "agreement reported with divergence structure), "
                         "so the arm competes for the decode headline")
    ap.add_argument("--kv-int8", action="store_true",
                    help="also time an int8-quantized KV cache arm "
                         "(kv_dtype=jnp.int8: same params, half the "
                         "HBM-resident cache bytes) against the float "
                         "cache in the SAME process; reports the speedup "
                         "and the greedy-token agreement structure")
    ap.add_argument("--speculative", type=int, default=0, metavar="K",
                    help="also time speculative decoding with K proposals "
                         "per round from a shallow draft model")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="draft depth (default: layers // 4, min 1)")
    ap.add_argument("--draft-self", action="store_true",
                    help="draft = the target itself (perfect agreement): "
                         "measures the IDEAL-acceptance schedule — the "
                         "forwards cut a well-trained draft approaches — "
                         "rather than a random-weights draft whose "
                         "near-zero acceptance only shows overhead")
    ap.add_argument("--spec-ks", default=None,
                    help="comma list of K values to sweep (reuses the one "
                         "plain-decode timing; e.g. --spec-ks 2,4,8); "
                         "implies --speculative")
    ap.add_argument("--draft-mode", default=None,
                    choices=("self", "random", "distilled"),
                    help="self = ideal acceptance at FULL draft cost; "
                         "random = real small-draft cost at ~zero "
                         "acceptance (overhead floor); distilled = the "
                         "target's tail blocks are zeroed so its function "
                         "collapses to its first draft-layers blocks, and "
                         "exactly those blocks ARE the draft — realistic "
                         "draft cost with near-ideal acceptance, i.e. the "
                         "measured wall-clock bound a perfectly distilled "
                         "draft can reach (VERDICT r4 missing #3)")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    spec_ks = (
        [int(x) for x in args.spec_ks.split(",")] if args.spec_ks else None
    )
    if spec_ks:
        # max over BOTH sources: model/draft max_len is sized from
        # args.speculative, and a sweep entry larger than it would crash
        # the verify-chunk bound mid-run after the plain baseline already
        # burned chip time.
        args.speculative = max(args.speculative, *spec_ks)
    if args.rolling and not args.window:
        # Fail at argparse time, not after the full-cache baseline has
        # burned minutes of chip time.
        ap.error("--rolling needs --window (sliding-window model)")

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models import (
        TransformerLM,
        lm_generate,
        lm_speculative_generate,
    )
    from chainermn_tpu.ops import resolve_attention

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"decode bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        args.batch, args.prompt, args.new = 2, 16, 32
        args.layers, args.d_model, args.heads = 2, 128, 4
        args.d_ff, args.vocab, args.iters = 256, 1024, 2
        if args.window:
            # Shrink the ring below prompt+new so the smoke run actually
            # exercises wraparound/eviction (a 1024-slot ring over 48
            # positions would never wrap).
            args.window = min(args.window, 16)
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    model = TransformerLM(
        vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
        n_heads=args.heads, d_ff=args.d_ff,
        # --speculative needs verify headroom: the (k+1)-token verify chunk
        # touches positions past the plain generation bound.
        max_len=args.prompt + args.new + (
            args.speculative + 1 if args.speculative else 0
        ),
        window=args.window,
        pos_enc="rope" if args.rope else "learned",
        n_kv_heads=args.kv_heads,
    )
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, args.prompt), jnp.int32)
        )
    )(jax.random.PRNGKey(0))["params"]
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(
        rng.randint(0, args.vocab, size=(args.batch, args.prompt)).astype(
            np.int32
        )
    )

    def timed(rolling, m=None):
        m = m or model
        gen = jax.jit(
            lambda p, pr: lm_generate(m, p, pr, args.new,
                                      rolling=rolling)
        )
        warm = np.asarray(gen(params, prompt))  # compile+warm, value-synced
        # Sync each iteration with a real device->host readback: over the
        # axon tunnel `block_until_ready` can return EARLY on queued steps
        # (observed here as ms_per_gen_step 0.0 => a 22M tok/s fantasy); a
        # value transfer cannot lie.  Same policy as bench.py.
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out_tokens = gen(params, prompt)
            _ = np.asarray(out_tokens[:1, -1:])
        return time.perf_counter() - t0, warm

    dt, plain_toks = timed(False)
    rolling_dt = timed(True)[0] if args.rolling else None

    # Batched prefill = ONE forward; the sequential part is the n_new-1
    # generation steps (plus that prefill program).
    steps = args.new
    gen_tps = args.batch * args.new * args.iters / dt
    payload = {
        "metric": "lm_decode_tokens_per_sec",
        "value": round(gen_tps, 1),
        "unit": "generated tokens/sec",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "batch": args.batch,
        "prompt": args.prompt,
        "n_new": args.new,
        "config": {"layers": args.layers, "d_model": args.d_model,
                   "heads": args.heads, "d_ff": args.d_ff,
                   "vocab": args.vocab, "kv_heads": args.kv_heads},
        "ms_per_gen_step": round(dt / args.iters / steps * 1000.0, 3),
        # Resolved impl tag (ADVICE r3): the model default is "auto" — the
        # PREFILL resolves per-shape; generation steps always run the
        # cached single-position path (never the Pallas kernel).
        "attention_requested": model.attention,
        "attention_resolved_prefill": resolve_attention(
            model.attention, args.prompt
        ),
    }
    if args.window:
        payload["window"] = args.window
    if args.rope:
        payload["pos_enc"] = "rope"
    if args.decode_attention and args.decode_attention == model.decode_attention:
        # Not silently dropped: the requested impl IS the baseline, so a
        # separate arm would time the identical program twice.
        payload["decode_attention_arm"] = {
            "skipped": f"requested impl '{args.decode_attention}' is "
                       "already the model default — no separate arm to "
                       "time",
            "impl": args.decode_attention,
            "baseline_impl": model.decode_attention,
        }
    elif args.decode_attention:
        # BEFORE the speculative block (same reason as --kv-int8 below:
        # --draft-mode distilled mutates `params` in place).  Same params,
        # same prompt — only the decode-step attention impl and its cache
        # layout change, so the ratio isolates the fused kernel's single
        # VMEM pass over the cache vs the einsum's fp32 materializations.
        # Greedy-token agreement vs the default path is reported with the
        # divergence structure: exact in fp32, bf16 near-argmax ties can
        # flip between the two kernels.
        fa_model = model.clone(decode_attention=args.decode_attention)
        fa_dt, fa_toks = timed(False, m=fa_model)
        payload["decode_attention_arm"] = {
            "impl": args.decode_attention,
            "baseline_impl": model.decode_attention,
            "tokens_per_sec": round(
                args.batch * args.new * args.iters / fa_dt, 1
            ),
            "ms_per_gen_step": round(
                fa_dt / args.iters / steps * 1000.0, 3
            ),
            "speedup_vs_default": round(dt / fa_dt, 3),
            "greedy_agreement": _divergence_stats(fa_toks, plain_toks),
        }
    if args.kv_int8:
        # BEFORE the speculative block: --draft-mode distilled mutates
        # `params` in place (zeroing tail-block write-backs), so an int8
        # arm run after it would time — and compare agreement against —
        # the zero-tail model while `dt`/`plain_toks` came from the real
        # one.  Same params (kv_dtype only changes cache storage), same
        # prompt, same process: the ratio isolates the cache-bandwidth
        # halving.  Token agreement vs the float cache is reported with
        # the same divergence structure as the speculative check — int8
        # absmax noise can flip near-argmax-ties, a logic bug flips row 0
        # step 0.
        q8_model = model.clone(kv_dtype=jnp.int8)
        q8_dt, q8_toks = timed(False, m=q8_model)
        payload["kv_int8"] = {
            "tokens_per_sec": round(
                args.batch * args.new * args.iters / q8_dt, 1
            ),
            "ms_per_gen_step": round(
                q8_dt / args.iters / steps * 1000.0, 3
            ),
            "speedup_vs_float_cache": round(dt / q8_dt, 3),
            # k+v int8 payload plus the two fp32 scale planes.
            "cache_bytes_per_layer": (
                2 * args.batch * model.max_len
                * (args.kv_heads or args.heads)
                * (args.d_model // args.heads + 4)
            ),
            "greedy_agreement": _divergence_stats(q8_toks, plain_toks),
        }
    if args.speculative:
        # Draft-propose / target-verify: output is EXACTLY the target's
        # greedy generation (asserted below on real outputs), so the
        # speedup — if any — is pure schedule.  Decode is latency-bound
        # per sequential step; a k-round accepts 1..k+1 tokens for
        # k draft steps + ONE target forward.
        k = args.speculative
        mode = args.draft_mode or ("self" if args.draft_self else "random")
        if mode == "self":
            draft, dparams = model, params
        elif mode == "random":
            draft = TransformerLM(
                vocab=args.vocab,
                n_layers=args.draft_layers or max(1, args.layers // 4),
                d_model=args.d_model, n_heads=args.heads, d_ff=args.d_ff,
                max_len=args.prompt + args.new + k + 1,
                window=args.window,
                pos_enc="rope" if args.rope else "learned",
                n_kv_heads=args.kv_heads,
            )
            dparams = jax.jit(
                lambda r: draft.init(
                    r, jnp.zeros((1, args.prompt), jnp.int32)
                )
            )(jax.random.PRNGKey(1))["params"]
        else:  # distilled
            # Zero the residual write-backs (proj, ff2) of every block past
            # the draft depth: those blocks become exact identities, so the
            # TARGET's function equals its first `dl` blocks while still
            # paying full 12-layer compute — and those `dl` blocks + head
            # ARE the draft.  Greedy acceptance is then near-perfect (only
            # bf16 verify-vs-step kernel tie-flips differ) at a REAL
            # dl/layers draft cost: the measured upper bound for a
            # perfectly distilled draft.  No training needed, nothing
            # simulated — both programs run at full honest cost.
            dl = args.draft_layers or max(1, args.layers // 6)
            params = dict(params)
            for i in range(dl, args.layers):
                blk = dict(params[f"block_{i}"])
                for nm in ("proj", "ff2"):
                    blk[nm] = jax.tree.map(jnp.zeros_like, blk[nm])
                params[f"block_{i}"] = blk
            draft = TransformerLM(
                vocab=args.vocab, n_layers=dl, d_model=args.d_model,
                n_heads=args.heads, d_ff=args.d_ff,
                max_len=args.prompt + args.new + k + 1,
                window=args.window,
                pos_enc="rope" if args.rope else "learned",
                n_kv_heads=args.kv_heads,
            )
            dparams = {
                f"block_{i}": params[f"block_{i}"] for i in range(dl)
            }
            for nm in ("embed", "ln_f", "lm_head"):
                dparams[nm] = params[nm]
            if not args.rope:
                dparams["pos"] = params["pos"]
            # The zero-tail target is a different function from the
            # random-init one the plain timing ran (same cost, different
            # values): regenerate the greedy reference for the equality
            # check below.
            plain_toks = np.asarray(jax.jit(
                lambda p, pr: lm_generate(model, p, pr, args.new)
            )(params, prompt))
        draft_labels = {
            "self": "self (ideal acceptance, full draft cost)",
            "random": "random init (near-zero acceptance: overhead "
                      "bound only — untrained drafts can't agree)",
            "distilled": "zero-tail distillation (realistic "
                         f"{draft.n_layers}/{args.layers}-layer draft "
                         "cost, near-ideal acceptance: the bound a "
                         "perfectly distilled draft reaches)",
        }
        ks = spec_ks or [k]
        spec_recs = []
        for ki in ks:
            spec = jax.jit(
                lambda tp, dp, pr, _k=ki: lm_speculative_generate(
                    model, tp, draft, dp, pr, n_new=args.new, k=_k
                )
            )
            toks, fwds = spec(params, dparams, prompt)
            toks = np.asarray(toks)  # compile + warm, value-synced
            t0 = time.perf_counter()
            for _ in range(args.iters):
                toks_i, fwds = spec(params, dparams, prompt)
                _ = np.asarray(toks_i[:1, -1:])
            spec_dt = time.perf_counter() - t0
            spec_recs.append({
                "k": ki,
                "draft_layers": draft.n_layers,
                "draft": draft_labels[mode],
                # fwds includes the PREFILL forward, which emits 1 token
                # outside any draft round (lm_speculative_generate doc);
                # each of the fwds-1 rounds then emits accepted + 1 tokens
                # (the verify step's own token is free).  Subtracting both
                # makes the metric exact at every acceptance level: 0.0
                # for a zero-acceptance draft, k for a perfect one.
                "tokens_per_target_forward": round(
                    args.new / int(fwds), 3
                ),
                "mean_accepted_per_round": round(
                    (args.new - 1) / max(int(fwds) - 1, 1) - 1.0, 3
                ),
                "tokens_per_sec": round(
                    args.batch * args.new * args.iters / spec_dt, 1
                ),
                "speedup_vs_plain": round(dt / spec_dt, 3),
                "target_forwards": int(fwds),
                "plain_sequential_steps": args.new,
                "matches_target_greedy": bool((toks == plain_toks).all()),
                # Speculative equality with plain greedy holds in EXACT
                # arithmetic (pinned bitwise by the CPU f32 oracle tests);
                # on TPU bf16 the (k+1)-token verify chunk and the 1-token
                # plain step are different XLA kernels whose logits differ
                # by ~0.04 (measured, 2026-08-01), so near-argmax-ties can
                # flip and everything after a flip diverges.  Divergence
                # structure distinguishes that from a logic bug (which
                # diverges immediately on every row):
                "greedy_tie_divergence": _divergence_stats(toks, plain_toks),
            })
        # Monomorphic schema: "speculative" stays the single-run OBJECT the
        # existing artifacts carry (result/decode_spec_tpu.json consumers
        # keep working); a --spec-ks sweep lands under its own LIST key.
        if spec_ks:
            payload["speculative_sweep"] = spec_recs
        else:
            payload["speculative"] = spec_recs[0]
    if rolling_dt is not None:
        payload["rolling"] = {
            "tokens_per_sec": round(
                args.batch * args.new * args.iters / rolling_dt, 1
            ),
            "ms_per_gen_step": round(
                rolling_dt / args.iters / steps * 1000.0, 3
            ),
            "speedup_vs_full_cache": round(dt / rolling_dt, 3),
            "cache_slots": args.window,
            "full_cache_slots": args.prompt + args.new,
        }
    print(json.dumps(payload))
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(payload, args.out)


if __name__ == "__main__":
    main()
