"""MoE-LM training throughput: dense FFN vs mixture-of-experts at MATCHED
active FLOPs per token.

The EP subsystem's perf story (SURVEY.md §2.3 EP row — the reference shipped
only the ``alltoall`` building block; VERDICT r4 missing #2 asked for the
measured payoff).  One GPT-2-small trunk; the dense arm runs ``d_ff = k·F``,
the MoE arms run ``E`` experts of per-expert width ``F`` with top-``k``
routing, so every arm spends the same expert matmul FLOPs per token — the
measured delta IS the routing overhead (router + dispatch/combine einsums +
load-imbalance drops), i.e. the price of decoupling parameter count from
active compute.  Capacity-factor sweep records the drop-rate/overhead trade.

    python benchmarks/moe.py --out result/moe_tpu.json       # real chip
    JAX_PLATFORMS=cpu python benchmarks/moe.py --smoke ...    # plumbing
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--expert-ff", type=int, default=1536,
                    help="per-expert hidden width F; the dense arm runs "
                         "d_ff = k*F so active FLOPs match")
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--moe-k", type=int, default=2)
    ap.add_argument("--capacity-factors", default="1.0,1.25,2.0")
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import TransformerLM, lm_loss_chunked
    from chainermn_tpu.utils import compiled_flops, mfu

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"moe bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        args.batch, args.seq, args.layers = 8, 128, 2
        args.d_model, args.heads, args.expert_ff = 64, 2, 128
        args.experts, args.vocab, args.iters = 4, 512, 2
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    cfs = [float(s) for s in args.capacity_factors.split(",")]
    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": vars(args),
        "note": "dense arm d_ff = k*expert_ff: identical active expert "
                "FLOPs/token; MoE deltas = routing overhead + drops",
    }

    comm = cmn.create_communicator("xla", allreduce_grad_dtype=jnp.bfloat16)
    tokens_per_step = args.batch * args.seq
    rng = np.random.RandomState(0)
    toks = rng.randint(
        0, args.vocab, size=(args.batch, args.seq)
    ).astype(np.int32)
    batch = comm.shard_batch((toks, toks))

    def run_arm(label, **model_kw):
        model = TransformerLM(
            vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
            n_heads=args.heads, max_len=args.seq, attention="auto",
            remat=True, **model_kw,
        )
        # adafactor both arms: the MoE arm's E/k-fold parameter surplus
        # with adamw fp32 moments would confound the throughput compare
        # with an optimizer-memory story.
        opt = cmn.create_multi_node_optimizer(optax.adafactor(3e-4), comm)
        params = jax.jit(
            lambda r: model.init(r, jnp.zeros((1, args.seq), jnp.int32))
        )(jax.random.PRNGKey(0))["params"]
        n_params = sum(x.size for x in jax.tree.leaves(params))
        state = jax.block_until_ready(jax.jit(opt.init)(params))
        step = opt.make_train_step(
            lm_loss_chunked(model, chunk_size=8192), has_aux=True
        )
        compiled = step.lower(state, batch).compile()
        flops = compiled_flops(compiled)
        for _ in range(2):
            state, metrics = step(state, batch)
            _ = float(metrics["loss"])  # device→host sync (tunnel-safe)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        dt = time.perf_counter() - t0
        rec = {
            "label": label,
            "n_params_m": round(n_params / 1e6, 1),
            "step_ms": round(dt / args.iters * 1000.0, 2),
            "tokens_per_sec_per_chip": round(
                tokens_per_step * args.iters / dt / n_dev, 1
            ),
        }
        if flops:
            rec["tflops_per_step"] = round(flops / 1e12, 3)
            m = mfu(compiled, dt / args.iters, n_dev, out["device_kind"])
            if m is not None:
                rec["mfu_pct"] = round(m, 2)
            from chainermn_tpu.ops import resolve_attention
            from chainermn_tpu.utils import (
                attention_core_flops,
                flash_mfu_fields,
            )

            if m is not None and resolve_attention(
                    "auto", args.seq, causal=True) == "flash":
                # The trunk's auto-attention resolves to the Pallas flash
                # kernel at this T, which XLA's FLOP counter can't see —
                # mfu_pct is a lower bound; emit the inclusive number too.
                extra = args.layers * attention_core_flops(
                    args.batch, args.heads, args.seq,
                    args.d_model // args.heads, causal=True,
                    n_forward=2,  # remat=True re-runs the forward kernel
                )
                rec.update(flash_mfu_fields(
                    flops, extra, dt / args.iters, n_dev,
                    out["device_kind"],
                ))
        for key in ("moe_aux", "moe_dropped"):
            if key in metrics:
                rec[key] = round(float(metrics[key]), 4)
        held = jax.tree.leaves((params, state))
        del params, state, step, compiled
        for a in held:
            try:
                a.delete()
            except Exception:
                pass
        jax.clear_caches()
        return rec

    arms = [("dense", dict(d_ff=args.moe_k * args.expert_ff))]
    for cf in cfs:
        arms.append((
            f"moe_cf{cf:g}",
            dict(d_ff=args.expert_ff, n_experts=args.experts,
                 moe_k=args.moe_k, moe_capacity_factor=cf),
        ))

    retryable = False
    results = []
    for label, kw in arms:
        try:
            rec = run_arm(label, **kw)
        except Exception as e:
            # Same artifact discipline as benchmarks/lm.py: OOM is a real
            # property of the geometry (recordable); anything else is
            # transient — withhold so the watcher retries.
            rec = {"label": label,
                   "error": f"{type(e).__name__}: {str(e)[:200]}"}
            if "RESOURCE_EXHAUSTED" not in str(e):
                retryable = True
            jax.clear_caches()
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if retryable:
            break
    out["arms"] = results

    dense = next((r for r in results if r["label"] == "dense"
                  and "step_ms" in r), None)
    for r in results:
        if dense and r is not dense and "step_ms" in r:
            r["vs_dense_tokens"] = round(
                r["tokens_per_sec_per_chip"]
                / dense["tokens_per_sec_per_chip"], 3
            )
    print(json.dumps({k: v for k, v in out.items() if k != "config"}))
    measured = [r for r in results if "step_ms" in r]
    complete = bool(measured) and not retryable
    if args.out and complete:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)
    elif args.out:
        print(json.dumps({"error": "incomplete run; artifact withheld"}))
    if not complete:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
