"""PipelineChain vs compute-replicated MultiNodeChainList.

The reference's ``MultiNodeChainList`` chains sub-models sequentially with no
microbatch interleaving (SURVEY.md §2.3 "Pipeline parallel: PARTIAL").  Our
API-parity tier reproduces that (and, under SPMD, is compute-replicated —
every device computes every stage); :class:`PipelineChain` is the tier that
must actually be *faster*: stage-sharded params, GPipe microbatching, per
-device work ∝ (S+M-1)/M microbatches instead of S full batches.

This harness measures both on an identical homogeneous stage stack
(fwd+bwd+update-free step), prints one JSON line per config, and reports the
speedup.  Run on the forced-CPU mesh (shared cores make total work visible)
or real chips:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/pipeline.py
"""

from __future__ import annotations

import argparse
import json


def measure(d: int = 256, B: int = 128, M: int = 4, iters: int = 5):
    """Return ``{"replicated_s", "pipeline_s", "speedup", ...}`` for an
    S=n_devices-stage tanh-MLP stack (fwd+bwd per step)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as cmn
    from chainermn_tpu.links import MultiNodeChainList, PipelineChain
    from chainermn_tpu.utils import benchmark

    comm = cmn.create_communicator("xla")
    S = comm.size
    rng = np.random.RandomState(0)
    stages = rng.normal(size=(S, d, d)).astype(np.float32) * (0.5 / np.sqrt(d))
    x = rng.normal(size=(B, d)).astype(np.float32)

    # --- compute-replicated chain (API-parity tier) ----------------------
    chain = MultiNodeChainList(comm)
    for s in range(S):
        chain.add_link(
            (lambda w: lambda p, h: jnp.tanh(h @ p))(None),
            rank=s,
            rank_in=s - 1 if s > 0 else None,
            rank_out=s + 1 if s < S - 1 else None,
        )

    def chain_loss(params_list, x):
        def body(*args):
            *ps, xx = args
            y = chain(list(ps), xx)
            y = cmn.functions.bcast(comm, y, root=S - 1)
            return jnp.sum(y**2)

        return comm.spmd(
            body,
            in_specs=tuple([P()] * S) + (P(),),
            out_specs=P(),
            check_vma=False,
        )(*params_list, x)

    chain_step = jax.jit(jax.grad(chain_loss))
    params_list = [stages[s] for s in range(S)]

    rep = benchmark(lambda: chain_step(params_list, x), warmup=2, iters=iters)

    # --- pipeline tier ---------------------------------------------------
    pipe = PipelineChain(lambda p, h: jnp.tanh(h @ p[0]), comm, n_microbatches=M)

    def pipe_loss(stages, x):
        f = comm.spmd(
            lambda p, xx: jnp.sum(pipe(p, xx) ** 2),
            in_specs=(P(comm.axes), P()),
            out_specs=P(),
            check_vma=False,
        )
        return f(stages, x)

    pipe_step = jax.jit(jax.grad(pipe_loss))
    pip = benchmark(lambda: pipe_step(stages, x), warmup=2, iters=iters)

    return {
        "devices": S,
        "stages": S,
        "microbatches": M,
        "dim": d,
        "batch": B,
        "replicated_s": round(rep["mean_s"], 5),
        "pipeline_s": round(pip["mean_s"], 5),
        "speedup": round(rep["mean_s"] / pip["mean_s"], 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--out", default=None, help="optional JSON output path")
    args = ap.parse_args()

    import jax

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    if jax.default_backend() == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    res = measure(args.dim, args.batch, args.microbatches, args.iters)
    print(json.dumps(res), flush=True)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(res, args.out)


if __name__ == "__main__":
    main()
