"""ResNet-50 roofline analysis — what bounds MFU on a TPU v5e-class chip.

Pure static math (no hardware needed): enumerate the model's conv/matmul
layers at the benchmark geometry, compute each one's FLOPs and minimum HBM
traffic (bf16 activations in+out, fp32 weights), and lower-bound its time by
``max(flops/peak_flops, bytes/peak_bw)`` — the roofline.  Summing the
per-layer bounds (+ the BN/ReLU elementwise traffic, which is pure
bandwidth) yields the best-case step time a perfect scheduler could reach,
i.e. an MFU *ceiling* to interpret measured numbers against (VERDICT r2
item 6: "report ≥40% MFU or a written analysis of what bounds it").

The model: fwd conv FLOPs ×3 for fwd+bwd (dgrad + wgrad each cost one
conv), traffic ×3 likewise — the standard training approximation.

    python benchmarks/roofline.py --out result/roofline_resnet50.json
"""

from __future__ import annotations

import argparse
import json

# Chip model (public specs, bf16).
PEAK_FLOPS = 197e12
PEAK_HBM_BW = 819e9  # bytes/sec

# ResNet-50 conv inventory at 224²: (out_spatial, k, c_in, c_out, repeats).
# Bottleneck blocks: 1x1 reduce, 3x3, 1x1 expand (+ the stage's projection
# shortcut once).  Spatial sizes after the stride-2 stem conv + maxpool.
def resnet50_convs():
    layers = [("stem", 112, 7, 3, 64, 1)]
    stages = [  # (spatial, width, blocks)
        (56, 64, 3),
        (28, 128, 4),
        (14, 256, 6),
        (7, 512, 3),
    ]
    c_prev = 64
    for s, w, blocks in stages:
        layers.append((f"proj{w}", s, 1, c_prev, w * 4, 1))
        for b in range(blocks):
            cin = c_prev if b == 0 else w * 4
            layers.append((f"r{w}a", s, 1, cin, w, 1))
            layers.append((f"r{w}b", s, 3, w, w, 1))
            layers.append((f"r{w}c", s, 1, w, w * 4, 1))
        c_prev = w * 4
    layers.append(("head", 1, 1, 2048, 1000, 1))
    return layers


def analyze(batch: int):
    rows = []
    t_total = 0.0
    f_total = 0.0
    bw_bound_time = 0.0
    for name, s, k, cin, cout, rep in resnet50_convs():
        n_pix = batch * s * s
        flops = 2.0 * n_pix * k * k * cin * cout * rep * 3  # fwd+dgrad+wgrad
        act_bytes = 2.0 * n_pix * (cin + cout) * rep * 3  # bf16 in+out
        w_bytes = 4.0 * k * k * cin * cout * rep * 3
        bytes_ = act_bytes + w_bytes
        t = max(flops / PEAK_FLOPS, bytes_ / PEAK_HBM_BW)
        rows.append({
            "layer": name, "spatial": s, "k": k, "cin": cin, "cout": cout,
            "gflops": round(flops / 1e9, 1),
            "mbytes": round(bytes_ / 1e6, 1),
            "intensity": round(flops / bytes_, 1),
            "bound": "flops" if flops / PEAK_FLOPS >= bytes_ / PEAK_HBM_BW
            else "bandwidth",
            "us": round(t * 1e6, 1),
        })
        t_total += t
        f_total += flops
        if rows[-1]["bound"] == "bandwidth":
            bw_bound_time += t
    # BN + ReLU + residual adds: pure elementwise traffic over every
    # activation tensor ~3x per block position (read+write, fwd+bwd).  A
    # coarse but honest floor: 6 bytes/bf16-element × activations touched.
    act_elems = 0
    for name, s, k, cin, cout, rep in resnet50_convs():
        act_elems += batch * s * s * cout * rep
    elementwise_bytes = 6.0 * 2.0 * act_elems * 3
    t_elem = elementwise_bytes / PEAK_HBM_BW
    t_convs = t_total
    t_total += t_elem
    return {
        "batch": batch,
        "total_train_tflops_per_step": round(f_total / 1e12, 2),
        "roofline_step_ms": round(t_total * 1e3, 2),
        "conv_only_roofline_ms": round(t_convs * 1e3, 2),
        "elementwise_ms": round(t_elem * 1e3, 2),
        "bandwidth_bound_conv_ms": round(bw_bound_time * 1e3, 2),
        # Two ceilings bracketing reality: no fusion at all (every BN/ReLU
        # round-trips HBM) vs perfect fusion (elementwise free, convs pay
        # only their own roofline — the bandwidth-bound stem/head and
        # first-stage convs still cap it well below 100%).
        "mfu_ceiling_unfused_pct": round(
            100 * f_total / (t_total * PEAK_FLOPS), 1
        ),
        "mfu_ceiling_fused_pct": round(
            100 * f_total / (t_convs * PEAK_FLOPS), 1
        ),
        "peak_flops": PEAK_FLOPS,
        "peak_hbm_bw": PEAK_HBM_BW,
        "layers": rows,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    res = analyze(args.batch)
    line = json.dumps({k: v for k, v in res.items() if k != "layers"})
    print(line)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(res, args.out)


if __name__ == "__main__":
    main()
