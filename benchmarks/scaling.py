"""Scaling-efficiency harness: per-chip throughput retention across pod sizes.

The reference's headline claim is near-linear ResNet-50 scaling (BASELINE.md);
this harness measures the same quantity for any model/step on whatever
devices are present — real chips on a pod, or the forced-CPU simulation:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/scaling.py

Also runs the ``DummyCommunicator`` ablation (upper-bound scaling with
communication removed — the reference's stated purpose for that class),
so the printed efficiency gap attributes directly to comm cost.
Prints one JSON line per (size, communicator) config.
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-per-chip", type=int, default=64)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--out", default=None, help="optional JSON output path")
    args = ap.parse_args()

    import jax

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    if jax.default_backend() == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import MLP, classification_loss
    from chainermn_tpu.utils import benchmark, scaling_efficiency

    all_devices = jax.devices()
    on_cpu = all_devices[0].platform == "cpu"
    sizes = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= len(all_devices)]
    rng = np.random.RandomState(0)

    results = {
        "platform": all_devices[0].platform,
        "device_kind": all_devices[0].device_kind,
        "sizes": sizes,
        "batch_per_chip": args.batch_per_chip,
        "dim": args.dim,
    }
    if on_cpu:
        # Honest framing: the forced-CPU virtual devices SHARE one host's
        # cores, so per-chip retention measures nothing — total throughput
        # staying flat as N grows, and the xla-vs-dummy gap (communication
        # cost), are the meaningful CPU-mesh quantities.
        results["note"] = (
            "virtual CPU mesh: devices share one host's cores; read "
            "total_samples_per_sec flatness and comm_overhead_pct, not "
            "per-chip scaling"
        )
    for dummy in (False, True):
        throughputs = []
        for n in sizes:
            devs = all_devices[:n]
            comm = (
                cmn.DummyCommunicator(cmn.flat_mesh(devs))
                if dummy
                else cmn.XlaCommunicator(cmn.flat_mesh(devs))
            )
            model = MLP([args.dim, args.dim], 10)
            B = args.batch_per_chip * n
            x = rng.normal(size=(B, args.dim)).astype(np.float32)
            y = rng.randint(0, 10, size=(B,)).astype(np.int32)
            params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
            opt = cmn.create_multi_node_optimizer(
                optax.sgd(0.1, momentum=0.9), comm
            )
            state = opt.init(params)
            step = opt.make_train_step(
                classification_loss(model), has_aux=True, donate=False
            )
            batch = comm.shard_batch((x, y))
            holder = {"state": state}

            def run():
                holder["state"], m = step(holder["state"], batch)
                return m

            t = benchmark(run, warmup=2, iters=args.iters)
            ips = B / t["mean_s"]
            throughputs.append(ips)
            print(json.dumps({
                "config": "dummy" if dummy else "xla",
                "devices": n,
                "samples_per_sec": round(ips, 1),
                "per_chip": round(ips / n, 1),
            }), flush=True)
        effs = scaling_efficiency(throughputs, sizes)
        key = "dummy" if dummy else "xla"
        results[key] = {
            "samples_per_sec": [round(t, 1) for t in throughputs],
            "scaling_efficiency": [round(e, 3) for e in effs],
        }
        print(json.dumps({
            "config": key,
            "scaling_efficiency": [round(e, 3) for e in effs],
            "sizes": sizes,
        }), flush=True)
    # Communication-cost attribution: 1 - xla/dummy at each size (the
    # DummyCommunicator ablation is the reference's stated tool for this).
    overhead = [
        round(100.0 * (1.0 - a / b), 1) if b else 0.0
        for a, b in zip(
            results["xla"]["samples_per_sec"], results["dummy"]["samples_per_sec"]
        )
    ]
    results["comm_overhead_pct"] = overhead
    print(json.dumps({"comm_overhead_pct": overhead, "sizes": sizes}), flush=True)
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(results, args.out)
    return results


if __name__ == "__main__":
    main()
