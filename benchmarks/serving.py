"""Serving throughput: static batching vs continuous batching.

Mixed-length traffic is where static batching loses: ``lm_generate`` pads
every member of a batch to the longest prompt and decodes until the
longest ``max_new_tokens``, so short requests burn chip time generating
tokens nobody asked for, and the whole batch holds its slots until the
straggler finishes.  The continuous-batching arm streams the same
requests through the fixed-shape paged-KV engine
(``chainermn_tpu/serving``): a slot is recycled the moment its request
completes, so the device only ever decodes requested tokens.

Traffic model: open-loop Poisson arrivals; prompt lengths and
``max_new_tokens`` drawn per request from ranges wide enough that a
static batch's padded work is a multi-x of the useful work.  Both arms
see the identical request list and arrival times.  Reported tokens/sec
counts USEFUL tokens only (each request's own ``max_new_tokens``) over
the arm's makespan; per-token latency is a request's
(completion - arrival) / generated tokens, reported at p50/p95.

The static arm's wall clock is assembled from real measured batch service
times on a simulated arrival clock (batch i starts when its last member
has arrived and batch i-1 is done) — the same idle-skipping semantics the
scheduler's clock gives the continuous arm, so neither arm pays
real-world sleeps.

An observability A/B (short drain-mode passes, alternating the
serve.*/SLO/timeline stack off and on, median of per-pair ratios)
proves the ISSUE 6 overhead contract (< 1% tokens/s), and
``--trace-out`` exports the obs-on traffic pass's request timeline as
Perfetto-loadable Chrome trace JSON.

Two further arms ride the same alternating-pair methodology (ISSUE 7):

* ``--prefix-reuse N`` — Zipf-distributed shared-prefix traffic (N
  prompt templates, popularity ~ 1/rank^a: the system-prompt /
  few-shot-template regime) through a sharing engine vs an identical
  engine with ``prefix_cache=False``; reports ``prefix_hit_rate`` and
  the useful-tokens/s ratio (prefill for a hot prefix is mapped, not
  recomputed).
* ``--spec-k K`` — speculative decoding A/B: the zero-tail distilled
  draft (same construction as ``benchmarks/decode.py --draft-mode
  distilled`` — realistic draft cost, near-ideal acceptance) lifted
  into the engine vs the plain engine on the same zero-tail target;
  reports per-slot acceptance and the tokens/s ratio.

``--disagg`` (ISSUE 14) runs the disaggregated prefill/decode arm: a
prefill-role + decode-role engine pair over the in-process KV-migration
plane vs a colocated engine under identical traffic — p95 clean-decode
latency, the ``serve.mixed_ms`` mass shifted off the decode role (it
must be zero there), and the migration cost envelope.

``--chaos`` (ISSUE 15) drives the failure plane: a 3-replica router
under a seeded randomized fault schedule (``crash@serve_step`` replica
deaths mid-stream, ``skew@serve_step`` fail-slow, ``drop@migrate``
recovery-frame loss) with dead replicas revived behind the probation
circuit breaker — reports the terminal-invariant verdict (every
submitted request terminates exactly once) and the ``serve.health.*``
counters (replica_dead / recovered / poisoned / shed).

``--tenants N`` (ISSUE 16) runs the multi-tenant metering arm: the same
traffic labeled across N tenants with Zipf-distributed popularity
through a router whose usage ledger is on — per-tenant tokens/s and
block-second shares, the top-consumer share, and the exact-conservation
verdict.

``--elastic`` (ISSUE 17) runs the elastic-fleet arm: diurnal traffic
(sinusoid-modulated Poisson with a mid-run burst window) through a
closed-loop autoscaled fleet (min 1 replica, scale-up behind probation,
scale-down via the zero-loss drain) vs the same traffic through a fleet
statically provisioned for the peak — reports p95 request latency both
ways, replica-seconds both ways (``replica_seconds_saved_pct`` is the
headline: capacity held only while needed), the flap count (must be 0),
and a mid-traffic rolling-deploy sub-arm whose ``rollout_zero_loss``
verdict pins zero lost / duplicated requests across a full fleet
replacement.

``--multitenant`` (ISSUE 19) runs the SLO-policy arm: a bursty
adversarial tenant dumps a 2x-capacity burst at t=0 with a
latency-sensitive tenant queued behind it, served twice over the same
warmed engine — plain FIFO, then through a ``PolicyPlane`` giving the
SLO tenant a 4:1 weighted-fair (VTC) share — reporting the SLO tenant's
p95 both ways, ``slo_tenant_p95_held`` (policy p95 within 1.1x of
FIFO's), and ``fairness_throughput_pct`` (policy aggregate tokens/s as
a percent of FIFO's; contract: >= 95 — fairness reorders work, it must
not destroy it).

    python benchmarks/serving.py --out result/serving_tpu.json  # real chip
    JAX_PLATFORMS=cpu python benchmarks/serving.py --smoke      # plumbing
"""

from __future__ import annotations

import argparse
import json
import time


def _pct(xs, q):
    xs = sorted(xs)
    if not xs:
        return None
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8,
                    help="static arm's batch size AND the engine's slot "
                         "capacity — same concurrency budget both arms")
    ap.add_argument("--prompt-min", type=int, default=16)
    ap.add_argument("--prompt-max", type=int, default=128)
    ap.add_argument("--new-min", type=int, default=8)
    ap.add_argument("--new-max", type=int, default=192)
    ap.add_argument("--len-sigma", type=float, default=1.4,
                    help="lognormal sigma for prompt/new lengths (0 = "
                         "uniform in [min, max]).  Serving traces are "
                         "heavy-tailed: most requests are short, a few "
                         "are long — exactly the regime where a static "
                         "batch pads everything to its straggler.  The "
                         "default matches trace studies (ShareGPT-style "
                         "output lengths are lognormal with sigma ~1-1.5 "
                         "in log space); sweep it to see the speedup "
                         "collapse toward 1x as traffic turns uniform")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="Poisson arrival rate, requests/sec (0 = derive "
                         "one that keeps the system busy: requests "
                         "arrive ~4x faster than the static arm serves)")
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--block-len", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=0,
                    help="physical KV blocks (0 = sized so the pool "
                         "covers ~batch x mean request length: real "
                         "contention, occasional eviction)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--decode-attention", default=None,
                    choices=("einsum", "fused"),
                    help="engine decode path: the paged Pallas kernel or "
                         "the gathered einsum fallback.  Default resolves "
                         "by platform — fused on TPU, einsum elsewhere "
                         "(off-TPU the Pallas kernels run in interpret "
                         "mode, never a perf win: the same policy as "
                         "ops.resolve_attention)")
    ap.add_argument("--kv-int8", action="store_true",
                    help="int8 KV pool + cache (both arms)")
    ap.add_argument("--repeats", type=int, default=2,
                    help="measure each arm this many times and keep the "
                         "least-contended (fastest) pass — both arms' "
                         "phases are seconds-long, so a background blip "
                         "on the host otherwise decides the comparison")
    ap.add_argument("--obs-pairs", type=int, default=0,
                    help="alternating obs-on/obs-off pass pairs for the "
                         "observability-overhead estimate (0 = same as "
                         "--repeats).  The stack's cost (~0.4% profiled) "
                         "sits below per-pass host noise (±2% even on "
                         "an idle shared host), so the median needs "
                         "several pairs to resolve the <1% contract")
    ap.add_argument("--prefix-reuse", type=int, default=0, metavar="N",
                    help="also run the Zipf shared-prefix arm: N prompt "
                         "templates drawn Zipf(--zipf-a), each request = "
                         "template + a short unique suffix; sharing "
                         "engine vs prefix_cache=False engine on "
                         "identical traffic, alternating drain pairs "
                         "(0 = skip)")
    ap.add_argument("--zipf-a", type=float, default=1.2,
                    help="Zipf exponent for template popularity")
    ap.add_argument("--spec-k", type=int, default=0, metavar="K",
                    help="also run the speculative A/B: K draft "
                         "proposals per round from the zero-tail "
                         "distilled draft (decode.py's construction) vs "
                         "the plain engine on the same zero-tail "
                         "target, alternating drain pairs (0 = skip)")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="distilled draft depth (default layers // 4, "
                         "min 1)")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="also run the multi-replica ROUTER arm: N "
                         "engines behind least-loaded dispatch under "
                         "the same Poisson traffic; reports aggregate "
                         "tokens/s, per-replica occupancy spread and "
                         "router dispatch latency (0 = skip)")
    ap.add_argument("--mesh-model", type=int, default=1, metavar="M",
                    help="shard EACH router-arm engine tensor-parallel "
                         "over M devices (a GSPMD mesh per replica — "
                         "N x M devices total, disjoint groups; 1 = "
                         "unsharded replicas).  Both decode paths work "
                         "sharded: --decode-attention fused runs the "
                         "Pallas kernels per shard under shard_map, "
                         "einsum the gathered GSPMD fallback.  M > 1 "
                         "also runs the SHARDED-DECODE A/B arm: one "
                         "M-way engine per decode path on identical "
                         "steady-state full-capacity clean decode "
                         "steps, reporting per-step kernel-vs-einsum "
                         "time and the greedy-identity verdict")
    ap.add_argument("--disagg", action="store_true",
                    help="also run the DISAGGREGATED prefill/decode arm "
                         "(ISSUE 14): a prefill-role engine + a "
                         "decode-role engine over the in-process "
                         "migration plane vs a colocated engine under "
                         "identical Poisson traffic; reports p95 "
                         "clean-decode latency and the serve.mixed_ms "
                         "mass shifted off the decode role")
    ap.add_argument("--chaos", action="store_true",
                    help="also run the CHAOS arm (ISSUE 15): a "
                         "3-replica router under a seeded fault "
                         "schedule (crash/skew@serve_step + "
                         "drop@migrate) with probation revivals; "
                         "reports the terminal-invariant verdict and "
                         "the serve.health.* counters")
    ap.add_argument("--elastic", action="store_true",
                    help="also run the ELASTIC-FLEET arm (ISSUE 17): "
                         "diurnal sinusoid+burst traffic through a "
                         "closed-loop autoscaled fleet vs a peak-"
                         "provisioned static fleet (p95 both ways, "
                         "replica-seconds saved, flap count) plus a "
                         "mid-traffic rolling-deploy sub-arm "
                         "(rollout_zero_loss verdict)")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="also run the MULTI-TENANT metering arm "
                         "(ISSUE 16): the same traffic shape with "
                         "requests labeled across N tenants "
                         "(Zipf(--zipf-a) popularity — a few tenants "
                         "dominate, the realistic skew) through a "
                         "router with the usage ledger on; reports "
                         "per-tenant tokens/s and block-second shares, "
                         "the top-consumer share, and the conservation "
                         "verdict (0 = skip)")
    ap.add_argument("--multitenant", action="store_true",
                    help="also run the SLO-POLICY arm (ISSUE 19): a "
                         "bursty adversarial tenant's 2x-capacity "
                         "burst with a latency-sensitive tenant queued "
                         "behind it, served FIFO then through the "
                         "PolicyPlane (4:1 VTC weights) — reports "
                         "slo_tenant_p95_held and "
                         "fairness_throughput_pct (contract: >= 95)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--trace-out", default=None,
                    help="also export the obs-on arm's request timeline "
                         "as Chrome trace-event JSON (Perfetto-loadable)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models import TransformerLM, lm_generate
    from chainermn_tpu.serving import DecodeEngine, Request, Scheduler

    platform = jax.devices()[0].platform
    if args.decode_attention is None:
        args.decode_attention = "fused" if platform == "tpu" else "einsum"
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"serving bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        # Small enough to finish in a couple of minutes on CPU, big
        # enough that a decode step's compute amortizes the engine's
        # per-step host dispatch (a 128-wide toy model measures dispatch,
        # not serving) and that the drain tail — the last long request
        # finishing alone — doesn't dominate the makespan.  Explicitly
        # passed flags win over these smoke defaults.
        # repeats=4: on a small shared-CPU host both arms' phases sit
        # inside the noise floor of background load — min-of-4 passes is
        # the cheapest way to recover the uncontended service times the
        # comparison is about (on-chip runs keep the default).
        smoke_over = dict(
            requests=48, batch=8, prompt_min=8, prompt_max=48,
            new_min=4, new_max=64, layers=4, d_model=512, heads=8,
            d_ff=1024, vocab=4096, block_len=8, prefill_chunk=16,
            repeats=4, obs_pairs=12, prefix_reuse=4, spec_k=3,
            draft_layers=1, replicas=2, disagg=True, chaos=True,
            tenants=3, elastic=True, multitenant=True,
        )
        for k, v in smoke_over.items():
            if getattr(args, k) == ap.get_default(k):
                setattr(args, k, v)
    # NOTE: async CPU dispatch stays ON (the jax default).  Both arms'
    # timings sync on actual value readbacks — the static arm
    # materializes its scan output, the engine reads every step's sampled
    # tokens — so async cannot inflate either number.  Disabling it (as
    # the training benches do for step-time stability) would serialize
    # the engine's ~4 small control-vector uploads per decode step behind
    # each other, a pure dispatch-latency tax on the continuous arm that
    # the static arm's single-dispatch lax.scan never pays.

    rng = np.random.RandomState(args.seed)

    def draw_lens(lo, hi, n):
        if not args.len_sigma:
            return rng.randint(lo, hi + 1, size=n)
        # Clipped lognormal with the median at the low quartile of the
        # range: a realistic length mix (mostly short, occasional long).
        med = max(lo, (lo + hi) // 8)
        return np.clip(
            np.round(np.exp(rng.normal(np.log(med), args.len_sigma,
                                       size=n))),
            lo, hi,
        ).astype(int)

    plens = draw_lens(args.prompt_min, args.prompt_max, args.requests)
    prompts = [
        rng.randint(1, args.vocab, size=int(n)).astype(np.int32)
        for n in plens
    ]
    new_counts = draw_lens(args.new_min, args.new_max, args.requests)
    max_total = args.prompt_max + int(new_counts.max()) + args.prefill_chunk

    model = TransformerLM(
        vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
        n_heads=args.heads, d_ff=args.d_ff, max_len=max_total,
        pos_enc="rope", n_kv_heads=args.kv_heads,
        kv_dtype=jnp.int8 if args.kv_int8 else None,
        decode_attention=args.decode_attention,
    )
    params = jax.jit(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))
    )(jax.random.PRNGKey(0))["params"]

    useful_tokens = int(new_counts.sum())

    # ------------------------------------------------------- static arm
    # Batches in arrival order; every batch padded to its longest prompt
    # (right-padding + prompt_lengths gives lm_generate ragged semantics)
    # and decoded to its longest max_new.  One compiled program per
    # (prompt_pad, n_new) geometry — rounding the pad up to the prefill
    # chunk bounds the variant count, exactly as real static servers
    # bucket shapes.
    def pad_to(n, q):
        return int(-(-n // q) * q)

    order = list(range(args.requests))
    batches = [order[i:i + args.batch]
               for i in range(0, args.requests, args.batch)]
    gen = jax.jit(
        lambda p, pr, lens, n_new: lm_generate(
            model, p, pr, n_new, prompt_lengths=lens
        ),
        static_argnums=(3,),
    )
    # Warm every geometry first so the timed loop measures steady-state
    # service, not compiles (a long-lived server's regime).
    geoms = set()
    for b in batches:
        pp = pad_to(max(len(prompts[i]) for i in b), args.prefill_chunk)
        nn = int(max(new_counts[i] for i in b))
        geoms.add((pp, nn))
    for pp, nn in sorted(geoms):
        pr = jnp.zeros((args.batch, pp), jnp.int32)
        lens = jnp.ones((args.batch,), jnp.int32)
        np.asarray(gen(params, pr, lens, nn)[:1, -1:])

    repeats = max(1, args.repeats)
    service = [float("inf")] * len(batches)
    static_tokens = {}
    for _ in range(repeats):
        for bi, b in enumerate(batches):
            pp = pad_to(
                max(len(prompts[i]) for i in b), args.prefill_chunk
            )
            nn = int(max(new_counts[i] for i in b))
            pr = np.zeros((args.batch, pp), np.int32)
            lens = np.zeros((args.batch,), np.int32)
            for row, i in enumerate(b):
                pr[row, :len(prompts[i])] = prompts[i]
                lens[row] = len(prompts[i])
            lens = np.maximum(lens, 1)  # tail batch's empty rows
            t0 = time.perf_counter()
            out = gen(params, jnp.asarray(pr), jnp.asarray(lens), nn)
            out = np.asarray(out)
            service[bi] = min(
                service[bi], time.perf_counter() - t0
            )
            for row, i in enumerate(b):
                static_tokens[i] = out[row, :new_counts[i]].tolist()

    # Arrival schedule shared by both arms.  Default rate: fast enough
    # that the queue never starves (throughput measures the server, not
    # the arrival process).
    static_service = sum(service)
    rate = args.rate or (4.0 * args.requests / max(static_service, 1e-9))
    gaps = rng.exponential(1.0 / rate, size=args.requests)
    arrivals = np.cumsum(gaps)

    # Simulated static makespan on the shared arrival clock.
    t = 0.0
    static_lat = []
    done_at = {}
    for b, dt in zip(batches, service):
        t = max(t, float(arrivals[b[-1]])) + dt
        for i in b:
            done_at[i] = t
    static_makespan = max(done_at.values()) - float(arrivals.min())
    for i in range(args.requests):
        static_lat.append(
            (done_at[i] - float(arrivals[i])) / int(new_counts[i])
        )
    static_tps = useful_tokens / static_makespan

    # --------------------------------------------------- continuous arm
    # Pool sized to the DRAWN traffic (p85 of total request length), not
    # the range midpoint — lognormal draws sit far below the midpoint, and
    # a pool sized to the midpoint is several x the working set, silently
    # skipping the eviction/backpressure path this benchmark claims to
    # exercise.  p85 is the provisioning a real server would pick: tail
    # draws above it still force occasional evictions (reported in the
    # payload), while a mean-sized pool thrashes — every above-mean slot
    # evicts and recomputes, and the benchmark measures recompute waste
    # instead of steady-state serving.
    p85 = float(np.percentile(plens + new_counts, 85))
    num_blocks = args.num_blocks or (
        1 + args.batch * (1 + int(p85) // args.block_len + 1)
    )
    # Block tables sized to the drawn traffic's LONGEST request (padded to
    # the prefill chunk), not the model's max_len: the einsum fallback
    # gathers the full table width every step, so table slack is pure
    # masked compute in the hot loop.  A real deployment knows its length
    # cap the same way.
    from chainermn_tpu.serving.kv_pool import blocks_for

    longest = int((plens + new_counts).max())
    padded_longest = pad_to(longest, args.prefill_chunk)
    eng = DecodeEngine(
        model, params, capacity=args.batch, num_blocks=num_blocks,
        block_len=args.block_len, prefill_chunk=args.prefill_chunk,
        max_blocks_per_slot=blocks_for(padded_longest, args.block_len),
    )
    reqs = [
        Request(id=i, prompt=prompts[i].tolist(),
                max_new_tokens=int(new_counts[i]),
                arrival=float(arrivals[i]))
        for i in range(args.requests)
    ]
    # Warm the engine programs off the clock (same steady-state policy
    # as the static arm) — one request per prefill-ladder geometry plus
    # the decode step — then run the measured traffic, keeping the
    # least-contended of `repeats` passes, mirroring the static arm.
    warm_eng = Scheduler(eng)
    warm_eng.run([
        Request(id=-(i + 1), prompt=[1] * c, max_new_tokens=2)
        for i, c in enumerate(eng.prefill_ladder)
    ])

    # Headline continuous arm — observability ON, the shipped default
    # (serve.* metrics, SLO monitor, request timeline, flight provider).
    from chainermn_tpu import observability as obs

    obs.set_enabled(True)
    try:
        comps, sched_on, cont_makespan = None, None, float("inf")
        for _ in range(repeats):
            # Cold prefix cache every pass: this arm's headline is
            # continuous-vs-static batching, and this traffic draws
            # unique prompts anyway — a pass re-serving the previous
            # pass's cached prefills would measure the cache, not the
            # scheduler (the --prefix-reuse arm measures the cache).
            eng.drop_prefix_cache()
            sched = Scheduler(eng)
            cs = sched.run(reqs)
            span = (
                max(c.finished_at for c in cs)
                - min(c.arrival for c in cs)
            )
            if span < cont_makespan:
                comps, sched_on, cont_makespan = cs, sched, span
    finally:
        obs.set_enabled(None)
    cont_tps = useful_tokens / cont_makespan
    if args.trace_out:
        sched_on.export_trace(args.trace_out)
        print(f"# chrome trace -> {args.trace_out} "
              f"(load at ui.perfetto.dev)", flush=True)

    # Observability-overhead A/B (ISSUE 6 <1% contract).  Deliberately
    # NOT measured on the traffic simulation above: its seconds-long
    # passes are long enough that one background-contention burst on a
    # shared host lands a whole pass ±10% — far above the stack's
    # profiled self-time (<0.5%).  Instead: short DRAIN-mode passes
    # (every request available at t=0, so the arrival process adds no
    # variance), alternating obs-off/obs-on within each pair (the
    # scheduler latches the switch at construction), overhead = median
    # of per-pair makespan ratios — a spike contaminates one short pair,
    # and the median stays in the clean bulk.  The compiled programs are
    # shared and identical across arms; only host-side instrumentation
    # differs.
    ab_n = min(16, args.requests)
    ab_reqs = [
        Request(id=10_000 + i, prompt=prompts[i].tolist(),
                max_new_tokens=min(int(new_counts[i]), 24))
        for i in range(ab_n)
    ]
    ab_useful = sum(r.max_new_tokens for r in ab_reqs)
    pair_ratios = []
    # decode_compiles is cumulative across arms; attribute any recompile
    # to the arm whose pass raised it (delta per pass), so a regression
    # indicts the right arm.  1 = the shared warm-time compile.
    recompiles = {False: 0, True: 0}
    ab_best = {False: float("inf"), True: float("inf")}
    for rep in range(args.obs_pairs or repeats):
        spans = {}
        # Swap pair order every repeat so neither arm systematically
        # runs into a fresher (or staler) cache/contention state.
        for on in ((False, True) if rep % 2 == 0 else (True, False)):
            obs.set_enabled(on)
            before = eng.decode_compiles
            # Cold cache per pass: within a pair, the second arm would
            # otherwise re-serve the first's cached prefills — a
            # systematic bias toward whichever runs second.
            eng.drop_prefix_cache()
            try:
                cs = Scheduler(eng).run(ab_reqs)
            finally:
                obs.set_enabled(None)
            recompiles[on] += eng.decode_compiles - before
            spans[on] = max(c.finished_at for c in cs)
            ab_best[on] = min(ab_best[on], spans[on])
        pair_ratios.append(spans[True] / spans[False] - 1.0)
    compiles = {arm: 1 + recompiles[arm] for arm in (False, True)}
    rs = sorted(pair_ratios)
    mid = len(rs) // 2
    obs_overhead_pct = 100.0 * (
        rs[mid] if len(rs) % 2 else (rs[mid - 1] + rs[mid]) / 2.0
    )
    cont_lat = [
        (c.finished_at - c.arrival) / len(c.tokens) for c in comps
    ]
    evictions = sum(c.evictions for c in comps)

    # Greedy equivalence spot-check: the continuous arm must produce the
    # static arm's tokens request for request, or the speedup compares
    # different functions.  Exact in fp32 (pinned by the serving oracle
    # tests); under bf16 the gathered/paged attention and the contiguous
    # einsum are different XLA kernels whose logits differ in the last
    # bits, so a near-argmax-tie can flip and everything after diverges —
    # report the divergence structure (a logic bug diverges at step ~0 on
    # every request) exactly as benchmarks/decode.py does for its arms.
    per_req = []
    for c in comps:
        want = static_tokens[c.id]
        mm = [i for i, (a, b) in enumerate(zip(c.tokens, want)) if a != b]
        per_req.append((c.id, mm[0] if mm else None))
    diverged = [(i, f) for i, f in per_req if f is not None]
    agreement = {
        "requests_exact": len(per_req) - len(diverged),
        "requests": len(per_req),
        "min_first_divergence": min(
            (f for _, f in diverged), default=None
        ),
        "diverged_request_ids": [i for i, _ in diverged][:8],
    }

    def median(xs):
        xs = sorted(xs)
        mid = len(xs) // 2
        return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2.0

    def warm_engine(e):
        """Compile an engine's whole ladder + its decode/spec step off
        the clock, then drop whatever the warm prompts cached."""
        Scheduler(e).run([
            Request(id=-(i + 1), prompt=[1] * c, max_new_tokens=2)
            for i, c in enumerate(e.prefill_ladder)
        ])
        e.drop_prefix_cache()

    # ---------------------------------------------- prefix-sharing arm
    # Zipf-distributed shared-prefix traffic (ROADMAP item 2's ground
    # truth): N templates, popularity ~ 1/rank^a — the system-prompt /
    # few-shot regime real traffic is dominated by.  Sharing engine vs
    # an identical prefix_cache=False engine on IDENTICAL traffic,
    # alternating drain-mode pass pairs (the PR-6 methodology: short
    # passes, a contention burst contaminates one pair, the median
    # stays in the clean bulk).  The sharing engine keeps its trie warm
    # across passes — a long-lived server's steady state IS the
    # treatment being measured; only host noise is paired away.
    prefix_payload = None
    if args.prefix_reuse:
        n_tpl = args.prefix_reuse
        tpl_lens = rng.randint(
            max(args.prompt_min, (3 * args.prompt_max) // 4),
            args.prompt_max + 1, size=n_tpl,
        )
        templates = [
            rng.randint(1, args.vocab, size=int(n)).astype(np.int32)
            for n in tpl_lens
        ]
        ranks = np.arange(1, n_tpl + 1, dtype=np.float64)
        pz = ranks ** -args.zipf_a
        pz /= pz.sum()
        n_px = max(24, min(args.requests, 48))
        choice = rng.choice(n_tpl, size=n_px, p=pz)
        suffix = max(2, args.prompt_min // 2)
        px_new = max(4, args.new_min)
        px_prompts = [
            np.concatenate([
                templates[c],
                rng.randint(1, args.vocab, size=suffix).astype(np.int32),
            ]).tolist()
            for c in choice
        ]
        px_reqs = [
            Request(id=20_000 + i, prompt=p, max_new_tokens=px_new)
            for i, p in enumerate(px_prompts)
        ]
        px_useful = n_px * px_new
        longest_px = max(len(p) for p in px_prompts) + px_new
        px_mbs = blocks_for(
            pad_to(longest_px + args.spec_k, args.prefill_chunk),
            args.block_len,
        )
        # Pool: templates stay resident (the trie) + a full-capacity
        # working set — contention is not this arm's subject.
        px_blocks = 1 + int(sum(
            blocks_for(int(n), args.block_len) for n in tpl_lens
        )) + args.batch * (px_mbs + 1)
        px_eng = {}
        for share in (False, True):
            px_eng[share] = DecodeEngine(
                model, params, capacity=args.batch,
                num_blocks=px_blocks, block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=px_mbs, prefix_cache=share,
            )
            warm_engine(px_eng[share])
        px_ratios = []
        px_best = {False: float("inf"), True: float("inf")}
        px_sched = None
        for rep in range(args.obs_pairs or repeats):
            spans = {}
            for share in (
                (False, True) if rep % 2 == 0 else (True, False)
            ):
                sched = Scheduler(px_eng[share])  # fresh per pass
                cs = sched.run(px_reqs)
                spans[share] = max(c.finished_at for c in cs)
                px_best[share] = min(px_best[share], spans[share])
                if share:
                    px_sched = sched
            px_ratios.append(spans[False] / spans[True])
        hit_rate = (
            px_sched.prefix_hit_tokens
            / max(px_sched.prefix_lookup_tokens, 1)
        )
        prefix_payload = {
            "templates": n_tpl,
            "zipf_a": args.zipf_a,
            "requests": n_px,
            "template_len": [int(tpl_lens.min()), int(tpl_lens.max())],
            "suffix_len": suffix,
            "max_new": px_new,
            # Steady-state (warm-trie) hit rate of the last sharing
            # pass: matched prompt tokens / looked-up prompt tokens.
            "prefix_hit_rate": round(hit_rate, 4),
            "tokens_per_sec_sharing": round(px_useful / px_best[True], 1),
            "tokens_per_sec_no_sharing": round(
                px_useful / px_best[False], 1
            ),
            # Median of paired no-sharing/sharing makespan ratios
            # (> 1 = sharing wins).
            "speedup_vs_no_sharing": round(median(px_ratios), 3),
            "pair_ratios": [round(r, 3) for r in px_ratios],
            "cached_blocks": px_eng[True].prefix.cached_blocks,
            "cow_compiles": px_eng[True].cow_compiles,
            "decode_compiles_sharing": px_eng[True].decode_compiles,
        }
        del px_eng  # drop both engines' device pools

    # ------------------------------------------------ speculative arm
    # Zero-tail distilled draft (benchmarks/decode.py --draft-mode
    # distilled): the target's blocks past `dl` become exact identities
    # (proj/ff2 zeroed), so its function collapses to its first dl
    # blocks at full honest cost — and those blocks + head ARE the
    # draft.  Realistic draft cost, near-ideal acceptance: the measured
    # bound a perfectly distilled draft reaches.  Spec engine vs plain
    # engine on the SAME zero-tail target, alternating drain pairs.
    spec_payload = None
    if args.spec_k:
        from chainermn_tpu.models import TransformerLM as _LM

        dl = args.draft_layers or max(1, args.layers // 4)
        zparams = dict(params)
        for i in range(dl, args.layers):
            blk = dict(zparams[f"block_{i}"])
            for nm in ("proj", "ff2"):
                blk[nm] = jax.tree.map(jnp.zeros_like, blk[nm])
            zparams[f"block_{i}"] = blk
        draft = _LM(
            vocab=args.vocab, n_layers=dl, d_model=args.d_model,
            n_heads=args.heads, d_ff=args.d_ff, max_len=max_total,
            pos_enc="rope", n_kv_heads=args.kv_heads,
            kv_dtype=jnp.int8 if args.kv_int8 else None,
            decode_attention=args.decode_attention,
        )
        dparams = {
            f"block_{i}": zparams[f"block_{i}"] for i in range(dl)
        }
        for nm in ("embed", "ln_f", "lm_head"):
            dparams[nm] = zparams[nm]
        # Decode-dominated drain traffic: short prompts, generous
        # budgets — speculation's win is sequential-step count.
        n_sp = max(12, min(args.requests, 24))
        sp_new = max(12, min(args.new_max, 24))
        sp_prompts = [
            rng.randint(
                1, args.vocab,
                size=int(rng.randint(args.prompt_min,
                                     max(args.prompt_min + 1, 17))),
            ).astype(np.int32).tolist()
            for _ in range(n_sp)
        ]
        longest_sp = max(len(p) for p in sp_prompts) + sp_new
        sp_mbs = blocks_for(
            pad_to(longest_sp + args.spec_k, args.prefill_chunk),
            args.block_len,
        )
        sp_blocks = 1 + args.batch * (sp_mbs + 1)
        sp_eng = {}
        for spec in (False, True):
            kw = dict(
                capacity=args.batch, num_blocks=sp_blocks,
                block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=sp_mbs,
            )
            if spec:
                kw.update(draft_model=draft, draft_params=dparams,
                          spec_k=args.spec_k)
            sp_eng[spec] = DecodeEngine(model, zparams, **kw)
            warm_engine(sp_eng[spec])
        sp_reqs = [
            Request(id=30_000 + i, prompt=p, max_new_tokens=sp_new)
            for i, p in enumerate(sp_prompts)
        ]
        sp_useful = n_sp * sp_new
        sp_ratios = []
        sp_best = {False: float("inf"), True: float("inf")}
        sp_tokens = {}
        accept, per_req_min = None, None
        for rep in range(args.obs_pairs or repeats):
            spans = {}
            for spec in (
                (False, True) if rep % 2 == 0 else (True, False)
            ):
                sp_eng[spec].drop_prefix_cache()
                sched = Scheduler(sp_eng[spec])  # fresh per pass
                cs = sched.run(sp_reqs)
                spans[spec] = max(c.finished_at for c in cs)
                sp_best[spec] = min(sp_best[spec], spans[spec])
                sp_tokens[spec] = {c.id: c.tokens for c in cs}
                if spec:
                    accept = (
                        sched.spec_accepted / max(sched.spec_proposed, 1)
                    )
                    per_req_min = min(
                        c.spec_accepted / max(c.spec_proposed, 1)
                        for c in cs
                    )
            sp_ratios.append(spans[False] / spans[True])
        # Greedy identity across arms (same zero-tail target): exact in
        # fp32; bf16 near-argmax ties can flip between the 1-token step
        # and the (k+1)-position verify kernel — report structure.
        mism = []
        for rid in sp_tokens[True]:
            a, b = sp_tokens[True][rid], sp_tokens[False][rid]
            first = next(
                (i for i, (x, y) in enumerate(zip(a, b)) if x != y), None
            )
            if first is not None:
                mism.append(first)
        spec_payload = {
            "k": args.spec_k,
            "draft_layers": dl,
            "target_layers": args.layers,
            "draft": "zero-tail distillation (realistic draft cost, "
                     "near-ideal acceptance)",
            "requests": n_sp,
            "max_new": sp_new,
            # Aggregate and worst per-request greedy acceptance from the
            # last speculative pass.
            "accept_rate": round(accept, 4),
            "accept_rate_per_request_min": round(per_req_min, 4),
            "tokens_per_sec_spec": round(sp_useful / sp_best[True], 1),
            "tokens_per_sec_plain": round(sp_useful / sp_best[False], 1),
            "speedup_vs_plain": round(median(sp_ratios), 3),
            "pair_ratios": [round(r, 3) for r in sp_ratios],
            "decode_compiles_spec": sp_eng[True].decode_compiles,
            "verify_compiles": sp_eng[True].verify_compiles,
            "greedy_agreement_vs_plain": {
                "requests_exact": n_sp - len(mism),
                "requests": n_sp,
                "min_first_divergence": min(mism) if mism else None,
            },
        }
        del sp_eng

    # --------------------------------------------------- router arm
    # N engines x M chips behind least-loaded dispatch (ISSUE 13): the
    # same Poisson request stream through a Router over N fresh engines
    # (each optionally sharded tensor-parallel over its own M-device
    # mesh group).  The single-engine continuous arm above is the
    # baseline: aggregate tokens/s should scale with N once the single
    # engine saturates, and the occupancy spread shows the dispatch
    # policy keeping the replicas even.  Router cost itself is
    # host-side only — dispatch latency is reported so its budget is
    # visible.
    router_payload = None
    if args.replicas:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import Router
        from chainermn_tpu.serving.sharding import serving_mesh

        N, M = args.replicas, max(1, args.mesh_model)
        devs = jax.devices()
        # Both decode paths run sharded since the shard_map port: the
        # engine wires the mesh into the fused kernels' dispatch, so
        # --decode-attention is honored as-is on the mesh path.
        rt_model = model
        if M > 1 and len(devs) < N * M:
            print(f"# router arm: {N}x{M} devices requested, "
                  f"{len(devs)} available — shrinking mesh to 1",
                  flush=True)
            M = 1
        meshes = [
            serving_mesh(M, devices=devs[i * M:(i + 1) * M])
            if M > 1 else None
            for i in range(N)
        ]
        rt_engines = []
        for i in range(N):
            e = DecodeEngine(
                rt_model, params, capacity=args.batch,
                num_blocks=num_blocks, block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=blocks_for(
                    padded_longest, args.block_len
                ),
                mesh=meshes[i],
                # Unsharded replicas still get their own chip when the
                # host has one to give — N engines piled on the default
                # device would measure single-chip contention, not
                # replica scaling.
                device=(
                    devs[i] if meshes[i] is None and len(devs) >= N
                    else None
                ),
            )
            warm_engine(e)
            rt_engines.append(e)
        rt_best, rt_router, rt_reg = float("inf"), None, None
        for _ in range(repeats):
            for e in rt_engines:
                e.drop_prefix_cache()
            # Fresh registry per pass: the dispatched/migrated/
            # backpressure counters below must describe the BEST run,
            # not accumulate across every repeat.
            reg = MetricsRegistry()
            router = Router(rt_engines, registry=reg)
            rcs = router.run([
                Request(id=40_000 + i, prompt=prompts[i].tolist(),
                        max_new_tokens=int(new_counts[i]),
                        arrival=float(arrivals[i]))
                for i in range(args.requests)
            ])
            span = (
                max(c.finished_at for c in rcs)
                - min(c.arrival for c in rcs)
            )
            if span < rt_best:
                rt_best, rt_router, rt_reg = span, router, reg
        rstats = rt_router.replica_stats()
        occs = [s["occupancy_mean"] for s in rstats]
        dms = sorted(rt_router.dispatch_ms)
        router_payload = {
            "replicas": N,
            "mesh_model": M,
            "decode_attention": rt_model.decode_attention,
            "aggregate_tokens_per_sec": round(useful_tokens / rt_best, 1),
            "makespan_s": round(rt_best, 3),
            "speedup_vs_single_engine": round(cont_makespan / rt_best, 3),
            "per_replica_occupancy_mean": [round(o, 4) for o in occs],
            "occupancy_spread": round(max(occs) - min(occs), 4),
            "dispatch_ms_p50": round(_pct(dms, 0.5), 4) if dms else None,
            "dispatch_ms_p95": round(_pct(dms, 0.95), 4) if dms else None,
            "dispatched": rt_reg.peek("serve.router.dispatched").value,
            "migrated": rt_reg.peek("serve.router.migrated").value,
            "backpressure_deferrals": rt_reg.peek(
                "serve.router.backpressure"
            ).value,
            "per_replica_served": [s["served"] for s in rstats],
            "decode_compiles": [
                s["engine"]["decode_compiles"] for s in rstats
            ],
        }
        del rt_engines, rt_router

    # ------------------------------------------- sharded-decode A/B arm
    # The shard_map kernel port's ground truth (ISSUE 20): one M-way
    # tensor-parallel engine per decode path — "fused" (Pallas paged
    # kernel per shard under shard_map) vs "einsum" (the gathered GSPMD
    # fallback) — on IDENTICAL steady-state full-capacity clean decode
    # steps.  The per-step comparison is the honest one: the einsum
    # path gathers and scores every slot's FULL padded table width each
    # step, while the paged kernel streams each pool byte once at
    # storage width and walks only the blocks a slot has actually
    # filled (the block-skip recurrence) — the PagedAttention claim,
    # now held under sharding.  A small greedy drain on both engines
    # doubles as the token-identity verdict.
    #
    # CPU caveat (measured, not assumed): off-TPU the Pallas kernels
    # run in Pallas INTERPRET mode, whose per-grid-program emulation
    # overhead is orders of magnitude above the kernel's real cost —
    # the same reason the bench's --decode-attention default resolves
    # to einsum off-TPU ("never a perf win").  The CPU arm therefore
    # validates the comparison's PLUMBING (identical tokens, one
    # compile, both paths timed per step on a real multi-device mesh)
    # and flags itself ``interpret``; the speedup >= 1 claim is the
    # on-chip capture's, behind the standing TPU-probe note.
    sharded_payload = None
    if args.mesh_model > 1:
        from chainermn_tpu.serving.sharding import serving_mesh

        M = args.mesh_model
        devs = jax.devices()
        kvh = args.kv_heads or args.heads
        if len(devs) < M or kvh % M:
            print(f"# sharded-decode arm skipped: need {M} devices "
                  f"(have {len(devs)}) and kv heads ({kvh}) divisible "
                  f"by the mesh", flush=True)
        else:
            sd_mesh = serving_mesh(M, devices=devs[:M])
            S = args.batch
            MB = blocks_for(padded_longest, args.block_len)
            sd_blocks = max(num_blocks, 2 + S * MB)
            # Steady-state slot lengths: the drawn traffic's own mix
            # (prompt + generated so far), capped to the table width —
            # the regime a long-lived server decodes in.
            totals = (plens + new_counts)[:S]
            sd_pos = np.minimum(
                totals, MB * args.block_len - 1
            ).astype(np.int32)
            sd_tokens = np.random.RandomState(args.seed + 7).randint(
                1, args.vocab, size=S
            ).astype(np.int32)
            sd_tables = np.zeros((S, MB), np.int32)
            nxt = 1
            for s in range(S):
                need = 1 + int(sd_pos[s]) // args.block_len
                for m in range(need):
                    sd_tables[s, m] = nxt
                    nxt += 1
            sd_active = np.ones(S, bool)
            sd_steps = 12
            step_ms = {}
            sd_tok = {}
            sd_compiles = {}
            for attn in ("fused", "einsum"):
                e = DecodeEngine(
                    model.clone(decode_attention=attn), params,
                    capacity=S, num_blocks=sd_blocks,
                    block_len=args.block_len,
                    prefill_chunk=args.prefill_chunk,
                    max_blocks_per_slot=MB, mesh=sd_mesh,
                )
                # Greedy-identity drain (also compiles the ladder).
                cs = Scheduler(e).run([
                    Request(id=50_000 + i, prompt=prompts[i].tolist(),
                            max_new_tokens=8)
                    for i in range(min(6, args.requests))
                ])
                sd_tok[attn] = {c.id: list(c.tokens) for c in cs}
                # Clean steady-state steps: same control vectors both
                # paths, shapes fixed by construction (no recompiles).
                np.asarray(e.step(sd_tokens, sd_pos, sd_tables,
                                  sd_active))  # warm
                best = float("inf")
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    for _ in range(sd_steps):
                        out = e.step(sd_tokens, sd_pos, sd_tables,
                                     sd_active)
                    np.asarray(out)
                    best = min(best, time.perf_counter() - t0)
                step_ms[attn] = 1e3 * best / sd_steps
                sd_compiles[attn] = e.decode_compiles
                del e
            # Agreement structure, same shape as the headline arm's:
            # the kernel and einsum reductions are numerically
            # different programs, so at real model widths greedy argmax
            # ties may break differently mid-sequence — exact-request
            # counts tell that story honestly (the BIT-identity claim
            # is the ops-level sharded-vs-unsharded KERNEL pin, and the
            # tier-1 engine battery holds full fused-vs-einsum token
            # identity at its geometry).
            sd_exact = sum(
                sd_tok["fused"][i] == sd_tok["einsum"][i]
                for i in sd_tok["fused"]
            )
            sd_divs = [
                next((k for k, (a, b)
                      in enumerate(zip(sd_tok["fused"][i],
                                       sd_tok["einsum"][i]))
                      if a != b),
                     min(len(sd_tok["fused"][i]),
                         len(sd_tok["einsum"][i])))
                for i in sd_tok["fused"]
                if sd_tok["fused"][i] != sd_tok["einsum"][i]
            ]
            sharded_payload = {
                "mesh_model": M,
                "capacity": S,
                "max_blocks_per_slot": MB,
                "steps": sd_steps,
                "kernel_step_ms": round(step_ms["fused"], 3),
                "einsum_step_ms": round(step_ms["einsum"], 3),
                "kernel_speedup_vs_einsum": round(
                    step_ms["einsum"] / step_ms["fused"], 3
                ),
                "greedy_agreement_vs_einsum": {
                    "requests_exact": sd_exact,
                    "requests": len(sd_tok["fused"]),
                    "min_first_divergence": (min(sd_divs) if sd_divs
                                             else None),
                },
                "decode_compiles": sd_compiles,
                # Off-TPU the kernel arm times the Pallas INTERPRET
                # emulator, not the kernel (see the arm comment) — the
                # speedup is only a chip claim when this is False.
                "interpret": platform != "tpu",
            }

    # ------------------------------------------------ disaggregated arm
    # Prefill/decode role split over the in-process migration plane
    # (ISSUE 14) vs a colocated engine on IDENTICAL Poisson traffic.
    # The headline is latency attribution, not throughput: the colocated
    # engine's decode iterations that absorb queued prefill dispatches
    # book to serve.mixed_ms (the PR-6 tag); the decode ROLE runs clean
    # decode steps only, so its mixed mass must be ZERO and its
    # serve.slo token p95 is the clean-decode p95 the SLO monitor
    # already computes.  Same alternating best-of-N discipline as the
    # other arms (fewer passes — two full traffic simulations each).
    disagg_payload = None
    if args.disagg:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import (
            DecodeRole,
            LocalComm,
            MigrationTransport,
            PrefillRole,
            serve_disaggregated,
        )
        from chainermn_tpu.serving.scheduler import _Clock

        def mk_engine():
            e = DecodeEngine(
                model, params, capacity=args.batch,
                num_blocks=num_blocks, block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=blocks_for(
                    padded_longest, args.block_len
                ),
            )
            warm_engine(e)
            return e

        co_eng, pf_eng, de_eng = mk_engine(), mk_engine(), mk_engine()
        dz_reqs = [
            Request(id=50_000 + i, prompt=prompts[i].tolist(),
                    max_new_tokens=int(new_counts[i]),
                    arrival=float(arrivals[i]))
            for i in range(args.requests)
        ]

        def hist(reg, name):
            inst = reg.peek(name)
            if inst is None:
                return {"count": 0, "sum": 0.0}
            d = inst.to_dict()
            return {"count": d["count"], "sum": round(d["sum"], 3)}

        dz_repeats = max(1, min(2, repeats))
        co_best = (float("inf"), None, None, None)
        dz_best = (float("inf"), None, None, None, None)
        for _ in range(dz_repeats):
            co_eng.drop_prefix_cache()
            reg_co = MetricsRegistry()
            sched = Scheduler(co_eng, registry=reg_co)
            cs = sched.run(dz_reqs)
            span = (
                max(c.finished_at for c in cs)
                - min(c.arrival for c in cs)
            )
            if span < co_best[0]:
                co_best = (span, reg_co, sched, cs)
            pf_eng.drop_prefix_cache()
            de_eng.drop_prefix_cache()
            clock = _Clock()
            comm = LocalComm(2)
            reg_p, reg_d = MetricsRegistry(), MetricsRegistry()
            pr = PrefillRole(
                Scheduler(pf_eng, registry=reg_p, clock=clock),
                MigrationTransport(comm.endpoint(0), registry=reg_p),
                decode_ranks=[1],
            )
            dr = DecodeRole(
                Scheduler(de_eng, registry=reg_d, clock=clock),
                MigrationTransport(comm.endpoint(1), registry=reg_d),
                prefill_ranks=[0],
            )
            cs2 = serve_disaggregated(pr, dr, dz_reqs)
            span2 = (
                max(c.finished_at for c in cs2)
                - min(c.arrival for c in cs2)
            )
            if span2 < dz_best[0]:
                dz_best = (span2, reg_p, reg_d, dr, cs2)
        co_span, reg_co, co_sched, co_cs = co_best
        dz_span, reg_p, reg_d, dr, dz_cs = dz_best

        def slo_token_p95(sched):
            rep = (sched.slo.last_report or {}).get("token", {})
            v = rep.get("p95_ms")
            return round(v, 3) if v is not None else None

        co_tokens = {c.id: c.tokens for c in co_cs}
        mism = []
        for c in dz_cs:
            want = co_tokens[c.id]
            first = next(
                (i for i, (a, b) in enumerate(zip(c.tokens, want))
                 if a != b), None,
            )
            if first is None and len(c.tokens) != len(want):
                # A truncated/overlong completion with an identical
                # common prefix is still a divergence (zip is
                # length-blind) — first difference is the shorter end.
                first = min(len(c.tokens), len(want))
            if first is not None:
                mism.append(first)
        mig_ms = reg_p.peek("serve.migration.migrate_ms").to_dict()
        disagg_payload = {
            "requests": args.requests,
            "tokens_per_sec_disagg": round(useful_tokens / dz_span, 1),
            "tokens_per_sec_colocated": round(useful_tokens / co_span, 1),
            "speedup_vs_colocated": round(co_span / dz_span, 3),
            # p95 of CLEAN decode iterations (the SLO monitor's token
            # stream) — the acceptance headline.
            "clean_decode_p95_ms": slo_token_p95(dr.sched),
            "colocated_clean_decode_p95_ms": slo_token_p95(co_sched),
            # The steal, measured: mixed-iteration mass per arm.  The
            # decode role's must be zero — prefill interference now
            # lives on the prefill rank.
            "mixed_colocated": hist(reg_co, "serve.mixed_ms"),
            "mixed_decode_role": hist(reg_d, "serve.mixed_ms"),
            "decode_iterations_decode_role": hist(
                reg_d, "serve.decode_ms"
            )["count"],
            "prefill_role_decode_iterations": hist(
                reg_p, "serve.decode_ms"
            )["count"],
            "migration": {
                "slots": reg_p.peek(
                    "serve.migration.slots_migrated"
                ).value,
                "blocks": reg_p.peek(
                    "serve.migration.blocks_moved"
                ).value,
                "bytes": reg_p.peek("serve.migration.bytes").value,
                "migrate_ms_mean": round(
                    mig_ms["sum"] / max(mig_ms["count"], 1), 4
                ),
                "failed": reg_p.peek("serve.migration.failed").value,
            },
            "decode_compiles_decode_role": de_eng.decode_compiles,
            "greedy_agreement_vs_colocated": {
                "requests_exact": len(dz_cs) - len(mism),
                "requests": len(dz_cs),
                "min_first_divergence": min(mism) if mism else None,
            },
        }
        del co_eng, pf_eng, de_eng

    # ------------------------------------------------------- chaos arm
    # The failure plane under fire (ISSUE 15): a 3-replica router
    # driven by the seeded ChaosHarness — replicas crash mid-stream and
    # run fail-slow per the schedule, recovery re-dispatch frames drop
    # on the wire, dead replicas revive behind the probation circuit
    # breaker, and load shedding is armed.  The headline is not
    # throughput (replica deaths + revival recomputes make the makespan
    # a function of the schedule): it is the terminal invariant —
    # every submitted request terminates exactly once with a definite
    # status — plus the serve.health.* counter envelope.
    chaos_payload = None
    if args.chaos:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import ChaosHarness

        def chaos_engine():
            e = DecodeEngine(
                model, params, capacity=args.batch,
                num_blocks=num_blocks, block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=blocks_for(
                    padded_longest, args.block_len
                ),
            )
            warm_engine(e)
            return e

        cz_reg = MetricsRegistry()
        harness = ChaosHarness(
            chaos_engine, replicas=3, seed=args.seed,
            registry=cz_reg, revive_after=4, max_revives=2,
            shed_depth=4 * args.batch,
        )
        cz_n = min(args.requests, 32)
        cz_reqs = [
            Request(id=60_000 + i, prompt=prompts[i].tolist(),
                    max_new_tokens=int(new_counts[i]),
                    arrival=float(arrivals[i]))
            for i in range(cz_n)
        ]
        t0 = time.perf_counter()
        report = harness.run(cz_reqs)
        cz_wall = time.perf_counter() - t0

        def cz_cnt(name):
            inst = cz_reg.peek(name)
            return inst.value if inst is not None else 0

        router = harness.router
        ok_tokens = sum(
            len(c.tokens) for c in router.completions
            if c.status == "ok"
        )
        chaos_payload = {
            "replicas": 3,
            "seed": args.seed,
            "requests": cz_n,
            "schedule": harness.schedule,
            "invariant_holds": report["holds"],
            "by_status": report["by_status"],
            "lost": report["lost"],
            "duplicated": report["duplicated"],
            "replica_dead": cz_cnt("serve.health.replica_dead"),
            "recovered": cz_cnt("serve.health.recovered"),
            "retries": cz_cnt("serve.health.retries"),
            "poisoned": cz_cnt("serve.health.poisoned"),
            "shed": cz_cnt("serve.health.shed"),
            "deadline_cancels": sum(
                int(reg.peek("serve.health.deadline_cancels").value)
                if reg.peek("serve.health.deadline_cancels") is not None
                else 0
                for reg in router.replica_registries
            ),
            "revived": report["revived"],
            "health": report["health"],
            "wall_s": round(cz_wall, 3),
            "ok_tokens": ok_tokens,
            # One-compile contract on every replica whose tick loop
            # still runs and that actually decoded.
            "decode_compiles_up_replicas": [
                s.engine.decode_compiles
                for i, s in enumerate(router.schedulers)
                if router.health.is_up(i) and s._iterations
            ],
        }
        del harness, router

    # ------------------------------------------------------ elastic arm
    # Closed-loop autoscaling (ISSUE 17): diurnal traffic — a sinusoid-
    # modulated Poisson process with a 3x burst window in the middle
    # third — served two ways: a fleet statically provisioned for the
    # peak, and a fleet that starts at one replica behind a closed-loop
    # Autoscaler (scale-up behind probation on backlog, scale-down via
    # the zero-loss drain on idleness, hysteresis + cooldown against
    # flapping).  The headline is replica-seconds saved at held p95 —
    # capacity paid for only while the burst needs it — plus the flap
    # count (must be 0) and a mid-traffic rolling-deploy sub-arm whose
    # zero-loss verdict covers a full fleet replacement.
    elastic_payload = None
    if args.elastic:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import (
            Autoscaler,
            RollingDeploy,
            Router,
            verify_terminal_invariant,
        )

        def elastic_engine():
            e = DecodeEngine(
                model, params, capacity=args.batch,
                num_blocks=num_blocks, block_len=args.block_len,
                prefill_chunk=args.prefill_chunk,
                max_blocks_per_slot=blocks_for(
                    padded_longest, args.block_len
                ),
            )
            warm_engine(e)
            return e

        ez_max = max(2, args.replicas)
        ez_n = min(args.requests, 32)
        # Sinusoid + burst arrivals: base rate modulated over one full
        # period across the run, tripled in the middle third.  The base
        # is calibrated to ONE replica's measured service rate (the
        # continuous arm's saturated makespan), not the bench's global
        # 4x-overload `rate`: off-peak demand sits at half a replica's
        # capacity — one replica keeps up, so the static fleet's extra
        # replicas are pure idle spend — while the burst window pushes
        # past one replica and forces the scale-up the arm is about.
        one_replica_rate = args.requests / max(cont_makespan, 1e-9)
        base_rate = max(0.5 * one_replica_rate, 1e-6)
        t_arr, ez_arrivals = 0.0, []
        for i in range(ez_n):
            lam = base_rate * (
                1.0 + 0.8 * np.sin(2.0 * np.pi * i / max(ez_n, 1))
            )
            if ez_n // 3 <= i < 2 * ez_n // 3:
                lam *= 3.0
            t_arr += float(rng.exponential(1.0 / max(lam, 1e-9)))
            ez_arrivals.append(t_arr)

        def ez_reqs(base_id):
            return [
                Request(id=base_id + i, prompt=prompts[i].tolist(),
                        max_new_tokens=min(int(new_counts[i]), 24),
                        arrival=float(ez_arrivals[i]))
                for i in range(ez_n)
            ]

        def ez_drive(router, scaler=None):
            """Drain the fleet, integrating up-replica count over the
            shared virtual clock (replica-seconds: what a capacity bill
            charges) and skipping idle gaps to the next arrival exactly
            as Router.run does."""
            area, last = 0.0, router.clock.now()
            ticks = 0
            while router.pending:
                progressed = router.tick()
                ticks += 1
                if scaler is not None:
                    scaler.tick()
                now = router.clock.now()
                area += (now - last) * sum(
                    1 for i, s in enumerate(router.schedulers)
                    if s is not None and router.health.is_up(i)
                )
                last = now
                if not progressed:
                    nxt = [
                        t for t in (
                            [r.arrival
                             for r in router.queued_requests()[:1]]
                            + [s.next_arrival()
                               for i, s in enumerate(router.schedulers)
                               if s is not None
                               and router.health.is_up(i)]
                        )
                        if t is not None and t > now
                    ]
                    if nxt:
                        router.clock.skip_to(min(nxt))
            router.finish()
            return ticks, area

        def ez_p95(comps):
            return _pct(
                [c.finished_at - c.arrival for c in comps], 0.95
            )

        # Peak-provisioned static fleet.
        st_router = Router(
            [elastic_engine() for _ in range(ez_max)],
            registry=MetricsRegistry(),
        )
        st_reqs = ez_reqs(80_000)
        for r in st_reqs:
            st_router.submit(r)
        st_ticks, st_area = ez_drive(st_router)
        st_comps = st_router.completions
        st_report = verify_terminal_invariant(st_reqs, st_comps)

        # Warm standby pool: a real fleet scales up onto a machine that
        # compiled its programs long before the burst.  Building +
        # warming an engine inside the driven loop would charge
        # multi-second XLA compiles to the fleet's shared wall clock —
        # every queued request ages across the compile and both
        # headlines measure the build, not the policy.
        ez_spares = [elastic_engine() for _ in range(ez_max + 1)]

        def ez_factory(params=None):
            del params  # same-version scale-up / rollout
            return ez_spares.pop() if ez_spares else elastic_engine()

        # Autoscaled fleet: starts at one replica.
        ez_reg = MetricsRegistry()
        ez_router = Router([elastic_engine()], registry=ez_reg)
        # Aggressive-up, damped-down: every tick a burst spends queued
        # is p95 damage, so the up-trigger fires on the first breaching
        # tick; the down watch needs a 3-tick idle streak (the tick
        # after a scale-up always samples a transient occupancy dip —
        # the newcomer is empty — which must not register as a flap).
        scaler = Autoscaler(
            ez_router, ez_factory, registry=ez_reg,
            min_replicas=1, max_replicas=ez_max,
            up_depth=1.5, down_occ=0.25, hysteresis=1,
            down_hysteresis=3, cooldown_ticks=8,
        )
        el_reqs = ez_reqs(81_000)
        for r in el_reqs:
            ez_router.submit(r)
        ez_ticks, ez_area = ez_drive(ez_router, scaler)
        ez_comps = ez_router.completions
        ez_report = verify_terminal_invariant(el_reqs, ez_comps)
        st_p95 = ez_p95(st_comps)
        el_p95 = ez_p95(ez_comps)

        # Rolling-deploy sub-arm: replace every replica mid-traffic.
        rl_reg = MetricsRegistry()
        rl_router = Router(
            [elastic_engine() for _ in range(2)],
            registry=rl_reg, probation_ticks=8,
        )
        rl_reqs = [
            Request(id=85_000 + i, prompt=prompts[i].tolist(),
                    max_new_tokens=min(int(new_counts[i]), 24))
            for i in range(min(ez_n, 16))
        ]
        for r in rl_reqs:
            rl_router.submit(r)
        for _ in range(3):
            rl_router.tick()
        rollout = RollingDeploy(
            rl_router, ez_factory, registry=rl_reg,
        )
        guard = 0
        while not rollout.done and not rollout.paused:
            rl_router.tick()
            rollout.tick()
            guard += 1
            if guard > 200 * max(1, len(rl_router.schedulers)):
                break
        rl_router.run()
        rl_report = verify_terminal_invariant(
            rl_reqs, rl_router.completions
        )
        rollout_zero_loss = bool(
            rl_report["holds"] and rollout.done and not rollout.paused
            and all(c.status == "ok" for c in rl_router.completions)
        )

        saved_pct = round(
            100.0 * (1.0 - ez_area / max(st_area, 1e-9)), 2
        )
        elastic_payload = {
            "replicas_max": ez_max,
            "requests": ez_n,
            "traffic": {
                "shape": "sinusoidal+burst",
                "base_rate_per_sec": round(base_rate, 3),
                "burst_multiplier": 3.0,
            },
            "invariant_holds": bool(
                st_report["holds"] and ez_report["holds"]
            ),
            "static": {
                "p95_latency_s": round(st_p95, 4),
                "replica_seconds": round(st_area, 4),
                "mean_replicas": float(ez_max),
                "ticks": st_ticks,
            },
            "elastic": {
                "p95_latency_s": round(el_p95, 4),
                "replica_seconds": round(ez_area, 4),
                "mean_replicas": round(
                    scaler.replica_ticks / max(ez_ticks, 1), 2
                ),
                "ticks": ez_ticks,
                "scale_ups": len([
                    d for d in scaler.decisions
                    if d["action"] == "scale_up"
                ]),
                "scale_downs": len([
                    d for d in scaler.decisions
                    if d["action"] == "scale_down"
                ]),
                "flaps": scaler.flaps,
                "decisions": scaler.decisions[:8],
            },
            # "Held" = within 1.5x of the peak-provisioned fleet.  The
            # in-process harness ticks replicas SERIALLY on the shared
            # wall clock, so an added replica buys slots but never
            # wall-parallel compute — the elastic fleet can absorb a
            # burst it queued through, not out-run static.  The margin
            # covers the scale-up response window (watch trigger +
            # probation admission) that is the policy's real price.
            "replica_seconds_saved_pct": saved_pct,
            "p95_held": bool(el_p95 <= 1.5 * st_p95),
            "rollout": {
                "requests": len(rl_reqs),
                "replaced": list(rollout.replaced),
                "paused": rollout.paused,
                "zero_loss": rollout_zero_loss,
                "decode_compiles_per_replica": [
                    s.engine.decode_compiles
                    for s in rl_router.schedulers if s is not None
                ],
            },
        }
        del st_router, ez_router, rl_router

    # ------------------------------------------------------ tenants arm
    # Multi-tenant metering (ISSUE 16): the same traffic labeled across
    # N tenants with Zipf-distributed popularity (a couple of tenants
    # dominate — the skew a quota system must survive) through a router
    # whose fleet-wide usage ledger is ON.  Reuses the warmed continuous
    # engine: the arm's subject is attribution, not throughput.  The
    # headline is ``tenant_top_share`` (the top consumer's fraction of
    # fleet block-seconds — the scarce resource) plus the conservation
    # verdict: per-tenant sums equal fleet totals EXACTLY, every request
    # finalized exactly once.
    tenant_payload = None
    if args.tenants:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import Router

        n_t = args.tenants
        t_ranks = np.arange(1, n_t + 1, dtype=np.float64)
        pt = t_ranks ** -args.zipf_a
        pt /= pt.sum()
        tn_n = min(args.requests, 32)
        assign = rng.choice(n_t, size=tn_n, p=pt)
        eng.drop_prefix_cache()
        tn_reg = MetricsRegistry()
        tn_router = Router([eng], registry=tn_reg)
        tn_reqs = [
            Request(id=70_000 + i, prompt=prompts[i].tolist(),
                    max_new_tokens=min(int(new_counts[i]), 24),
                    arrival=float(arrivals[i]),
                    tenant=f"tenant{int(assign[i])}")
            for i in range(tn_n)
        ]
        tn_cs = tn_router.run(tn_reqs)
        tn_span = (
            max(c.finished_at for c in tn_cs)
            - min(c.arrival for c in tn_cs)
        )
        led = tn_router.ledger
        cons = led.verify_conservation(requests=tn_reqs)
        t_agg = led.aggregate()
        fleet_block_us = max(led.totals["block_us"], 1)
        tenant_payload = {
            "tenants": n_t,
            "zipf_a": args.zipf_a,
            "requests": tn_n,
            "conservation_holds": cons["holds"],
            # Top consumer's share of fleet block-seconds — also
            # published live as the serve.tenant.top_share gauge.
            "tenant_top_share": round(
                max(t["block_us"] for t in t_agg.values())
                / fleet_block_us, 4,
            ),
            "top": led.top(3),
            "per_tenant": {
                name: {
                    "requests": t["requests"],
                    "tokens": t["tokens"],
                    "tokens_per_sec": round(t["tokens"] / tn_span, 1),
                    "block_seconds": round(t["block_us"] / 1e6, 4),
                    "block_second_share": round(
                        t["block_us"] / fleet_block_us, 4
                    ),
                }
                for name, t in sorted(t_agg.items())
            },
        }
        del tn_router

    # -------------------------------------------------- multitenant arm
    # SLO-aware policy (ISSUE 19): a bursty adversarial tenant dumps a
    # 2x-capacity burst at t=0 with a latency-sensitive tenant's
    # requests queued BEHIND it (submission order — FIFO's worst case),
    # served twice over the same warmed engine: plain FIFO, then
    # through a PolicyPlane giving the SLO tenant a 4:1 VTC weight.
    # Same priority class both ways — the comparison is about admission
    # ORDER, not preemption recompute — so aggregate work is identical
    # and the fairness contract (policy tokens/s >= 95% of FIFO's) has
    # no systematic reason to fail; FIFO drains the whole burst before
    # the SLO tenant sees a slot, while the policy hands every freed
    # slot to the cheapest virtual clock, collapsing the SLO tenant's
    # p95.  Reuses the warmed continuous engine: decode_compiles must
    # stay pinned with the policy ON.
    mt_payload = None
    if args.multitenant:
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.serving import (
            PolicyPlane,
            Router,
            TenantPolicy,
        )

        mt_adv = min(2 * args.batch, 16)
        mt_slo = max(4, args.batch // 2)

        def mt_reqs(base_id):
            def pick(j, tenant, i):
                return Request(
                    id=base_id + j,
                    prompt=prompts[i % len(prompts)].tolist(),
                    max_new_tokens=min(
                        int(new_counts[i % len(new_counts)]), 16
                    ),
                    arrival=0.0, tenant=tenant,
                )
            adv = [pick(i, "adv", i) for i in range(mt_adv)]
            slo = [pick(500 + i, "slo", mt_adv + i)
                   for i in range(mt_slo)]
            return adv + slo  # burst first, SLO trickle queued behind

        def mt_pass(base_id, policy):
            eng.drop_prefix_cache()
            mr = Router([eng], registry=MetricsRegistry(),
                        policy=policy)
            reqs = mt_reqs(base_id)
            comps = mr.run(reqs)
            assert all(c.status == "ok" for c in comps)
            span = max(
                max(c.finished_at for c in comps)
                - min(c.arrival for c in comps), 1e-9,
            )
            tps = sum(len(c.tokens) for c in comps) / span
            slo_lat = [c.finished_at - c.arrival for c in comps
                       if c.id >= base_id + 500]
            adv_lat = [c.finished_at - c.arrival for c in comps
                       if c.id < base_id + 500]
            return tps, _pct(slo_lat, 0.95), _pct(adv_lat, 0.95)

        # Alternating best-of-2 passes per arm (the bench's min-of-N
        # idiom): both arms run the SAME work in a different order, so
        # any tokens/s gap is host noise — a single pass on a shared
        # CPU can swing the fairness ratio by several percent either
        # way and flip the >=95% verdict on nothing.
        fifo_runs, pol_runs = [], []
        mt_plane = None
        for mp in range(2):
            fifo_runs.append(mt_pass(90_000 + 2_000 * mp, None))
            mt_plane = PolicyPlane(
                tenants={"slo": TenantPolicy("slo", weight=4.0),
                         "adv": TenantPolicy("adv", weight=1.0)},
                registry=MetricsRegistry(),
            )
            pol_runs.append(mt_pass(91_000 + 2_000 * mp, mt_plane))
        fifo_tps = max(r[0] for r in fifo_runs)
        fifo_slo_p95 = min(r[1] for r in fifo_runs)
        fifo_adv_p95 = min(r[2] for r in fifo_runs)
        pol_tps = max(r[0] for r in pol_runs)
        pol_slo_p95 = min(r[1] for r in pol_runs)
        pol_adv_p95 = min(r[2] for r in pol_runs)
        mt_payload = {
            "adv_requests": mt_adv,
            "slo_requests": mt_slo,
            "weights": {"slo": 4.0, "adv": 1.0},
            "fifo": {
                "tokens_per_sec": round(fifo_tps, 1),
                "slo_p95_latency_s": round(fifo_slo_p95, 4),
                "adv_p95_latency_s": round(fifo_adv_p95, 4),
            },
            "policy": {
                "tokens_per_sec": round(pol_tps, 1),
                "slo_p95_latency_s": round(pol_slo_p95, 4),
                "adv_p95_latency_s": round(pol_adv_p95, 4),
                # VTC audit trail: admitted tenant order (first wave)
                # and the final virtual clocks — the SLO tenant's must
                # run ~1/4 the adversary's per unit charged.
                "admission_order": [
                    t for _, t, _ in mt_plane.admission_log[:8]
                ],
                "virtual_clock": {
                    t: round(v, 2)
                    for t, v in sorted(mt_plane.virtual.items())
                },
            },
            "decode_compiles": eng.decode_compiles,
            # Held = the policy's SLO-tenant p95 within 1.1x FIFO's
            # (in practice far below it: the burst no longer queues
            # ahead); the margin absorbs host jitter on the shared-CPU
            # smoke path.
            "slo_tenant_p95_held": bool(
                pol_slo_p95 <= 1.1 * fifo_slo_p95
            ),
            "fairness_throughput_pct": round(
                100.0 * pol_tps / max(fifo_tps, 1e-9), 2
            ),
            "contract": "slo p95 held at >= 95% of FIFO tokens/s",
        }

    payload = {
        "metric": "serving_tokens_per_sec",
        "value": round(cont_tps, 1),
        "unit": "useful generated tokens/sec",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "requests": args.requests,
        "capacity": args.batch,
        "repeats": repeats,
        "traffic": {
            "prompt_len": [args.prompt_min, args.prompt_max],
            "max_new": [args.new_min, args.new_max],
            "len_sigma": args.len_sigma,
            "poisson_rate_per_sec": round(rate, 3),
            "useful_tokens": useful_tokens,
        },
        "config": {"layers": args.layers, "d_model": args.d_model,
                   "heads": args.heads, "d_ff": args.d_ff,
                   "vocab": args.vocab, "kv_heads": args.kv_heads,
                   "decode_attention": args.decode_attention,
                   "kv_int8": bool(args.kv_int8)},
        "pool": {"num_blocks": num_blocks, "block_len": args.block_len,
                 "bytes_per_block": eng.pool.bytes_per_block,
                 "prefill_ladder": list(eng.prefill_ladder),
                 "evictions": evictions},
        "continuous": {
            "tokens_per_sec": round(cont_tps, 1),
            "makespan_s": round(cont_makespan, 3),
            "token_latency_ms_p50": round(_pct(cont_lat, 0.5) * 1e3, 3),
            "token_latency_ms_p95": round(_pct(cont_lat, 0.95) * 1e3, 3),
            "decode_compiles": eng.decode_compiles,
            "prefill_compiles": eng.prefill_compiles,
        },
        # Serving-plane observability overhead (ISSUE 6 contract: the
        # default-on stack costs < 1% tokens/s).  Drain-mode A/B (see
        # the comment above); ``overhead_pct`` is the median of paired
        # alternating-pass ratios — host jitter can land it slightly
        # negative, the contract reads the magnitude.  The obs-on/off
        # tokens/s are each arm's best drain pass over the A/B workload
        # (not the traffic headline above).
        "observability": {
            "tokens_per_sec_obs_on": round(ab_useful / ab_best[True], 1),
            "tokens_per_sec_obs_off": round(
                ab_useful / ab_best[False], 1
            ),
            "overhead_pct": round(obs_overhead_pct, 3),
            "overhead_pct_min_ratio": round(
                100 * (ab_best[True] / ab_best[False] - 1.0), 3
            ),
            "overhead_pair_ratios_pct": [
                round(100 * r, 3) for r in pair_ratios
            ],
            "contract": "obs-on within 1% of obs-off tokens/s",
            "decode_compiles_obs_off": compiles[False],
            "decode_compiles_obs_on": compiles[True],
            "slo_p95_ms": {
                s: round(rep["p95_ms"], 3)
                for s, rep in (sched_on.slo.last_report or {}).items()
                if rep.get("p95_ms") is not None
            } if sched_on.slo is not None else None,
            "timeline_events": (
                len(sched_on.timeline)
                if sched_on.timeline is not None else 0
            ),
        },
        "static": {
            "tokens_per_sec": round(static_tps, 1),
            "makespan_s": round(static_makespan, 3),
            "token_latency_ms_p50": round(_pct(static_lat, 0.5) * 1e3, 3),
            "token_latency_ms_p95": round(_pct(static_lat, 0.95) * 1e3, 3),
            "batches": len(batches),
            "padded_token_overhead": round(
                args.batch * sum(
                    max(new_counts[i] for i in b) for b in batches
                ) / useful_tokens, 3,
            ),
        },
        "speedup_vs_static": round(cont_tps / static_tps, 3),
        "greedy_agreement_vs_static": agreement,
    }
    if prefix_payload is not None:
        payload["prefix_reuse"] = prefix_payload
    if spec_payload is not None:
        payload["speculative"] = spec_payload
    if router_payload is not None:
        payload["router"] = router_payload
    if sharded_payload is not None:
        payload["sharded_decode"] = sharded_payload
    if disagg_payload is not None:
        payload["disagg"] = disagg_payload
    if chaos_payload is not None:
        payload["chaos"] = chaos_payload
    if elastic_payload is not None:
        payload["elastic"] = elastic_payload
    if tenant_payload is not None:
        payload["tenants"] = tenant_payload
    if mt_payload is not None:
        payload["multitenant"] = mt_payload
    print(json.dumps(payload))
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(payload, args.out)


if __name__ == "__main__":
    main()
