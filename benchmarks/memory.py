"""Memory-lever ablation: XLA's own buffer-assignment numbers per config.

For the Transformer-LM train step, compiles (never executes) each config and
records ``compiled.memory_analysis()`` — XLA's temp/argument/output buffer
sizes after fusion and scheduling.  This is the compiler's ground truth for
what the levers buy:

  * ``remat``    — decoder blocks rematerialized (``TransformerLM(remat=)``)
  * ``accum``    — gradient accumulation (``make_train_step(accum_steps=)``)
  * ``ce_chunk`` — chunked LM-head loss (``lm_loss_chunked``)

Lowering uses abstract ShapeDtypeStructs (``jax.eval_shape``), so no batch
or parameter arrays are materialized — the harness runs in seconds and needs
the device only as a compile target.  Numbers are per-platform (buffer
assignment differs between XLA:CPU and XLA:TPU); the TPU run is the honest
one and the watcher captures it (``result/memory_tpu.json``).

    python benchmarks/memory.py --out result/memory_tpu.json    # on TPU
    JAX_PLATFORMS=cpu python benchmarks/memory.py --smoke       # plumbing
"""

from __future__ import annotations

import argparse
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--ce-chunk", type=int, default=4096)
    ap.add_argument("--accum", type=int, default=4)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="explicitly permit a (clearly labeled) CPU run")
    ap.add_argument("--autopsy", action="store_true",
                    help="the 1.5B T=4096/B=1 OOM autopsy (VERDICT r4 "
                         "weak #4): compile the exact failing lm.py "
                         "geometry and its lever variants, and report "
                         "where the bytes go")
    ap.add_argument("--fitprobe", action="store_true",
                    help="the >2B storage-lever A/B: compile the 2.6B "
                         "(GPT-3-2.7B geometry) train step AND the donated "
                         "init program with fp32 vs bf16 param storage, "
                         "and report where the bytes go — compile-only "
                         "evidence for the param_dtype lever without "
                         "burning a full bench window")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.fitprobe:
        args.batch, args.seq = 1, 2048
        args.layers, args.d_model, args.heads = 32, 2560, 20
        args.d_ff, args.vocab = 10240, 32768
    if args.autopsy:
        # The config result/lm_1558m_t4096_stderr.log died on (both arms,
        # RESOURCE_EXHAUSTED on the 15.75 GB chip).
        args.batch, args.seq = 1, 4096
        args.layers, args.d_model, args.heads = 48, 1600, 25
        args.d_ff, args.vocab = 6400, 32768

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import optax

    if (jax.devices()[0].platform != "tpu" and not args.smoke
            and not args.allow_cpu):
        # Same policy as the sibling benches: a CPU fallback must never
        # claim the TPU artifact slot (--out is skipped too).
        print(json.dumps({
            "error": f"memory ablation wants a TPU (got "
                     f"{jax.devices()[0].platform}); pass --smoke or "
                     "--allow-cpu for an explicitly labeled CPU run"
        }))
        return

    import chainermn_tpu as cmn
    from chainermn_tpu.models import (
        TransformerLM,
        lm_loss,
        lm_loss_chunked,
    )

    if args.smoke:
        # batch 8 divides any of the test meshes (1 device or the forced
        # 8-device CPU pool) — same convention as lm.py's smoke config.
        args.batch, args.seq, args.layers = 8, 256, 2
        args.d_model, args.heads, args.d_ff = 128, 4, 256
        args.vocab, args.ce_chunk, args.accum = 1024, 256, 2

    comm = cmn.create_communicator("xla")
    out = {
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "config": vars(args).copy(),
        "configs": {},
    }
    out["config"].pop("out", None)

    batch_abs = (
        jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32),
        jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32),
    )

    def analyze(name, remat=False, accum=1, ce_chunk=0, optimizer="adamw",
                param_dtype="float32", include_init=False):
        model = TransformerLM(
            vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
            n_heads=args.heads, d_ff=args.d_ff, max_len=args.seq,
            remat=remat, param_dtype=getattr(jnp, param_dtype),
        )
        loss_fn = (
            lm_loss_chunked(model, chunk_size=ce_chunk)
            if ce_chunk
            else lm_loss(model)
        )
        base_opt = (
            optax.adafactor(3e-4) if optimizer == "adafactor"
            else optax.adamw(3e-4)
        )
        opt = cmn.create_multi_node_optimizer(base_opt, comm)
        # Per-arm geometry recorded in the rec itself: the fitprobe's wall
        # arm re-points args at a different model size after the top-level
        # config snapshot, so the snapshot alone would misdescribe it.
        rec_geometry = {
            "layers": args.layers, "d_model": args.d_model,
            "heads": args.heads, "d_ff": args.d_ff,
            "batch": args.batch, "seq": args.seq,
            "param_dtype": param_dtype,
        }
        # Abstract all the way down: shapes of params/state via eval_shape,
        # so nothing is materialized on (or transferred to) the device.
        params_abs = jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, args.seq), jnp.int32)
            )["params"]
        )
        state_abs = jax.eval_shape(opt.init, params_abs)
        step = opt.make_train_step(loss_fn, has_aux=True, accum_steps=accum)
        rec = {"geometry": rec_geometry}
        if include_init:
            # The DONATED init program's own peak (benchmarks/lm.py runs
            # exactly this before the first step): with donation its
            # argument buffers alias into the state, so temp+output is the
            # honest init-time high-water mark — the live 2.08B fp32 OOM
            # happened here, not in the steady-state step.
            try:
                imem = (
                    jax.jit(opt.init, donate_argnums=0)
                    .lower(params_abs).compile().memory_analysis()
                )
                rec["init"] = {
                    k.replace("_in_bytes", "_mb"): round(
                        getattr(imem, k) / 2**20, 1
                    )
                    for k in (
                        "temp_size_in_bytes", "argument_size_in_bytes",
                        "output_size_in_bytes",
                    )
                    if getattr(imem, k, None) is not None
                }
            except Exception as e:
                # Same triage as the step path below: transients abort the
                # run (no artifact → the watcher retries); only an OOM-ish
                # verdict is a recordable property of the geometry.  A
                # generic non-OOM error frozen in here would satisfy the
                # watcher's file-existence gate forever.
                msg = str(e)
                if not any(s in msg for s in (
                        "Ran out of memory", "RESOURCE_EXHAUSTED",
                        "hbm requirement", "tpu_compile_helper",
                )):
                    raise
                rec["init"] = {"compile_oom": True,
                               "compile_error": msg[:300]}
        try:
            mem = step.lower(state_abs, batch_abs).compile().memory_analysis()
        except Exception as e:
            # A config that doesn't fit fails AT COMPILE — and that failure
            # is the autopsy's subject, not a crash: record what the
            # compiler said and keep going so the lever variants that DO
            # fit report real memory_analysis numbers.  On this rig the
            # tunnel's remote-compile helper can wrap the OOM in a generic
            # INTERNAL/HTTP-500 error with the allocation dump on stderr
            # only, so the parse is best-effort.
            import re

            msg = str(e)
            if any(t in msg for t in ("UNAVAILABLE", "DEADLINE_EXCEEDED")):
                # Transient tunnel drop, not a memory verdict: abort with no
                # artifact so the watcher's missing-file gate retries —
                # recording it would freeze an outage in as compile_oom.
                raise
            oomish = any(s in msg for s in (
                "Ran out of memory", "RESOURCE_EXHAUSTED",
                "hbm requirement", "tpu_compile_helper",
            ))
            if not oomish:
                raise
            rec["compile_oom"] = True
            m = re.search(r"Used ([\d.]+)G of ([\d.]+)G hbm", msg)
            if m:
                rec["hbm_used_gb"], rec["hbm_capacity_gb"] = (
                    float(m.group(1)), float(m.group(2)))
            m = re.search(r"Program hbm requirement ([\d.]+)G", msg)
            if m:
                rec["program_hbm_requirement_gb"] = float(m.group(1))
            allocs = re.findall(
                r"Size: ([\d.]+[GMK])\s*\n\s*Operator: op_name=\"([^\"]+)\"",
                msg,
            )
            if allocs:
                rec["largest_allocations"] = [
                    {"size": s, "op": op} for s, op in allocs[:8]
                ]
            if len(rec) == 1:
                # Nothing parseable beyond the fact of failure — keep the
                # head of the message so the record stands alone.
                rec["compile_error"] = msg[:500]
            mem = None
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                rec[k.replace("_in_bytes", "_mb")] = round(v / 2**20, 1)
        # Where the persistent bytes go: params vs optimizer state, from
        # the abstract trees (exact — shapes and dtypes, no execution).
        rec["params_mb"] = round(sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(params_abs)
        ) / 2**20, 1)
        rec["opt_state_mb"] = round(sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(state_abs)
        ) / 2**20, 1) - rec["params_mb"]
        out["configs"][name] = rec
        print(json.dumps({name: rec}), flush=True)

    if args.autopsy:
        analyze("as_failed_adafactor_remat_ce8192", remat=True,
                ce_chunk=8192, optimizer="adafactor")
        analyze("ce2048", remat=True, ce_chunk=2048,
                optimizer="adafactor")
        analyze("ce512", remat=True, ce_chunk=512, optimizer="adafactor")
        analyze("adamw_for_scale", remat=True, ce_chunk=8192)
    elif args.fitprobe:
        analyze("fp32_params", remat=True, ce_chunk=8192,
                optimizer="adafactor", include_init=True)
        analyze("bf16_params", remat=True, ce_chunk=8192,
                optimizer="adafactor", param_dtype="bfloat16",
                include_init=True)
        if not args.smoke:
            # Where does the single-chip ladder END?  GPT-3-6.7B geometry
            # in the same bf16 layout: params alone are ~12.9 GiB — the
            # expected verdict is compile-OOM, recorded honestly as the
            # wall between 2.6B (fits) and 6.7B (cannot; needs ZeRO over
            # a real multi-chip mesh, optimizers/zero.py).
            args.layers, args.d_model, args.heads = 32, 4096, 32
            args.d_ff = 16384
            analyze("bf16_params_6700m_wall", remat=True, ce_chunk=8192,
                    optimizer="adafactor", param_dtype="bfloat16",
                    include_init=True)
    else:
        analyze("baseline")
        analyze("remat", remat=True)
        analyze(f"accum{args.accum}", accum=args.accum)
        analyze("ce_chunk", ce_chunk=args.ce_chunk)
        analyze("remat+accum+ce_chunk", remat=True, accum=args.accum,
                ce_chunk=args.ce_chunk)

    base = (out["configs"].get("baseline") or {}).get("temp_size_mb")
    if base:
        for name, rec in out["configs"].items():
            if "temp_size_mb" in rec:
                rec["temp_vs_baseline"] = round(rec["temp_size_mb"] / base, 3)
    print(json.dumps({k: v for k, v in out.items() if k != "config"}))
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)


if __name__ == "__main__":
    main()
