"""Long-context attention scaling on chip: flash vs sliding-window vs XLA.

SURVEY.md §5 makes long-context a first-class (beyond-parity) capability;
round 3 verified the Pallas kernels compile and win at T=2048.  This harness
measures how they SCALE: a sweep over sequence lengths at a constant total
token budget (B·T fixed, so HBM pressure and per-token cost stay
comparable), timing

  * full causal flash attention            — O(T²/2) work,
  * sliding-window flash (|q-k| < W)       — O(T·W) work,
  * XLA materialized-scores attention      — the baseline, skipped once the
    (B, H, T, T) score tensor would not fit (the point of flash),

fwd and fwd+bwd each, with achieved attention-FLOP/s so the O(T²) vs O(T·W)
curves are visible in one table.

    python benchmarks/longcontext.py --out result/longcontext_tpu.json
    JAX_PLATFORMS=cpu python benchmarks/longcontext.py --smoke ...
"""

from __future__ import annotations

import argparse
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=16384,
                    help="total tokens per config (batch = tokens // seq)")
    ap.add_argument("--seqs", default="2048,4096,8192,16384")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--window", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--xla-max-score-gb", type=float, default=2.0,
                    help="skip the XLA baseline when the bf16 (B,H,T,T) "
                         "score tensor alone would exceed this")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny interpret-mode config for CPU plumbing checks")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import time

    from chainermn_tpu.ops import flash_attention, reference_attention
    from chainermn_tpu.utils import sync

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"longcontext sweep needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    interpret = platform != "tpu"
    if args.smoke:
        args.tokens, args.seqs, args.window = 512, "256,512", 128
        args.heads, args.head_dim, args.iters = 2, 64, 2

    H, D, W = args.heads, args.head_dim, args.window
    seqs = [int(s) for s in args.seqs.split(",")]
    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "tokens_per_config": args.tokens,
        "heads": H, "head_dim": D, "window": W,
        "dtype": "bfloat16",
        "rows": [],
    }

    def flash_fn(window):
        def f(q, k, v):
            return flash_attention(q, k, v, causal=True, window=window,
                                   interpret=interpret)
        return f

    def xla_fn(q, k, v):
        return reference_attention(q, k, v, causal=True)

    def loss_of(fn):
        # Fixed cotangent so fwd+bwd exercises the real backward kernels.
        def loss(q, k, v):
            o = fn(q, k, v)
            return (o.astype(jnp.float32) ** 2).mean()
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def bench(fn, *a):
        # Queue all iterations, then one data readback: the device runs
        # enqueued programs in order, so syncing the LAST output bounds all
        # of them — the tunnel's dispatch/readback latency is paid once,
        # not per iteration (flash_tpu.py's amortized pattern; a per-iter
        # readback added a constant ~60 ms here and swamped the kernels).
        sync(fn(*a))  # compile + warm
        sync(fn(*a))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            r = fn(*a)
        sync(r)
        return (time.perf_counter() - t0) / args.iters

    for T in seqs:
        B = max(1, args.tokens // T)
        rng = np.random.RandomState(0)
        shape = (B, T, H, D)
        q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

        # Attention-only FLOPs (QKᵀ + PV, both 2·B·H·T_q·T_k·D multiply-adds
        # → factor 4), scaled by the visited fraction of the (T,T) plane.
        causal_frac = 0.5 * (1 + 1 / T)
        if W < T:
            # causal ∩ window: each query sees min(W, q+1) keys.
            win_frac = (min(W, T) * T - W * (W - 1) / 2) / (T * T)
        else:
            win_frac = causal_frac
        full_flops = 4.0 * B * H * T * T * D

        score_gb = B * H * T * T * 2 / 1e9
        variants = [
            ("flash_causal", flash_fn(None), causal_frac),
            ("flash_window", flash_fn(W), win_frac),
        ]
        if score_gb <= args.xla_max_score_gb:
            variants.append(("xla_causal", xla_fn, causal_frac))

        row = {"seq": T, "batch": B, "score_gb": round(score_gb, 2),
               "variants": {}}
        for name, raw_fn, frac in variants:
            fwd_s = bench(jax.jit(raw_fn), q, k, v)
            bwd_s = bench(loss_of(raw_fn), q, k, v)
            flops = full_flops * frac
            row["variants"][name] = {
                "fwd_ms": round(fwd_s * 1e3, 3),
                "fwd_bwd_ms": round(bwd_s * 1e3, 3),
                # bwd does ~2.5× the fwd attention work (dQ, dK, dV).
                "fwd_tflops_per_s": round(flops / fwd_s / 1e12, 2),
                "us_per_token_fwd_bwd": round(bwd_s * 1e6 / (B * T), 3),
            }
            print(f"# T={T} B={B} {name}: fwd {row['variants'][name]['fwd_ms']} ms, "
                  f"fwd+bwd {row['variants'][name]['fwd_bwd_ms']} ms", flush=True)
        if score_gb > args.xla_max_score_gb:
            row["variants"]["xla_causal"] = {
                "skipped": f"score tensor {score_gb:.1f} GB > "
                           f"{args.xla_max_score_gb} GB cap"
            }
        out["rows"].append(row)

    line = json.dumps(out)
    print(line)
    if args.out and not args.smoke:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)


if __name__ == "__main__":
    main()
