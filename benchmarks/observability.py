"""Observability overhead: the LM train step with the stack on vs off.

The observability subsystem is DEFAULT-ON, so its cost must be proven, not
assumed: this bench drives the identical jitted TransformerLM train step
through the :class:`~chainermn_tpu.training.Trainer` twice — once with the
full default-on stack (per-step registry publishers, step trace
annotations, a cadenced :class:`~chainermn_tpu.training.MetricsReport`
with rank-0 aggregation) and once with observability forced off
(``set_enabled(False)``: every publisher short-circuits, no extension
attached) — and reports the per-step delta.  The jitted step executable is
shared between arms (same optimizer, same loss callable → same step
cache), so the A/B isolates the host-side observability cost.

Contract (ISSUE 4 / docs/observability.md): overhead < 1% of step time at
real workload geometry.  The per-step cost is two instrument updates and
one TraceAnnotation; the cadenced cost is one float() sync + a small
object-plane gather per ``--report-every`` steps.

    python benchmarks/observability.py --out result/obs_overhead_tpu.json
    JAX_PLATFORMS=cpu python benchmarks/observability.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


class _RepeatIterator:
    """Yields the same global batch forever (epoch never advances — the
    bench stops on iteration count)."""

    def __init__(self, batch):
        self._batch = batch
        self.epoch = 0

    def __next__(self):
        return self._batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--report-every", type=int, default=10,
                    help="MetricsReport cadence in the obs-on arm (the "
                         "float() metric sync + rank-0 gather interval)")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu import observability as obs
    from chainermn_tpu.models import TransformerLM, lm_loss
    from chainermn_tpu.training import MetricsReport, Trainer

    platform = jax.devices()[0].platform
    if platform != "tpu" and not args.smoke:
        print(json.dumps({
            "error": f"observability bench needs a TPU (got {platform}); "
                     "pass --smoke for a CPU plumbing check"
        }))
        return
    if args.smoke:
        args.batch, args.seq, args.layers = 8, 128, 2
        args.d_model, args.heads, args.d_ff, args.vocab = 128, 4, 256, 1024
        # Warmup generous relative to iters: XLA:CPU's first executions
        # run well below steady state, and the smoke tier only checks
        # plumbing — the overhead NUMBER is meaningful on a real chip.
        args.iters, args.warmup = 8, 4
        args.report_every = 2
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    comm = cmn.create_communicator("xla")
    model = TransformerLM(
        vocab=args.vocab, n_layers=args.layers, d_model=args.d_model,
        n_heads=args.heads, d_ff=args.d_ff, max_len=args.seq,
    )
    params = jax.jit(
        lambda r: model.init(r, np.zeros((1, args.seq), np.int32))
    )(jax.random.PRNGKey(0))["params"]
    loss_fn = lm_loss(model)
    rng = np.random.RandomState(0)
    toks = rng.randint(
        0, args.vocab, size=(args.batch, args.seq)
    ).astype(np.int32)
    batch = (toks, toks)
    _opt0 = cmn.create_multi_node_optimizer(optax.adamw(3e-4), comm)
    state0 = _opt0.init(params)

    obs_dir = tempfile.mkdtemp(prefix="cmn_obs_bench_")

    def run_arm(on: bool) -> float:
        """Per-step wall ms through the Trainer.  Each arm builds its
        OWN optimizer (→ its own jitted step, compiled in that arm's
        warmup): the compile-watch wrap latches at the step's birth
        (ISSUE 11), so a step born in the off arm would be a raw jit and
        the on arm would silently measure a stack with its fourth plane
        missing.  Identical programs compile identically; the compile
        lands in the warmup either way, never in the timed window."""
        obs.set_enabled(on)
        opt = cmn.create_multi_node_optimizer(optax.adamw(3e-4), comm)
        try:
            # device=True: the obs-on arm carries the FULL stack under
            # measurement, compile watcher + device roofline gauges
            # included (ISSUE 11 — the A/B proves the fourth plane also
            # fits the <1% contract; the one-time cost capture lands in
            # the arm's warmup, not the timed window).
            exts = (
                [MetricsReport(comm, trigger=(args.report_every,
                                              "iteration"),
                               out_dir=os.path.join(obs_dir, "on"),
                               device=True)]
                if on else []
            )
            # Fresh trainer + a fresh COPY of the state per arm: the step
            # donates its input, so handing both arms the same buffers
            # would leave arm B reading deleted arrays.
            import jax.numpy as jnp

            trainer = Trainer(
                opt, jax.tree_util.tree_map(jnp.array, state0),
                loss_fn, _RepeatIterator(comm.shard_batch(batch)),
                stop=(args.warmup, "iteration"), has_aux=True,
            )
            trainer.run()  # warmup (compile on first arm, cache after)
            if on:
                # Pre-warm the device plane's ONE-TIME cost capture (an
                # extra lowering of the step) outside the timed window —
                # the A/B measures the steady-state cost of the plane,
                # exactly as step compiles live in the warmup.
                from chainermn_tpu.observability import device as odev

                wf = odev.watch().find("train_step")
                if wf is not None:
                    wf.cost_analysis()
            trainer.stop_n = args.warmup + args.iters
            trainer.extensions = list(exts)
            t0 = time.perf_counter()
            trainer.run()
            _ = float(np.asarray(trainer.last_metrics["loss"]))
            return (time.perf_counter() - t0) / args.iters * 1000.0
        finally:
            obs.set_enabled(None)

    # Off first (pays the compile inside its warmup), then on; both timed
    # regions run the cached executable only.
    off_ms = run_arm(False)
    on_ms = run_arm(True)
    overhead_pct = (on_ms - off_ms) / off_ms * 100.0

    payload = {
        "metric": "observability_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "% of step time (obs default-on vs forced off)",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
        "step_ms_obs_off": round(off_ms, 3),
        "step_ms_obs_on": round(on_ms, 3),
        "report_every": args.report_every,
        "iters": args.iters,
        "config": {"batch": args.batch, "seq": args.seq,
                   "layers": args.layers, "d_model": args.d_model,
                   "heads": args.heads, "d_ff": args.d_ff,
                   "vocab": args.vocab},
        "contract": "overhead < 1% of step time (docs/observability.md)",
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(payload))
    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(payload, args.out)


if __name__ == "__main__":
    main()
