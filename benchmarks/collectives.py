"""Collective microbenchmarks: the comm data plane in isolation.

The reference benchmarked its communicator zoo by timing allreduce on raw
buffers across sizes (the hierarchical/two_dimensional design space).  Here
the zoo is XLA's scheduler, but the numbers still matter: this harness
times each collective primitive the framework builds on (psum, all_gather,
psum_scatter, ppermute ring hop, all_to_all) across payload sizes, and
derives achieved bytes/sec (algorithm bandwidth).

    python benchmarks/collectives.py --out result/collectives_tpu.json

On the single real chip this measures single-device latency floors (the
collectives compile to copies); the interesting numbers come from a real
multi-chip slice, and on the CPU mesh the values are plumbing-only — the
JSON records the platform so nobody mistakes either for ICI bandwidth.
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from chainermn_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as cmn
    from chainermn_tpu.utils import sync

    comm = cmn.create_communicator("xla")
    n = comm.size
    platform = jax.devices()[0].platform
    if platform == "cpu":
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    out = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n,
        "results": [],
    }

    def build(op):
        def body(x):
            if op == "psum":
                return lax.psum(x, comm.axis_name)
            if op == "psum_scatter":
                return lax.psum_scatter(
                    x.reshape(n, -1), comm.axis_name, scatter_dimension=0,
                    tiled=False,
                )
            if op == "all_gather":
                return lax.all_gather(x, comm.axis_name, axis=0, tiled=True)
            if op == "ppermute":
                return lax.ppermute(
                    x, comm.axis_name,
                    perm=[(i, (i + 1) % n) for i in range(n)],
                )
            if op == "all_to_all":
                return lax.all_to_all(
                    x.reshape(n, -1), comm.axis_name, split_axis=0,
                    concat_axis=0, tiled=True,
                )
            raise ValueError(op)

        return jax.jit(
            comm.spmd(body, in_specs=P(comm.axes), out_specs=P(comm.axes))
        )

    for mb in (float(s) for s in args.sizes_mb.split(",")):
        per_dev = int(mb * 1e6 / 4)
        per_dev -= per_dev % (n * n)  # all_to_all/psum_scatter divisibility
        if per_dev <= 0:
            continue
        x = jnp.asarray(
            np.random.RandomState(0).normal(size=(n * per_dev,)).astype(
                np.float32
            )
        )
        for op in ("psum", "psum_scatter", "all_gather", "ppermute",
                   "all_to_all"):
            f = build(op)
            r = f(x)
            sync(r)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                r = f(x)
            sync(r)
            dt = (time.perf_counter() - t0) / args.iters
            payload_bytes = per_dev * 4  # per-device contribution
            rec = {
                "op": op,
                "payload_mb_per_device": round(payload_bytes / 1e6, 3),
                "time_ms": round(dt * 1e3, 4),
                "gbytes_per_sec_per_device": round(
                    payload_bytes / dt / 1e9, 3
                ),
            }
            out["results"].append(rec)
            print(json.dumps(rec), flush=True)

    if args.out:
        from chainermn_tpu.utils import atomic_json_dump

        atomic_json_dump(out, args.out)


if __name__ == "__main__":
    main()
