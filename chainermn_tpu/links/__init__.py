"""Distributed links (model-parallel building blocks).

Reference anchors: ``chainermn/links/multi_node_chain_list.py``,
``chainermn/links/batch_normalization.py``.
"""

from chainermn_tpu.links.batch_normalization import (
    MultiNodeBatchNormalization,
    sync_batch_norm,
)
from chainermn_tpu.links.chain_list import (
    HeteroPipelineChain,
    MultiNodeChainList,
    PipelineChain,
)

__all__ = [
    "HeteroPipelineChain",
    "MultiNodeChainList",
    "PipelineChain",
    "MultiNodeBatchNormalization",
    "sync_batch_norm",
]
