"""Cross-replica (sync) batch normalization.

Reference anchor: ``chainermn/links/batch_normalization.py`` —
``class MultiNodeBatchNormalization``: batch mean and squared-mean are
allreduced across the communicator each forward, with the matching allreduce
in backward.

TPU-native: the moments are ``lax.pmean``'d over the data axis inside the
traced step — a few lines, with backward handled by AD (the transpose of
pmean is pmean).  Usable standalone (as below) or via the flax module.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _global_moments(x: jax.Array, axis_name) -> Tuple[jax.Array, jax.Array]:
    """Batch mean/variance reduced over the local batch AND the mesh axis —
    the numerically sensitive core shared by the functional and module APIs.
    Always accumulated in float32 (bf16 inputs would lose the moments)."""
    x = x.astype(jnp.float32)
    red = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=red)
    mean_sq = jnp.mean(jnp.square(x), axis=red)
    if axis_name is not None:
        mean = lax.pmean(mean, axis_name)
        mean_sq = lax.pmean(mean_sq, axis_name)
    return mean, mean_sq - jnp.square(mean)


def sync_batch_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    axis_name,
    eps: float = 1e-5,
) -> jax.Array:
    """Functional sync-BN over leading (batch) dim + the mesh axis.
    Moments accumulate in fp32; output keeps the input dtype."""
    mean, var = _global_moments(x, axis_name)
    inv = lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) - mean) * inv * scale + bias
    return y.astype(x.dtype)


class MultiNodeBatchNormalization(nn.Module):
    """Flax module; use inside a ``shard_map``-traced step where
    ``communicator.axis_name`` is bound.

    Running statistics live in the ``batch_stats`` collection, updated with
    the *globally* reduced moments, so eval-mode behavior matches a
    single-process model trained on the global batch.
    """

    features: int
    axis_name: Any = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    use_running_average: bool = False

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(self.features)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(self.features)
        )
        in_dtype = x.dtype
        if use_ra:
            inv = lax.rsqrt(ra_var.value + self.epsilon)
            y = (x.astype(jnp.float32) - ra_mean.value) * inv * scale + bias
            return y.astype(in_dtype)

        # init traces outside shard_map where the mesh axis is unbound
        axis = None if self.is_initializing() else self.axis_name
        mean, var = _global_moments(x, axis)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        inv = lax.rsqrt(var + self.epsilon)
        y = (x.astype(jnp.float32) - mean) * inv * scale + bias
        return y.astype(in_dtype)
