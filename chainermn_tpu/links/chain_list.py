"""Model-parallel chains.

Reference anchor: ``chainermn/links/multi_node_chain_list.py`` —
``class MultiNodeChainList(chainer.ChainList)`` with ``add_link(link,
rank_in, rank_out)``: a declarative model-parallel graph where each component
runs on its rank, stitched with blocking MPI send/recv and delegate variables
(the most fragile machinery in the reference — SURVEY.md §3.4).

SPMD re-design, two tiers:

* :class:`MultiNodeChainList` — API-compatible heterogeneous chain.  Under a
  single traced SPMD program every device walks the same stage list;
  activations move between stage owners with ``ppermute`` so the comm pattern
  (and its AD transpose) matches the reference's, and there is no deadlock to
  sequence away.  Note on cost: GSPMD cannot skip a branch whose predicate
  varies per device, so heterogeneous stages are *compute-replicated* (every
  device computes each stage, only the owner's result propagates).  Capability
  parity, not a speedup — for distributed speedup use :class:`PipelineChain`.

* :class:`PipelineChain` — the TPU-idiomatic upgrade the reference lacked
  (its chains were sequential; SURVEY.md §2.3 "no microbatch interleaving"):
  homogeneous stacked stages whose parameters are SHARDED over the ``stage``
  mesh axis (each device holds 1/S of the weights), with GPipe-style
  microbatch pipelining via ``lax.scan`` + ``ppermute``.  Backward is AD
  through the scan — the transposed pipeline schedule comes for free.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.functions.point_to_point import send_recv


class _ChainLink(NamedTuple):
    apply: Callable  # apply(params, x) -> y
    rank: int  # owner
    rank_in: Optional[int]
    rank_out: Optional[int]


class MultiNodeChainList:
    """Heterogeneous model-parallel chain (API parity tier).

    ``add_link(apply_fn, rank=owner, rank_in=..., rank_out=...)`` mirrors the
    reference's ``add_link(link, rank_in, rank_out)`` with the owner made
    explicit (MPMD implied it via the calling process).  ``__call__`` runs
    inside a ``shard_map`` body over the communicator's axis.
    """

    def __init__(self, comm):
        self.comm = comm
        self._links: List[_ChainLink] = []

    def add_link(
        self,
        apply_fn: Callable,
        rank: int,
        rank_in: Optional[int] = None,
        rank_out: Optional[int] = None,
    ):
        self._links.append(_ChainLink(apply_fn, rank, rank_in, rank_out))
        return self

    def __call__(self, params_list: Sequence[Any], x):
        """In-graph forward.  ``params_list[i]`` feeds link i (replicated).

        Activation routing follows the reference's recv → compute → send walk
        (SURVEY.md §3.4) with ``ppermute`` edges instead of MPI.  The edge
        into link i is derived from its ``rank_in`` or the previous link's
        ``rank_out`` (validated for consistency); owners on the same rank
        need no edge."""
        assert len(params_list) == len(self._links)
        h = x
        for i, link in enumerate(self._links):
            src = None
            if link.rank_in is not None:
                src = link.rank_in
            if i > 0:
                prev = self._links[i - 1]
                # The only valid edge source is the previous link's owner —
                # validate BOTH declarations against it, whichever is given.
                if src is not None and src != prev.rank:
                    raise ValueError(
                        f"link {i} declares rank_in={src} but link "
                        f"{i - 1} is owned by rank {prev.rank}"
                    )
                if prev.rank_out is not None:
                    if prev.rank_out != link.rank:
                        raise ValueError(
                            f"link {i - 1} declares rank_out={prev.rank_out} "
                            f"but link {i} is owned by rank {link.rank}"
                        )
                    src = prev.rank
                if src is None and prev.rank != link.rank:
                    raise ValueError(
                        f"broken chain: link {i - 1} (rank {prev.rank}) → "
                        f"link {i} (rank {link.rank}) has no declared edge; "
                        f"set rank_out/rank_in"
                    )
            if src is not None and src != link.rank:
                h = send_recv(h, self.comm, [(src, link.rank)])
            h = link.apply(params_list[i], h)
            if link.rank_out is not None and i + 1 == len(self._links):
                # terminal send (to the output consumer)
                h = send_recv(h, self.comm, [(link.rank, link.rank_out)])
        return h


class PipelineChain:
    """GPipe-style pipeline over homogeneous stacked stages.

    Args:
      stage_apply: ``stage_apply(stage_params, x) -> y`` with matching
        x/y shapes (e.g. one transformer block).
      comm: communicator whose (single) axis is the ``stage`` dimension;
        device s owns stage s.
      n_microbatches: how many microbatches the global batch splits into.

    Call inside ``shard_map``: ``pipe(stacked_params_local, x)`` where
    ``stacked_params_local`` is this device's stage slice (leading axis 1 of
    the stage-stacked params) and ``x`` is the full local batch (replicated
    input; stage 0 consumes it).  Returns the pipeline output (replicated).
    """

    def __init__(self, stage_apply: Callable, comm, n_microbatches: int):
        self.stage_apply = stage_apply
        self.comm = comm
        self.n_micro = n_microbatches

    def __call__(self, stage_params, x):
        comm = self.comm
        S = comm.size
        M = self.n_micro
        idx = comm.axis_index()
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        micro = x.reshape(M, B // M, *x.shape[1:])
        mb_shape = micro.shape[1:]

        fwd_pairs = [(s, s + 1) for s in range(S - 1)]

        def tick(buf, t):
            # Inject microbatch t at stage 0 (valid while t < M).
            t_in = jnp.minimum(t, M - 1)
            inj = lax.dynamic_index_in_dim(micro, t_in, axis=0, keepdims=False)
            is_stage0 = (idx == 0)
            cur = jnp.where(is_stage0, inj, buf)
            y = self.stage_apply(stage_params, cur)
            # Collect stage S-1's output on every device (psum-broadcast).
            mask = (idx == S - 1).astype(y.dtype)
            out = lax.psum(y * mask, comm.axis_name)
            # Shift activations one stage forward for the next tick.
            nxt = send_recv(y, comm, fwd_pairs)
            return nxt, out

        T = S + M - 1
        buf0 = jnp.zeros(mb_shape, x.dtype)
        _, outs = lax.scan(tick, buf0, jnp.arange(T))
        # Microbatch m leaves the last stage at tick (S - 1 + m).
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        return valid.reshape(B, *valid.shape[2:])
