"""Model-parallel chains.

Reference anchor: ``chainermn/links/multi_node_chain_list.py`` —
``class MultiNodeChainList(chainer.ChainList)`` with ``add_link(link,
rank_in, rank_out)``: a declarative model-parallel graph where each component
runs on its rank, stitched with blocking MPI send/recv and delegate variables
(the most fragile machinery in the reference — SURVEY.md §3.4).

SPMD re-design, two tiers:

* :class:`MultiNodeChainList` — API-compatible heterogeneous chain.  Under a
  single traced SPMD program every device walks the same stage list;
  activations move between stage owners with ``ppermute`` so the comm pattern
  (and its AD transpose) matches the reference's, and there is no deadlock to
  sequence away.  Note on cost: GSPMD cannot skip a branch whose predicate
  varies per device, so heterogeneous stages are *compute-replicated* (every
  device computes each stage, only the owner's result propagates).  Capability
  parity, not a speedup — linear chains lower to the distributed tier with
  one call (:meth:`MultiNodeChainList.to_pipeline`).

* :class:`HeteroPipelineChain` — distributed compute for HETEROGENEOUS
  stages (different functions/widths per rank, the reference's VGG example
  shape): per-device ``lax.switch`` over a flat activation buffer + GPipe
  microbatching; device ``s`` executes only stage ``s``.

* :class:`PipelineChain` — the TPU-idiomatic upgrade the reference lacked
  (its chains were sequential; SURVEY.md §2.3 "no microbatch interleaving"):
  homogeneous stacked stages whose parameters are SHARDED over the ``stage``
  mesh axis (each device holds 1/S of the weights), with GPipe-style
  microbatch pipelining via ``lax.scan`` + ``ppermute``.  Backward is AD
  through the scan — the transposed pipeline schedule comes for free.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chainermn_tpu.functions.point_to_point import send_recv


#: Last JAX release KNOWN to mis-route ``lax.switch`` cotangents under the
#: ``check_vma=True`` transpose when the branch index is device-varying
#: (all closures collapse onto branch 0's operands) — the defect pinned by
#: ``tests/links_tests/test_hetero_pipeline.py``.  Versions at or below
#: this skip the probe and run the hetero chain with the checker off.
_SWITCH_VMA_LAST_KNOWN_BAD = (0, 9, 0)

_switch_vma_probe_cache: dict = {}


def switch_vma_safe(mesh) -> bool:
    """Does ``lax.switch`` with a device-varying index differentiate
    correctly under ``check_vma=True`` on the installed JAX?

    Versions up to :data:`_SWITCH_VMA_LAST_KNOWN_BAD` return ``False``
    without spending a compile.  NEWER versions run a one-off numeric
    probe (tiny switch-grad vs oracle, cached per process) so the
    debug-mode default flips back ON the moment upstream ships the fix
    (VERDICT r3 item 9) — and stays off if the fix regresses."""
    from chainermn_tpu import _compat

    if _compat.VMA_SHIMMED:
        # No vma checker exists on this runtime (shimmed to checker-off):
        # there is nothing to mis-route, so the switch path is trivially
        # safe — and the version pin below (which describes the REAL
        # checker's defect) does not apply.
        return True
    ver = tuple(
        int(p) for p in jax.__version__.split(".")[:3] if p.isdigit()
    )
    if ver <= _SWITCH_VMA_LAST_KNOWN_BAD:
        return False
    key = (ver, tuple(d.id for d in mesh.devices.flat))
    hit = _switch_vma_probe_cache.get(key)
    if hit is None:
        hit = _switch_vma_probe_cache[key] = _probe_switch_vma(mesh)
    return hit


def _probe_switch_vma(mesh) -> bool:
    from jax.sharding import Mesh, PartitionSpec as P

    devices = list(mesh.devices.flat)
    S = len(devices)
    if S < 2:
        return True  # no device-varying index possible: nothing to mis-route
    rng = np.random.RandomState(0)
    pm = Mesh(np.array(devices), ("_vmaprobe",))
    params = tuple(
        jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))
        for _ in range(S)
    )
    x = jnp.asarray(rng.normal(size=(2, 4)).astype(np.float32))

    def f(ps, xx):
        def body(pl, b):
            idx = lax.axis_index("_vmaprobe")
            branches = [
                (lambda bb, s=s: jnp.tanh(bb @ pl[s])) for s in range(S)
            ]
            y = lax.switch(idx, branches, b)
            mask = (idx == S - 1).astype(y.dtype)
            return jnp.sum(lax.psum(y * mask, "_vmaprobe") ** 2)

        return jax.shard_map(
            body, mesh=pm, in_specs=(P(), P()), out_specs=P(),
            check_vma=True,
        )(ps, xx)

    try:
        g = jax.jit(jax.grad(f))(params, x)
    except Exception:
        return False  # checker rejects the program outright: not safe
    oracle = jax.grad(
        lambda ps, xx: jnp.sum(jnp.tanh(xx @ ps[S - 1]) ** 2)
    )(params, x)
    return all(
        bool(np.allclose(np.asarray(g[s]), np.asarray(oracle[s]),
                         atol=1e-5))
        for s in range(S)
    )


def _make_unravel(treedef, shapes):
    """Traced inverse of the host-side flat ravel in ``shard_params``:
    slices a flat row back into the stage's leaves (same ``tree_flatten``
    order).  Pure reshape/slice, so AD transposes it exactly."""
    sizes = [int(np.prod(s)) for s in shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(int)

    def unravel(vec):
        parts = [
            vec[offsets[i]: offsets[i + 1]].reshape(shapes[i])
            for i in range(len(shapes))
        ]
        return jax.tree_util.tree_unflatten(treedef, parts)

    return unravel


class _ChainLink(NamedTuple):
    apply: Callable  # apply(params, x) -> y
    rank: int  # owner
    rank_in: Optional[int]
    rank_out: Optional[int]


class MultiNodeChainList:
    """Heterogeneous model-parallel chain (API parity tier).

    ``add_link(apply_fn, rank=owner, rank_in=..., rank_out=...)`` mirrors the
    reference's ``add_link(link, rank_in, rank_out)`` with the owner made
    explicit (MPMD implied it via the calling process).  ``__call__`` runs
    inside a ``shard_map`` body over the communicator's axis.
    """

    def __init__(self, comm):
        self.comm = comm
        self._links: List[_ChainLink] = []

    def add_link(
        self,
        apply_fn: Callable,
        rank: int,
        rank_in: Optional[int] = None,
        rank_out: Optional[int] = None,
    ):
        self._links.append(_ChainLink(apply_fn, rank, rank_in, rank_out))
        return self

    def __call__(self, params_list: Sequence[Any], x):
        """In-graph forward.  ``params_list[i]`` feeds link i (replicated).

        Activation routing follows the reference's recv → compute → send walk
        (SURVEY.md §3.4) with ``ppermute`` edges instead of MPI.  The edge
        into link i is derived from its ``rank_in`` or the previous link's
        ``rank_out`` (validated for consistency); owners on the same rank
        need no edge."""
        assert len(params_list) == len(self._links)
        h = x
        for i, link in enumerate(self._links):
            src = None
            if link.rank_in is not None:
                src = link.rank_in
            if i > 0:
                prev = self._links[i - 1]
                # The only valid edge source is the previous link's owner —
                # validate BOTH declarations against it, whichever is given.
                if src is not None and src != prev.rank:
                    raise ValueError(
                        f"link {i} declares rank_in={src} but link "
                        f"{i - 1} is owned by rank {prev.rank}"
                    )
                if prev.rank_out is not None:
                    if prev.rank_out != link.rank:
                        raise ValueError(
                            f"link {i - 1} declares rank_out={prev.rank_out} "
                            f"but link {i} is owned by rank {link.rank}"
                        )
                    src = prev.rank
                if src is None and prev.rank != link.rank:
                    raise ValueError(
                        f"broken chain: link {i - 1} (rank {prev.rank}) → "
                        f"link {i} (rank {link.rank}) has no declared edge; "
                        f"set rank_out/rank_in"
                    )
            if src is not None and src != link.rank:
                h = send_recv(h, self.comm, [(src, link.rank)])
            h = link.apply(params_list[i], h)
            if link.rank_out is not None and i + 1 == len(self._links):
                # terminal send (to the output consumer)
                h = send_recv(h, self.comm, [(link.rank, link.rank_out)])
        return h

    def to_pipeline(self, io_shapes, n_microbatches: int):
        """Lower a LINEAR chain onto :class:`HeteroPipelineChain` — the
        distributed-speedup path (device ``s`` computes only stage ``s``)
        for the reference-shaped ``add_link`` API.

        Linear means: link ``i`` is owned by rank ``i`` and every edge goes
        ``i-1 → i`` (explicitly declared or implied), with no terminal
        send — exactly the shape of the reference's model-parallel examples
        (MNIST 2-rank split, VGG stacks).  Anything else (fan-in/fan-out,
        rank reuse, skips) stays on :class:`MultiNodeChainList`'s
        compute-replicated walk, which handles arbitrary graphs.

        ``io_shapes``/``n_microbatches`` are :class:`HeteroPipelineChain`'s:
        per-stage (in, out) shapes without the batch dim, and the GPipe
        microbatch count.  Returns the new chain; oracle-equivalence with
        the replicated walk is pinned by
        ``tests/links_tests/test_hetero_pipeline.py``.
        """
        S = len(self._links)
        if self.comm.size != S:
            raise ValueError(
                f"{S} links on a size-{self.comm.size} axis: the pipeline "
                "lowering needs exactly one stage per device"
            )
        for i, ln in enumerate(self._links):
            if ln.rank != i:
                raise ValueError(
                    f"link {i} owned by rank {ln.rank}: pipeline lowering "
                    "needs the identity placement (link i on rank i)"
                )
            if ln.rank_in not in (None, i - 1) or (
                i == 0 and ln.rank_in is not None
            ):
                raise ValueError(
                    f"link {i} has rank_in={ln.rank_in}: not a linear chain"
                )
            if ln.rank_out not in (None, i + 1) or (
                i == S - 1 and ln.rank_out is not None
            ):
                raise ValueError(
                    f"link {i} has rank_out={ln.rank_out}: not a linear "
                    "chain (terminal sends have no pipeline equivalent)"
                )
        return HeteroPipelineChain(
            self.comm,
            [ln.apply for ln in self._links],
            io_shapes,
            n_microbatches,
        )


class PipelineChain:
    """GPipe-style pipeline over homogeneous stacked stages.

    Args:
      stage_apply: ``stage_apply(stage_params, x) -> y`` with matching
        x/y shapes (e.g. one transformer block).
      comm: communicator whose (single) axis is the ``stage`` dimension;
        device s owns stage s.
      n_microbatches: how many microbatches the global batch splits into.

    Call inside ``shard_map``: ``pipe(stacked_params_local, x)`` where
    ``stacked_params_local`` is this device's stage slice (leading axis 1 of
    the stage-stacked params) and ``x`` is the full local batch (replicated
    input; stage 0 consumes it).  Returns the pipeline output (replicated).
    """

    def __init__(self, stage_apply: Callable, comm, n_microbatches: int):
        self.stage_apply = stage_apply
        self.comm = comm
        self.n_micro = n_microbatches

    def __call__(self, stage_params, x):
        comm = self.comm
        S = comm.size
        M = self.n_micro
        idx = comm.axis_index()
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        micro = x.reshape(M, B // M, *x.shape[1:])
        mb_shape = micro.shape[1:]

        fwd_pairs = [(s, s + 1) for s in range(S - 1)]

        def tick(buf, t):
            # Inject microbatch t at stage 0 (valid while t < M).
            t_in = jnp.minimum(t, M - 1)
            inj = lax.dynamic_index_in_dim(micro, t_in, axis=0, keepdims=False)
            is_stage0 = (idx == 0)
            cur = jnp.where(is_stage0, inj, buf)
            y = self.stage_apply(stage_params, cur)
            # Collect stage S-1's output on every device (psum-broadcast).
            mask = (idx == S - 1).astype(y.dtype)
            out = lax.psum(y * mask, comm.axis_name)
            # Shift activations one stage forward for the next tick.
            nxt = send_recv(y, comm, fwd_pairs)
            return nxt, out

        T = S + M - 1
        from chainermn_tpu.utils import pvary_to_match

        # The carry becomes device-varying after the first tick (ppermute +
        # stage compute); its initial type must match — including any OUTER
        # axes the INPUT already varies over when the pipeline is nested in
        # a wider program (the 4-axis ParallelLM).  Matched to x, not to
        # stage_params: param-only axes (e.g. tensor-parallel model) are
        # reduced INSIDE the stage, and over-typing the carry with them
        # would mark the whole pipeline output spuriously varying there.
        buf0 = pvary_to_match(
            jnp.zeros(mb_shape, x.dtype), x, axes=comm.axis_name,
        )
        _, outs = lax.scan(tick, buf0, jnp.arange(T))
        # Microbatch m leaves the last stage at tick (S - 1 + m).
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        return valid.reshape(B, *valid.shape[2:])


class HeteroPipelineChain:
    """GPipe pipelining for HETEROGENEOUS stages — the distributed-speedup
    path :class:`MultiNodeChainList` cannot provide under GSPMD (where a
    per-device branch predicate forces compute replication).

    The SPMD trick: all inter-stage activations live in one flat ``(b, F)``
    buffer (``F`` = the largest stage-boundary feature count, zero-padded),
    and each tick runs ``lax.switch(axis_index, branches, buffer)`` — XLA's
    ``Conditional`` executes ONLY the selected branch at runtime, so device
    ``s`` computes just stage ``s``: true heterogeneous compute
    distribution.  Microbatch schedule, output collection
    (psum mask at the last stage), and the ``ppermute`` shift are exactly
    :class:`PipelineChain`'s; backward is AD through scan + switch, and
    non-owner devices contribute zero grads for a stage, so the hybrid
    DP×MP reducer (:func:`~chainermn_tpu.optimizers.model_parallel_grad_reduce`'s
    pmean over the stage axis) restores full gradients everywhere.

    **Parameter memory, two tiers** (VERDICT r3 missing #4):

    * ``__call__(params_list, x)`` — replicated: every device holds all
      stages' params plus a per-step ``S x max_stage`` ravel/pad/stack
      buffer.  Simple (plain pytrees in), but a chain that doesn't fit one
      device has no path here.
    * :meth:`shard_params` + :meth:`apply_sharded` /
      :meth:`sharded_spmd_fn` — distributed: the ravel/pad/stack happens
      ONCE, placed with row ``s`` resident only on device ``s``
      (``NamedSharding`` over the stage axis), restoring the reference's
      each-rank-holds-only-its-own-links memory property
      (``multi_node_chain_list.py`` — SURVEY §2.5).  Per-device param
      bytes ≈ ``max_stage`` instead of ``sum(stages) + S x max_stage``,
      and the per-step stack disappears — asserted at compile time by
      ``tests/links_tests/test_hetero_sharded.py`` via ``memory_analysis``.

    Args:
      comm: communicator whose (single) axis is the stage dimension; its
        size must equal ``len(stages)``.
      stages: per-stage ``apply(params, x) -> y`` callables.
      io_shapes: per-stage ``(in_shape, out_shape)`` tuples WITHOUT the
        batch dim; consecutive stages must chain
        (``out_shape[i] == in_shape[i+1]``).
      n_microbatches: GPipe microbatch count (bubble fraction
        ``(S-1)/(S-1+M)``).

    Call inside ``shard_map``: ``chain(params_list, x)`` with ``x`` of
    shape ``(B, *io_shapes[0][0])`` replicated; returns the final stage's
    output ``(B, *io_shapes[-1][1])`` replicated.

    .. warning:: JAX ≤ 0.9.0 mis-routes ``lax.switch`` cotangents under
       the ``check_vma=True`` transpose when the branch index is
       device-varying (all closures collapse onto branch 0's operands);
       with the checker off, switch AD is exact — pinned by
       ``tests/links_tests/test_hetero_pipeline.py``.
       :meth:`as_spmd_fn` / :meth:`sharded_spmd_fn` pick the flag via
       :func:`switch_vma_safe` (version gate + numeric probe), so the
       debug-mode guarantee returns automatically on a fixed JAX; custom
       ``comm.spmd`` wrappers should pass
       ``check_vma=switch_vma_safe(comm.mesh)`` the same way.
    """

    def __init__(self, comm, stages: Sequence[Callable],
                 io_shapes: Sequence[Tuple[tuple, tuple]],
                 n_microbatches: int):
        if len(stages) != len(io_shapes):
            raise ValueError(
                f"{len(stages)} stages but {len(io_shapes)} io_shapes"
            )
        for i in range(len(stages) - 1):
            if tuple(io_shapes[i][1]) != tuple(io_shapes[i + 1][0]):
                raise ValueError(
                    f"stage {i} outputs {io_shapes[i][1]} but stage "
                    f"{i + 1} expects {io_shapes[i + 1][0]}"
                )
        self.comm = comm
        self.stages = list(stages)
        self.io_shapes = [
            (tuple(a), tuple(b)) for a, b in io_shapes
        ]
        self.n_micro = n_microbatches
        self._feat = [
            (int(np.prod(a)) if a else 1, int(np.prod(b)) if b else 1)
            for a, b in self.io_shapes
        ]
        self.buf_features = max(max(f) for f in self._feat)

    def __call__(self, params_list: Sequence[Any], x):
        comm = self.comm
        S = comm.size
        if S != len(self.stages):
            raise ValueError(
                f"{len(self.stages)} stages on a size-{S} axis (must match)"
            )
        # Each device needs only ITS stage's params inside the tick loop.
        # Feeding all stages' trees as switch operands every tick costs a
        # full copy of every stage's weights per tick (measured ~3x step
        # time); instead ravel each stage's tree to a flat vector, pad to
        # the longest, stack, and let each device select its row ONCE per
        # step — the switch then carries one vector + the activation buffer.
        # (:meth:`shard_params` lifts this same stack OUT of the step and
        # shards it over the stage axis — the 1/S-memory tier.)
        from jax.flatten_util import ravel_pytree

        flat_vecs, unravels = [], []
        for p in params_list:
            vec, unravel = ravel_pytree(p)
            flat_vecs.append(vec)
            unravels.append(unravel)
        lens = [int(v.shape[0]) for v in flat_vecs]
        Lmax = max(max(lens, default=0), 1)
        stacked = jnp.stack([
            jnp.pad(v, (0, Lmax - v.shape[0])) for v in flat_vecs
        ])  # (S, Lmax)
        mine = lax.dynamic_index_in_dim(
            stacked, comm.axis_index(), axis=0, keepdims=False
        )
        return self._pipeline(mine, x, lens, unravels)

    def _pipeline(self, mine, x, lens, unravels):
        """The tick loop, parameterized by THIS device's flat param row
        ``mine`` (however it was obtained: per-step stack+select in
        :meth:`__call__`, resident stage-sharded row in
        :meth:`apply_sharded`)."""
        comm = self.comm
        S = comm.size
        M = self.n_micro
        idx = comm.axis_index()
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        b = B // M
        F = self.buf_features
        dtype = x.dtype
        micro = x.reshape(M, b, -1)
        if micro.shape[-1] < F:
            micro = jnp.pad(micro, ((0, 0), (0, 0),
                                    (0, F - micro.shape[-1])))

        def apply_stage(s, pv, buf):  # (b, F) -> (b, F)
            in_feat, _ = self._feat[s]
            in_shape = self.io_shapes[s][0]
            inp = buf[:, :in_feat].reshape(b, *in_shape)
            p = unravels[s](pv[: lens[s]])
            y = self.stages[s](p, inp)
            yf = y.reshape(b, -1).astype(dtype)
            return jnp.pad(yf, ((0, 0), (0, F - yf.shape[1])))

        branches = [
            (lambda op, s=s: apply_stage(s, op[0], op[1])) for s in range(S)
        ]
        fwd_pairs = [(s, s + 1) for s in range(S - 1)]

        def tick(buf, t):
            t_in = jnp.minimum(t, M - 1)
            inj = lax.dynamic_index_in_dim(micro, t_in, axis=0,
                                           keepdims=False)
            cur = jnp.where(idx == 0, inj, buf)
            y = lax.switch(idx, branches, (mine, cur))
            mask = (idx == S - 1).astype(y.dtype)
            out = lax.psum(y * mask, comm.axis_name)
            nxt = send_recv(y, comm, fwd_pairs)
            return nxt, out

        T = S + M - 1
        from chainermn_tpu.utils import pvary_to_match

        # The carry becomes device-varying after the first tick (switch on
        # axis_index); the initial zeros must carry the same vma type —
        # matched to the inputs so nesting under extra mesh axes works.
        buf0 = pvary_to_match(
            jnp.zeros((b, F), dtype), x, mine, axes=comm.axis_name,
        )
        _, outs = lax.scan(tick, buf0, jnp.arange(T))
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        out_feat = self._feat[-1][1]
        out_shape = self.io_shapes[-1][1]
        return valid[:, :, :out_feat].reshape(B, *out_shape)

    # ------------------------------------------------- stage-sharded params
    def shard_params(self, params_list: Sequence[Any]):
        """Stage-shard the chain's parameters: the 1/S-memory tier.

        Ravels each stage's tree to a flat row, zero-pads to the longest
        stage, and builds the ``(S, Lmax)`` stack with row ``s`` resident
        ONLY on stage ``s``'s device(s) (``NamedSharding`` over the stage
        axis, assembled per-shard via ``make_array_from_callback`` so the
        full stack is never materialized on any single device — a chain
        that doesn't fit one device works).  Per-stage ravel metadata is
        cached on the chain for :meth:`apply_sharded` /
        :meth:`unshard_params`.

        Returns the sharded ``(S, Lmax)`` array — a single pytree leaf, so
        plain optax updates (elementwise) keep it sharded, and orbax
        checkpoints it like any other array.

        Dtype rule: one dtype per stage tree AND across stages (a flat
        row can't mix) — pass fp32 masters and cast inside the stage fn
        if you want mixed-precision compute.
        """
        S = len(self.stages)
        if S != self.comm.size:
            raise ValueError(
                f"{S} stages on a size-{self.comm.size} axis (must match; "
                "the sharded path places exactly one stage row per device)"
            )
        if len(params_list) != S:
            raise ValueError(
                f"{len(params_list)} param trees for {S} stages"
            )
        # Ravel on the HOST (numpy): jax.flatten_util.ravel_pytree would
        # concatenate on the default device, materializing the whole
        # chain's bytes there — defeating the point for a chain that
        # doesn't fit one device.
        vec_nps, unravels = [], []
        for i, p in enumerate(params_list):
            leaves, treedef = jax.tree_util.tree_flatten(p)
            arrs = [np.asarray(l) for l in leaves]
            dts = sorted({str(a.dtype) for a in arrs})
            if len(dts) > 1:
                raise ValueError(
                    f"stage {i} tree mixes dtypes {dts}: stage-sharded "
                    "rows need one dtype (cast inside the stage fn)"
                )
            vec_nps.append(
                np.concatenate([a.ravel() for a in arrs])
                if arrs else np.zeros((0,), np.float32)
            )
            unravels.append(_make_unravel(treedef, [a.shape for a in arrs]))
        dt = vec_nps[0].dtype
        for i, v in enumerate(vec_nps):
            if v.dtype != dt:
                raise ValueError(
                    f"stage {i} ravels to {v.dtype}, stage 0 to {dt}: "
                    "stage-sharded rows need one dtype"
                )
        lens = [int(v.shape[0]) for v in vec_nps]
        Lmax = max(max(lens, default=0), 1)
        self._shard_meta = (lens, unravels, Lmax)

        def cb(index):
            sel = range(S)[index[0]]
            return np.stack([
                np.pad(vec_nps[s], (0, Lmax - lens[s])) for s in sel
            ])

        return jax.make_array_from_callback(
            (S, Lmax), self.comm.rankwise_sharding(), cb
        )

    def unshard_params(self, stacked) -> List[Any]:
        """Gather a stage-sharded stack back to per-stage pytrees (host
        side — for export/inspection; checkpointing should save ``stacked``
        itself, which orbax handles sharded)."""
        lens, unravels, Lmax = self._require_shard_meta()
        rows = np.asarray(stacked)  # gathers all rows to host
        return [
            unravels[s](jnp.asarray(rows[s, : lens[s]]))
            for s in range(len(self.stages))
        ]

    def _require_shard_meta(self):
        meta = getattr(self, "_shard_meta", None)
        if meta is None:
            raise ValueError(
                "no stage-shard metadata: call shard_params(params_list) "
                "first (it caches the per-stage ravel structure this chain "
                "needs to unravel rows inside the step)"
            )
        return meta

    def apply_sharded(self, stacked_local, x):
        """Forward from the stage-sharded stack — call inside ``shard_map``
        with ``in_specs=(P(stage_axis), P())``: ``stacked_local`` is this
        device's ``(1, Lmax)`` row (its own stage's params, resident), so
        no per-step stack and no cross-device param gather exist; the only
        param traffic is zero."""
        lens, unravels, _ = self._require_shard_meta()
        return self._pipeline(stacked_local[0], x, lens, unravels)

    def sharded_spmd_fn(self):
        """``jit(shard_map(...))``-wrapped :meth:`apply_sharded`:
        ``(stacked, x) -> y`` with the stack split over the stage axis and
        ``x``/output replicated (``check_vma`` via
        :func:`switch_vma_safe` — see the class warning)."""
        from jax.sharding import PartitionSpec as P

        f = self.comm.spmd(
            lambda st, xx: self.apply_sharded(st, xx),
            in_specs=(P(self.comm.axes), P()),
            out_specs=P(),
            check_vma=switch_vma_safe(self.comm.mesh),
        )
        return jax.jit(f)

    def as_spmd_fn(self):
        """``jit(shard_map(...))``-wrapped forward ``(params_list, x) -> y``
        with replicated in/out specs and ``check_vma`` picked by
        :func:`switch_vma_safe` (see the class warning).  For custom
        losses, wrap :meth:`__call__` in
        ``comm.spmd(..., check_vma=switch_vma_safe(comm.mesh))``
        yourself."""
        from jax.sharding import PartitionSpec as P

        f = self.comm.spmd(
            lambda pl, xx: self(pl, xx),
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=switch_vma_safe(self.comm.mesh),
        )
        return jax.jit(f)
