"""Prefetching batch iterator over the native threaded batch assembler.

Reference analog: the ImageNet example's multiprocess data loading
(SURVEY.md §2.9 — Chainer ``MultiprocessIterator``) plus the pinned staging
buffers of ``_memory_utility.py``.  Worker threads in C++
(``_native/dataloader.cpp``) gather dataset rows into a ring of preassembled
batch buffers while the TPU runs the previous step; Python just wraps the
ready slot in numpy and hands it to ``device_put``.

Falls back to synchronous assembly when the native library can't build, so
the API is always available.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from chainermn_tpu import _native


class PrefetchIterator:
    """Epoch-aware iterator with native background batch assembly.

    Drop-in for :class:`~chainermn_tpu.iterators.SerialIterator` over
    array-backed datasets (anything exposing ``.arrays``: a tuple of
    row-major numpy arrays sharing their leading dim).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        repeat: bool = True,
        shuffle: bool = True,
        seed: Optional[int] = None,
        depth: int = 4,
        n_workers: int = 4,
        copy: bool = True,
    ):
        arrays = tuple(np.ascontiguousarray(a) for a in dataset.arrays)
        self._arrays = arrays  # keep alive: native loader reads these bases
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._depth = depth
        self._copy = copy
        self._n = len(arrays[0])

        lib = _native.load_dataloader()
        self._lib = lib
        self._h = None
        if lib is not None:
            bases = (ctypes.c_void_p * len(arrays))(
                *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
            )
            row_bytes = (ctypes.c_uint64 * len(arrays))(
                *[a.strides[0] for a in arrays]
            )
            strides = (ctypes.c_uint64 * len(arrays))(
                *[a.strides[0] for a in arrays]
            )
            self._h = lib.loader_create(
                bases, row_bytes, strides, len(arrays), batch_size,
                depth, n_workers,
            )
        self.reset()

    # ------------------------------------------------------------- ordering
    def reset(self):
        # Recycle the zero-copy held slot, then drain in-flight slots from a
        # previous run of the ring.
        if getattr(self, "_held_slot", None) is not None:
            self._lib.loader_release(self._h, self._held_slot)
        self._held_slot: Optional[int] = None
        if getattr(self, "_h", None) and getattr(self, "_pending", None):
            while self._pending:
                if self._pending.pop(0)[1] is None:  # native-assembled
                    slot = self._lib.loader_next(self._h, -1)
                    if slot >= 0:
                        self._lib.loader_release(self._h, slot)
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._consumed = 0  # samples consumed this epoch (not submitted)
        self._order = self._new_order()
        self._pos = 0
        # Per submitted batch: (epoch_completing, short_tail_indices_or_None).
        self._pending: list = []
        if self._h:
            for _ in range(self._depth):
                self._submit_next()

    def _new_order(self):
        return (
            self._rng.permutation(self._n)
            if self._shuffle
            else np.arange(self._n)
        )

    def _next_indices(self) -> Optional[Tuple[np.ndarray, bool]]:
        """Next batch's row indices + whether it completes an epoch — the
        exact semantics shared with SerialIterator (one implementation, so
        the two iterators cannot drift)."""
        from chainermn_tpu.iterators import _next_epoch_indices

        return _next_epoch_indices(self)

    def _submit_next(self) -> bool:
        nxt = self._next_indices()
        if nxt is None:
            return False
        idx, completes = nxt
        if len(idx) < self.batch_size:
            # repeat=False short tail: the native ring is fixed-batch, so
            # assemble this one in Python at consume time.
            self._pending.append((completes, idx))
            return True
        buf = idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        seq = self._lib.loader_submit(self._h, buf, len(idx))
        if seq < 0:
            raise RuntimeError(f"loader_submit failed (rc={seq})")
        self._pending.append((completes, None))
        return True

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        return self

    def __next__(self):
        if self._h:
            return self._next_native()
        return self._next_sync()

    def _next_native(self):
        if not self._pending:
            raise StopIteration
        # zero-copy mode hands out views into the slot: recycle the previous
        # slot only now, once the caller is done with its views.
        if self._held_slot is not None:
            self._lib.loader_release(self._h, self._held_slot)
            self._held_slot = None
        completes, tail_idx = self._pending.pop(0)
        if tail_idx is not None:  # Python-assembled short tail (repeat=False)
            self._finish_tick(completes, len(tail_idx))
            return tuple(a[tail_idx] for a in self._arrays)
        slot = self._lib.loader_next(self._h, -1)
        if slot < 0:
            raise RuntimeError(f"loader_next failed (rc={slot})")
        out = []
        for f, a in enumerate(self._arrays):
            ptr = self._lib.loader_slot_ptr(self._h, slot, f)
            shape = (self.batch_size,) + a.shape[1:]
            arr = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(int(np.prod(shape)) * a.dtype.itemsize,),
            ).view(a.dtype).reshape(shape)
            out.append(arr.copy() if self._copy else arr)
        if self._copy:
            self._lib.loader_release(self._h, slot)
        else:
            self._held_slot = slot
        self._finish_tick(completes, self.batch_size)
        self._submit_next()  # keep the ring full
        return tuple(out)

    def _next_sync(self):  # pure-Python fallback
        nxt = self._next_indices()
        if nxt is None:
            raise StopIteration
        idx, completes = nxt
        self._finish_tick(completes, len(idx))
        return tuple(a[idx] for a in self._arrays)

    def _finish_tick(self, completes: bool, n_samples: int):
        self.iteration += 1
        self._consumed += n_samples
        if completes:
            self.epoch += 1
            self.is_new_epoch = True
            self._consumed = 0
        else:
            self.is_new_epoch = False

    # --------------------------------------------------------- checkpointing
    def checkpoint_loop_state(self) -> dict:
        """Consumption-granular cursor for the multi-node checkpointer.

        The submission cursor (``_pos``) runs ``depth`` batches ahead of
        consumption in native mode, so the raw attributes must never be
        saved/restored directly (stale in-flight batches + a skewed cursor).
        ``pos`` here is SAMPLES CONSUMED this epoch; exact when checkpoints
        fire at epoch boundaries (all examples' ``(1, 'epoch')`` trigger —
        ``pos == 0``, a fresh permutation is drawn on restore) and
        best-effort mid-epoch (the epoch's remaining order is preserved,
        in-flight lookahead is discarded)."""
        mt, keys, pos, has_gauss, cached = self._rng.get_state()
        return {
            "pos": int(self._consumed),
            "order": np.asarray(self._order, np.int64),
            "rng_keys": np.asarray(keys, np.uint32),
            "rng_pos": int(pos),
            "rng_has_gauss": int(has_gauss),
            "rng_cached": float(cached),
        }

    def restore_loop_state(self, epoch: int, state: dict) -> None:
        """Restore from :meth:`checkpoint_loop_state`: drain the ring,
        reinstall the cursor, refill the lookahead from the restored order."""
        # Drain in-flight slots (same recycle discipline as reset()).
        if self._held_slot is not None:
            self._lib.loader_release(self._h, self._held_slot)
            self._held_slot = None
        if self._h and self._pending:
            while self._pending:
                if self._pending.pop(0)[1] is None:
                    slot = self._lib.loader_next(self._h, -1)
                    if slot >= 0:
                        self._lib.loader_release(self._h, slot)
        self.epoch = int(epoch)
        self.is_new_epoch = False
        self._rng.set_state((
            "MT19937",
            np.asarray(state["rng_keys"]).astype(np.uint32),
            int(state["rng_pos"]),
            int(state["rng_has_gauss"]),
            float(state["rng_cached"]),
        ))
        self._consumed = int(state["pos"])
        self._pos = int(state["pos"])
        self._order = (
            np.asarray(state["order"]).astype(np.int64)
            if int(state["pos"]) > 0
            else self._new_order()  # epoch boundary: fresh permutation
        )
        self._pending = []
        if self._h:
            for _ in range(self._depth):
                self._submit_next()

    @property
    def epoch_detail(self):
        # Consumption-based (the submission cursor runs `depth` batches ahead
        # in native mode and must not leak into schedules keyed on progress).
        return self.epoch + min(self._consumed / max(self._n, 1), 1.0)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.loader_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
