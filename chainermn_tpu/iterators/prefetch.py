"""Prefetching batch iterator over the native threaded batch assembler.

Reference analog: the ImageNet example's multiprocess data loading
(SURVEY.md §2.9 — Chainer ``MultiprocessIterator``) plus the pinned staging
buffers of ``_memory_utility.py``.  Worker threads in C++
(``_native/dataloader.cpp``) gather dataset rows into a ring of preassembled
batch buffers while the TPU runs the previous step; Python just wraps the
ready slot in numpy and hands it to ``device_put``.

Falls back to synchronous assembly when the native library can't build, so
the API is always available.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from chainermn_tpu import _native


class PrefetchIterator:
    """Epoch-aware iterator with native background batch assembly.

    Drop-in for :class:`~chainermn_tpu.iterators.SerialIterator` over
    array-backed datasets (anything exposing ``.arrays``: a tuple of
    row-major numpy arrays sharing their leading dim).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        repeat: bool = True,
        shuffle: bool = True,
        seed: Optional[int] = None,
        depth: int = 4,
        n_workers: int = 4,
        copy: bool = True,
    ):
        # A scatter_dataset SubDataset view composes for free: gather from
        # the BASE arrays through the view's index map, so the native
        # workers page rows (mmap'd file-backed data included) off the
        # consumer thread instead of materializing the shard up front.
        translate = None
        src = dataset
        if not hasattr(src, "arrays"):
            inner = getattr(src, "base", None)
            if inner is not None and hasattr(inner, "arrays") and hasattr(
                src, "indices"
            ):
                translate = np.ascontiguousarray(
                    np.asarray(src.indices, np.int64)
                )
                src = inner
            else:
                raise TypeError(
                    "PrefetchIterator needs an array-backed dataset "
                    "(`.arrays`) or a SubDataset view of one; got "
                    f"{type(dataset).__name__}"
                )
        # No-copy for already-contiguous arrays (incl. np.memmap — the
        # file stays the backing store).
        arrays = tuple(np.ascontiguousarray(a) for a in src.arrays)
        self._arrays = arrays  # keep alive: native loader reads these bases
        self._translate = translate
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._depth = depth
        self._copy = copy
        self._n = len(dataset)

        lib = _native.load_dataloader()
        self._lib = lib
        self._h = None
        if lib is not None:
            bases = (ctypes.c_void_p * len(arrays))(
                *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
            )
            row_bytes = (ctypes.c_uint64 * len(arrays))(
                *[a.strides[0] for a in arrays]
            )
            strides = (ctypes.c_uint64 * len(arrays))(
                *[a.strides[0] for a in arrays]
            )
            self._h = lib.loader_create(
                bases, row_bytes, strides, len(arrays), batch_size,
                depth, n_workers,
            )
        self.reset()

    # ------------------------------------------------------------- ordering
    def reset(self):
        # Recycle the zero-copy held slot, then drain in-flight slots from a
        # previous run of the ring.
        if getattr(self, "_held_slot", None) is not None:
            self._lib.loader_release(self._h, self._held_slot)
        self._held_slot: Optional[int] = None
        if getattr(self, "_h", None) and getattr(self, "_pending", None):
            while self._pending:
                if self._pending.pop(0)[1] is None:  # native-assembled
                    slot = self._lib.loader_next(self._h, -1)
                    if slot >= 0:
                        self._lib.loader_release(self._h, slot)
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._consumed = 0  # samples consumed this epoch (not submitted)
        # Per-epoch (order, rng before/after its draw) in draw order; front =
        # the epoch currently being CONSUMED.  Lets the checkpoint cursor
        # stay exact even when the submission side has already drawn later
        # epochs' permutations (lookahead ring).
        self._epoch_log = []
        self._order = self._new_order()
        self._pos = 0
        # Per submitted batch: (epoch_completing, short_tail_indices_or_None).
        self._pending: list = []
        if self._h:
            for _ in range(self._depth):
                self._submit_next()

    def _new_order(self):
        rng_before = self._rng.get_state()
        order = (
            self._rng.permutation(self._n)
            if self._shuffle
            else np.arange(self._n)
        )
        self._epoch_log.append({
            "order": np.asarray(order, np.int64),
            "rng_before": rng_before,
            "rng_after": self._rng.get_state(),
        })
        return order

    def _next_indices(self):
        """Next batch's ``(row indices, completes_epoch, wrapped)`` — the
        exact semantics shared with SerialIterator (one implementation, so
        the two iterators cannot drift)."""
        from chainermn_tpu.iterators import _next_epoch_indices

        return _next_epoch_indices(self)

    def _submit_next(self) -> bool:
        nxt = self._next_indices()
        if nxt is None:
            return False
        idx, completes, wrapped = nxt
        if self._translate is not None:  # shard position → base row
            idx = np.ascontiguousarray(self._translate[idx])
        if len(idx) < self.batch_size:
            # repeat=False short tail: the native ring is fixed-batch, so
            # assemble this one in Python at consume time.
            self._pending.append((completes, idx, wrapped))
            return True
        buf = idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        seq = self._lib.loader_submit(self._h, buf, len(idx))
        if seq < 0:
            raise RuntimeError(f"loader_submit failed (rc={seq})")
        self._pending.append((completes, None, wrapped))
        return True

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        return self

    def __next__(self):
        if self._h:
            return self._next_native()
        return self._next_sync()

    def _next_native(self):
        if not self._pending:
            raise StopIteration
        # zero-copy mode hands out views into the slot: recycle the previous
        # slot only now, once the caller is done with its views.
        if self._held_slot is not None:
            self._lib.loader_release(self._h, self._held_slot)
            self._held_slot = None
        completes, tail_idx, wrapped = self._pending.pop(0)
        if tail_idx is not None:  # Python-assembled short tail (repeat=False)
            self._finish_tick(completes, len(tail_idx), wrapped)
            return tuple(a[tail_idx] for a in self._arrays)
        slot = self._lib.loader_next(self._h, -1)
        if slot < 0:
            raise RuntimeError(f"loader_next failed (rc={slot})")
        out = []
        for f, a in enumerate(self._arrays):
            ptr = self._lib.loader_slot_ptr(self._h, slot, f)
            shape = (self.batch_size,) + a.shape[1:]
            arr = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(int(np.prod(shape)) * a.dtype.itemsize,),
            ).view(a.dtype).reshape(shape)
            out.append(arr.copy() if self._copy else arr)
        if self._copy:
            self._lib.loader_release(self._h, slot)
        else:
            self._held_slot = slot
        self._finish_tick(completes, self.batch_size, wrapped)
        self._submit_next()  # keep the ring full
        return tuple(out)

    def _next_sync(self):  # pure-Python fallback
        nxt = self._next_indices()
        if nxt is None:
            raise StopIteration
        idx, completes, wrapped = nxt
        if self._translate is not None:  # shard position → base row
            idx = self._translate[idx]
        self._finish_tick(completes, len(idx), wrapped)
        return tuple(a[idx] for a in self._arrays)

    def _finish_tick(self, completes: bool, n_samples: int, wrapped: int = 0):
        self.iteration += 1
        self._consumed += n_samples
        if completes:
            self.epoch += 1
            self.is_new_epoch = True
            # A boundary-spanning batch (n % batch_size != 0, repeat=True)
            # already consumed `wrapped` samples of the NEXT epoch — the
            # cursor must carry them or a mid-epoch checkpoint in the new
            # epoch is silently offset by that many samples.
            self._consumed = int(wrapped)
            if self._epoch_log:  # consumed epoch done; front = next epoch
                self._epoch_log.pop(0)
        else:
            self.is_new_epoch = False

    # --------------------------------------------------------- checkpointing
    def checkpoint_loop_state(self) -> dict:
        """Consumption-granular cursor for the multi-node checkpointer.

        The submission cursor (``_pos``) runs ``depth`` batches ahead of
        consumption in native mode, so the raw attributes must never be
        saved/restored directly (stale in-flight batches + a skewed cursor).
        ``pos`` here is SAMPLES CONSUMED this epoch.  EXACT everywhere: the
        per-epoch draw log reconstructs the consumption epoch's permutation
        and the RNG state as of just after (mid-epoch) or just before
        (boundary — restore's fresh draw then reproduces the very same
        upcoming permutation) its draw, no matter how far the lookahead has
        run ahead."""
        ent = self._epoch_log[0] if self._epoch_log else None
        if int(self._consumed) > 0 and ent is not None:
            # Mid-epoch: this epoch's order + the RNG just after its draw,
            # so post-restore wraps continue the original draw sequence.
            rng_state = ent["rng_after"]
            order = ent["order"]
            pos = int(self._consumed)
        else:
            # Epoch boundary: restore draws fresh from this state, which is
            # the state the upcoming epoch's order was (or will be) drawn
            # from — the draw reproduces it exactly.
            if ent is not None and ent["rng_before"] is not None:
                rng_state = ent["rng_before"]
            else:
                rng_state = self._rng.get_state()
            order = self._order
            pos = 0
        mt, keys, rpos, has_gauss, cached = rng_state
        return {
            "pos": pos,
            "order": np.asarray(order, np.int64),
            "rng_keys": np.asarray(keys, np.uint32),
            "rng_pos": int(rpos),
            "rng_has_gauss": int(has_gauss),
            "rng_cached": float(cached),
        }

    def restore_loop_state(self, epoch: int, state: dict) -> None:
        """Restore from :meth:`checkpoint_loop_state`: drain the ring,
        reinstall the cursor, refill the lookahead from the restored order."""
        # Drain in-flight slots (same recycle discipline as reset()).
        if self._held_slot is not None:
            self._lib.loader_release(self._h, self._held_slot)
            self._held_slot = None
        if self._h and self._pending:
            while self._pending:
                if self._pending.pop(0)[1] is None:
                    slot = self._lib.loader_next(self._h, -1)
                    if slot >= 0:
                        self._lib.loader_release(self._h, slot)
        self.epoch = int(epoch)
        self.is_new_epoch = False
        self._rng.set_state((
            "MT19937",
            np.asarray(state["rng_keys"]).astype(np.uint32),
            int(state["rng_pos"]),
            int(state["rng_has_gauss"]),
            float(state["rng_cached"]),
        ))
        self._consumed = int(state["pos"])
        self._pos = int(state["pos"])
        self._epoch_log = []
        if int(state["pos"]) > 0:
            self._order = np.asarray(state["order"]).astype(np.int64)
            # Seed the draw log: RNG is this epoch's post-draw state, so
            # later wraps continue the original permutation sequence.
            self._epoch_log.append({
                "order": self._order,
                "rng_before": None,
                "rng_after": self._rng.get_state(),
            })
        else:
            # Epoch boundary: fresh draw (reproduces the upcoming epoch's
            # permutation — the saved RNG state predates its draw).
            self._order = self._new_order()
        self._pending = []
        if self._h:
            for _ in range(self._depth):
                self._submit_next()

    @property
    def epoch_detail(self):
        # Consumption-based (the submission cursor runs `depth` batches ahead
        # in native mode and must not leak into schedules keyed on progress).
        return self.epoch + min(self._consumed / max(self._n, 1), 1.0)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.loader_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
