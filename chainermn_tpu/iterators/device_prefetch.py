"""Device-side batch prefetching: overlap host→device transfer with compute.

Reference analog: the staging half of ``_memory_utility.py``'s pinned host
buffers (SURVEY.md §2.1) — the reference overlapped H2D copies with compute
via pinned memory + CUDA streams.  The TPU-native equivalent exploits JAX's
asynchronous dispatch: ``device_put`` returns immediately with the transfer
in flight, so submitting batch *k+depth* while the step consumes batch *k*
hides the transfer entirely behind compute.  No threads are needed — the
queue discipline alone creates the overlap.

Composes with :class:`~chainermn_tpu.iterators.prefetch.PrefetchIterator`
(native worker threads assemble batches from dataset rows) to cover the full
input path: rows → host batch (C++ ring, ahead of time) → device batch
(async transfer, ahead of time) → jitted step.
"""

from __future__ import annotations

from collections import deque
from typing import Any, NamedTuple, Optional

import numpy as np


def _leading_dim(batch: Any) -> int:
    if isinstance(batch, (tuple, list)):
        return _leading_dim(batch[0])
    return int(np.shape(batch)[0])


class _Entry(NamedTuple):
    batch: Any
    epoch: int
    is_new_epoch: bool
    iteration: int
    epoch_detail: float
    n_samples: int
    # Inner iterator's checkpoint state captured just BEFORE this batch was
    # pulled: restoring from it replays this batch and everything after it —
    # the exact resume point while the batch sits unconsumed in the queue.
    resume: Optional[dict]


class DevicePrefetchIterator:
    """Keeps up to ``depth`` batches resident on device, mesh-sharded.

    Wraps any epoch-aware host iterator (:class:`SerialIterator`,
    :class:`PrefetchIterator`, …); each yielded batch is already the result
    of ``comm.shard_batch`` — device arrays whose transfer was issued one or
    more steps ago.  Epoch bookkeeping (``epoch`` / ``is_new_epoch`` /
    ``iteration`` / ``epoch_detail``) reflects the CONSUMED batch, not the
    wrapped iterator's (submission-time) cursor, so trainer triggers fire at
    the same ticks as without the wrapper.
    """

    def __init__(self, iterator, comm, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._it = iterator
        self._comm = comm
        self._depth = depth
        self._queue: deque = deque()
        self._exhausted = False
        self.epoch = int(getattr(iterator, "epoch", 0))
        self.iteration = int(getattr(iterator, "iteration", 0))
        self.is_new_epoch = False
        self._epoch_detail = float(getattr(iterator, "epoch_detail", 0.0))
        self._fill()

    # ------------------------------------------------------------- pipeline
    def _fill(self) -> None:
        while not self._exhausted and len(self._queue) < self._depth:
            resume = self._snapshot_inner()
            try:
                host = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            # Async: the transfer is in flight the moment shard_batch
            # returns; it completes while earlier batches are consumed.
            self._queue.append(
                _Entry(
                    batch=self._comm.shard_batch(host),
                    epoch=int(getattr(self._it, "epoch", 0)),
                    is_new_epoch=bool(
                        getattr(self._it, "is_new_epoch", False)
                    ),
                    iteration=int(getattr(self._it, "iteration", 0)),
                    epoch_detail=float(
                        getattr(self._it, "epoch_detail", 0.0)
                    ),
                    n_samples=_leading_dim(host),
                    resume=resume,
                )
            )

    def __iter__(self):
        return self

    def __next__(self):
        if not self._queue:
            raise StopIteration
        e = self._queue.popleft()
        self.epoch = e.epoch
        self.is_new_epoch = e.is_new_epoch
        self.iteration = e.iteration
        self._epoch_detail = e.epoch_detail
        self._fill()
        return e.batch

    @property
    def epoch_detail(self) -> float:
        return self._epoch_detail

    # ---------------------------------------------------------- delegation
    def reset(self) -> None:
        self._it.reset()
        self._queue.clear()
        self._exhausted = False
        self.epoch = int(getattr(self._it, "epoch", 0))
        self.iteration = int(getattr(self._it, "iteration", 0))
        self.is_new_epoch = False
        self._epoch_detail = 0.0
        self._fill()

    def close(self) -> None:
        self._queue.clear()
        close = getattr(self._it, "close", None)
        if close is not None:
            close()

    def __getattr__(self, name):
        # Passthrough for batch_size/_n/dataset/... (ProgressBar totals etc).
        it = self.__dict__.get("_it")
        if it is None:  # guard against recursion before __init__ ran
            raise AttributeError(name)
        return getattr(it, name)

    @property
    def _pos(self):
        """Consumption-adjusted cursor.  The checkpointer's raw-attribute
        fallback (for inner iterators exposing ``_pos`` but neither
        checkpoint protocol) must not see the inner SUBMISSION cursor —
        it runs up to ``depth`` batches ahead of what the trainer consumed."""
        pos = getattr(self.__dict__["_it"], "_pos", 0)
        queued = sum(e.n_samples for e in self._queue)
        boundary = any(e.is_new_epoch for e in self._queue)
        if queued and not boundary and pos >= queued:
            return pos - queued
        return pos

    @_pos.setter
    def _pos(self, value):
        setattr(self.__dict__["_it"], "_pos", value)

    # ------------------------------------------------------- checkpointing
    def _snapshot_inner(self) -> Optional[dict]:
        """Inner iterator's current checkpoint state.  Works over both
        protocols: an inner ``checkpoint_loop_state`` (PrefetchIterator) is
        delegated to; a SerialIterator-shaped inner
        (``_pos``/``_order``/``_rng``) has the equivalent state synthesized
        here.  ``None`` when the inner is neither (checkpointer falls back
        to raw attributes)."""
        inner = getattr(self._it, "checkpoint_loop_state", None)
        if inner is not None:
            return inner()
        if hasattr(self._it, "_order") and hasattr(self._it, "_rng"):
            it = self._it
            mt, keys, pos, has_gauss, cached = it._rng.get_state()
            return {
                "pos": int(it._pos),
                "order": np.asarray(it._order, np.int64),
                "rng_keys": np.asarray(keys, np.uint32),
                "rng_pos": int(pos),
                "rng_has_gauss": int(has_gauss),
                "rng_cached": float(cached),
            }
        return None

    def checkpoint_loop_state(self) -> Optional[dict]:
        """Consumption-granular cursor for the multi-node checkpointer.

        EXACT at every tick: each queue entry carries the inner state
        captured just before that batch was pulled, so the snapshot for the
        oldest unconsumed batch replays the queue's contents precisely —
        epoch boundaries in flight included.  (The former pos-arithmetic
        adjustment degraded to a flagged best-effort cursor whenever a
        queued batch crossed an epoch boundary.)"""
        if self._queue:
            return self._queue[0].resume
        return self._snapshot_inner()

    def restore_loop_state(self, epoch: int, state: dict) -> None:
        self._queue.clear()
        self._exhausted = False
        inner = getattr(self._it, "restore_loop_state", None)
        if inner is not None:
            inner(epoch, state)
        else:
            it = self._it
            it.epoch = int(epoch)
            it.is_new_epoch = False
            it._pos = int(state["pos"])
            it._order = np.asarray(state["order"]).astype(np.int64)
            it._rng.set_state((
                "MT19937",
                np.asarray(state["rng_keys"]).astype(np.uint32),
                int(state["rng_pos"]),
                int(state["rng_has_gauss"]),
                float(state["rng_cached"]),
            ))
        self.epoch = int(getattr(self._it, "epoch", epoch))
        self.iteration = int(getattr(self._it, "iteration", 0))
        self.is_new_epoch = False
        self._fill()


def create_device_prefetch_iterator(iterator, communicator, depth: int = 2):
    """Wrap ``iterator`` so batches are mesh-sharded device arrays whose
    host→device transfer overlaps the previous steps' compute."""
    return DevicePrefetchIterator(iterator, communicator, depth=depth)
