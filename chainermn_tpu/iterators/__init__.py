"""Iterators.

Reference anchors: ``chainermn/iterators/multi_node_iterator.py —
create_multi_node_iterator`` (master rank iterates, broadcasts each batch) and
``chainermn/iterators/synchronized_iterator.py — create_synchronized_iterator``
(identical RNG seed on every rank so all draw the same batches).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _next_epoch_indices(it):
    """Advance an epoch-ordered iterator one batch.

    Shared by :class:`SerialIterator` and
    :class:`~chainermn_tpu.iterators.prefetch.PrefetchIterator` (duck-typed on
    ``_pos``/``_order``/``_n``/``batch_size``/``_repeat``/``_new_order``) so
    their epoch semantics cannot drift apart.  Returns ``(indices,
    completes_epoch, wrapped)`` — ``wrapped`` is how many of the indices
    came from the NEXT epoch's order (a boundary-spanning batch when
    ``n % batch_size != 0``) — or ``None`` when a non-repeating pass is
    exhausted.

    Semantics: epoch bookkeeping belongs to the batch that COMPLETES a pass
    (also with ``repeat=False``, so ``(N, 'epoch')``-triggered extensions fire
    on the final batch of a finite pass); a batch spanning the boundary wraps
    with the NEXT epoch's freshly shuffled order — wrapping with the head of
    the old permutation would repeat those samples in the coming pass.
    """
    n = it._n
    if it._pos >= n:
        if not it._repeat:
            return None
        it._order = it._new_order()
        it._pos = 0
    idx = it._order[it._pos : it._pos + it.batch_size]
    it._pos += it.batch_size
    completes = it._pos >= n
    wrapped = 0
    if len(idx) < it.batch_size and it._repeat:
        it._order = it._new_order()
        extra = it._order[: it.batch_size - len(idx)]
        idx = np.concatenate([idx, extra])
        it._pos = len(extra)
        wrapped = len(extra)
    return np.asarray(idx, np.int64), completes, wrapped


class SerialIterator:
    """Minimal epoch-aware batch iterator (the Chainer ``SerialIterator``
    shape the trainer loop consumes).  Yields tuples of stacked numpy arrays
    for tuple datasets."""

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._n = len(dataset)
        self.reset()

    def reset(self):
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0

    def _new_order(self):
        n = len(self.dataset)
        return self._rng.permutation(n) if self._shuffle else np.arange(n)

    def __iter__(self):
        return self

    def __next__(self):
        nxt = _next_epoch_indices(self)
        if nxt is None:
            raise StopIteration
        idx, completes, _wrapped = nxt
        self.iteration += 1
        if completes:
            self.epoch += 1
            self.is_new_epoch = True
        else:
            self.is_new_epoch = False
        batch = [self.dataset[int(i)] for i in idx]
        return self._stack(batch)

    @staticmethod
    def _stack(batch):
        if isinstance(batch[0], tuple):
            return tuple(np.stack([b[i] for b in batch]) for i in range(len(batch[0])))
        return np.stack(batch)

    @property
    def epoch_detail(self):
        return self.epoch + self._pos / max(len(self.dataset), 1)


class _MultiNodeIterator:
    """Master process iterates; every process sees the master's batch."""

    def __init__(self, actual_iterator, comm, rank_master: int = 0):
        self.actual = actual_iterator
        self.comm = comm
        self.rank_master = rank_master

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self.actual)
        # Object-plane broadcast — identity single-process, gRPC multi-host.
        return self.comm.bcast_obj(batch, root=self.rank_master)

    def __getattr__(self, name):
        return getattr(self.actual, name)


def create_multi_node_iterator(actual_iterator, communicator, rank_master: int = 0):
    """Reference anchor: ``create_multi_node_iterator`` — for datasets that
    cannot be scattered; replicas receive the master's batches."""
    return _MultiNodeIterator(actual_iterator, communicator, rank_master)


def create_synchronized_iterator(actual_iterator, communicator):
    """Reference anchor: ``create_synchronized_iterator`` — all ranks draw
    identical batches.  Under a single controller every device already sees
    the same stream, so synchronization reduces to broadcasting the master's
    RNG-driven batches; we reuse the multi-node iterator mechanism."""
    return _MultiNodeIterator(actual_iterator, communicator, rank_master=0)


from chainermn_tpu.iterators.prefetch import PrefetchIterator  # noqa: E402
from chainermn_tpu.iterators.device_prefetch import (  # noqa: E402
    DevicePrefetchIterator,
    create_device_prefetch_iterator,
)
