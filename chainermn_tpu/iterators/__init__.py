"""Iterators.

Reference anchors: ``chainermn/iterators/multi_node_iterator.py —
create_multi_node_iterator`` (master rank iterates, broadcasts each batch) and
``chainermn/iterators/synchronized_iterator.py — create_synchronized_iterator``
(identical RNG seed on every rank so all draw the same batches).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class SerialIterator:
    """Minimal epoch-aware batch iterator (the Chainer ``SerialIterator``
    shape the trainer loop consumes).  Yields tuples of stacked numpy arrays
    for tuple datasets."""

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.reset()

    def reset(self):
        self.epoch = 0
        self.iteration = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        self._pos = 0

    def _new_order(self):
        n = len(self.dataset)
        return self._rng.permutation(n) if self._shuffle else np.arange(n)

    def __iter__(self):
        return self

    def __next__(self):
        n = len(self.dataset)
        if self._pos >= n:
            if not self._repeat:
                raise StopIteration
            self._order = self._new_order()
            self._pos = 0
        idx = self._order[self._pos : self._pos + self.batch_size]
        if len(idx) < self.batch_size and self._repeat:
            # wrap to keep static batch shapes (XLA needs them)
            extra = self._order[: self.batch_size - len(idx)]
            idx = np.concatenate([idx, extra])
        self._pos += self.batch_size
        self.iteration += 1
        # Epoch bookkeeping happens on the batch that COMPLETES the pass, so
        # stop=(N, 'epoch') sees exactly N passes with no stray extra batch.
        if self._pos >= n and self._repeat:
            self.epoch += 1
            self.is_new_epoch = True
        else:
            self.is_new_epoch = False
        batch = [self.dataset[int(i)] for i in idx]
        return self._stack(batch)

    @staticmethod
    def _stack(batch):
        if isinstance(batch[0], tuple):
            return tuple(np.stack([b[i] for b in batch]) for i in range(len(batch[0])))
        return np.stack(batch)

    @property
    def epoch_detail(self):
        return self.epoch + self._pos / max(len(self.dataset), 1)


class _MultiNodeIterator:
    """Master process iterates; every process sees the master's batch."""

    def __init__(self, actual_iterator, comm, rank_master: int = 0):
        self.actual = actual_iterator
        self.comm = comm
        self.rank_master = rank_master

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self.actual)
        # Object-plane broadcast — identity single-process, gRPC multi-host.
        return self.comm.bcast_obj(batch, root=self.rank_master)

    def __getattr__(self, name):
        return getattr(self.actual, name)


def create_multi_node_iterator(actual_iterator, communicator, rank_master: int = 0):
    """Reference anchor: ``create_multi_node_iterator`` — for datasets that
    cannot be scattered; replicas receive the master's batches."""
    return _MultiNodeIterator(actual_iterator, communicator, rank_master)


def create_synchronized_iterator(actual_iterator, communicator):
    """Reference anchor: ``create_synchronized_iterator`` — all ranks draw
    identical batches.  Under a single controller every device already sees
    the same stream, so synchronization reduces to broadcasting the master's
    RNG-driven batches; we reuse the multi-node iterator mechanism."""
    return _MultiNodeIterator(actual_iterator, communicator, rank_master=0)


from chainermn_tpu.iterators.prefetch import PrefetchIterator  # noqa: E402
