"""Multi-host bootstrap — the ``jax.distributed`` control plane.

TPU-native equivalent of the reference's MPI process bootstrap
(``chainermn/communicators/_communication_utility.py`` — ``init_ranks`` /
``init_intra_mpi_comm`` / ``init_inter_mpi_comm``; SURVEY.md §2.1 "MPI
binding" and §3.1 ``create_communicator`` call stack).  Where the reference
relied on ``mpiexec`` to spawn N processes and ``MPI_COMM_WORLD`` to find
them, a TPU pod job runs one process per host and finds its peers through
the JAX coordination service (a gRPC server on process 0, reached over DCN).

``init_distributed()`` must run before any other JAX call, exactly like
``MPI_Init`` had to run before any MPI call.  After it, ``jax.devices()``
is the *global* device list, ``jax.process_index()``/``process_count()``
play the role of MPI rank/size on the control plane, and every communicator
built afterwards spans the whole job.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence


_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
    cpu_collectives: Optional[str] = None,
) -> None:
    """Initialize the multi-process JAX runtime (reference: MPI bootstrap).

    On Cloud TPU pods all arguments are auto-detected from the TPU metadata
    environment — call with no arguments, once, at program start.  Off-pod
    (CI, CPU simulation, bring-your-own cluster) pass them explicitly or via
    env: ``CMN_COORDINATOR`` (``ip:port``), ``CMN_NUM_PROCESSES``,
    ``CMN_PROCESS_ID``.

    Args:
      coordinator_address: ``ip:port`` of process 0's coordination service.
      num_processes: total process count (the ``mpiexec -n`` analog).
      process_id: this process's id (the MPI rank analog).
      local_device_ids: restrict this process to a subset of local devices.
      cpu_collectives: cross-process collective implementation for the CPU
        backend (``"gloo"`` or ``"mpi"``) — the CI analog of the reference
        running its whole test suite under ``mpiexec -n 2`` on one box
        (SURVEY.md §4).
    """
    global _initialized
    if _initialized:
        return

    coordinator_address = coordinator_address or os.environ.get("CMN_COORDINATOR")
    if num_processes is None and os.environ.get("CMN_NUM_PROCESSES"):
        num_processes = int(os.environ["CMN_NUM_PROCESSES"])
    if process_id is None and os.environ.get("CMN_PROCESS_ID"):
        process_id = int(os.environ["CMN_PROCESS_ID"])

    import jax

    if cpu_collectives:
        jax.config.update("jax_cpu_collectives_implementation", cpu_collectives)

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(**kwargs)
    _initialized = True


def shutdown_distributed() -> None:
    """Tear down the coordination service connection (MPI_Finalize analog)."""
    global _initialized
    if not _initialized:
        return
    import jax

    jax.distributed.shutdown()
    _initialized = False


def is_initialized() -> bool:
    return _initialized
