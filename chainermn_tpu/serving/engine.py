"""Fixed-shape continuous-batching decode engine over the paged KV pool.

The TPU-idiomatic serving loop is ONE jitted decode step whose shapes never
change: ``capacity`` slots × 1 token, every iteration, forever.  Slot churn
(requests finishing, new prompts admitted) only changes the *contents* of
the step's inputs — the block tables, position vector, live mask, RNG lanes
and temperatures — never their shapes or dtypes, so the steady-state loop
compiles **exactly once** (``tests/serving_tests/test_engine.py`` pins this
with a compilation-count guard).  Idle slots ride along masked: their cache
writes are parked on reserved block 0 and their sampled tokens discarded.

One step = gather block tables → paged decode attention
(:func:`~chainermn_tpu.ops.paged_decode_attention` under
``decode_attention="fused"``, the gathered einsum fallback otherwise) →
per-slot sampling (independent RNG lanes, per-slot temperature, engine-wide
``top_k``).

**Speculative decoding** (``draft_model``/``spec_k``): the hot loop becomes
one jitted *round* instead — ``k`` sequential draft proposals per slot
(plus one backfill forward for the last proposal's K/V), then ONE target
verify forward over all ``k + 1`` positions (the paged kernel's
multi-query mode — per-position causality inside the chunk), greedy
prefix acceptance per slot.  A round costs ``k + 1`` draft steps + one
target forward and emits 1..``k + 1`` tokens per slot; greedy output is
exactly the target's own generation (speculation changes the schedule,
never the tokens — Leviathan et al. 2023), and sampling slots simply
accept zero drafts and sample the verify step's position-0 logits, which
ARE the plain step's logits under the same stateless RNG key.  The draft
owns its own block pools but **shares the target's block tables and
allocator**, so admission, prefix sharing, eviction and rollback stay one
accounting decision: a rejected tail is rolled back by *not advancing*
the slot's position — its stale K/V (both pools) is causally masked and
overwritten by later writes, never copied.

**Prefix sharing** (``prefix_cache=True``): the engine owns a
:class:`~chainermn_tpu.serving.prefix_cache.PrefixCache` over its
allocator; the scheduler maps cached prompt blocks at admission and COWs
shared partial blocks through :meth:`DecodeEngine.cow_copy` (one jitted
whole-block copy across every layer of every pool — target and draft).

Prefill runs through a second single-row jitted program in chunks drawn
from a small fixed **ladder** of geometries (``prefill_ladder``, by
default ``prefill_chunk`` and its halves down to 8 — one slot per call;
prefill compute scales with every padded row, so a capacity-wide
variant would pay the full ``capacity x chunk`` forward even when a
single slot is refilling): each chunk writes its K/V into the slot's
blocks and the final chunk samples the first generated token from the
last real prompt position's logits.  Chunking bounds prefill's latency
footprint so the scheduler can interleave decode steps between chunks
(iteration-level scheduling, Yu et al. 2022, *Orca*); the ladder bounds
the final chunk's padding waste (a short tail pays the nearest ladder
size, not the full ``prefill_chunk``) at a bounded, admission-path-only
compile cost — at most ``len(prefill_ladder)`` prefill variants, ever,
and still exactly ONE decode-step variant.  A speculative engine's
prefill also runs the draft model over the same chunk (headless —
``return_hidden``), so the draft's cache tracks the target's.

Host↔device traffic per decode step: small int32 control vectors up
(tokens/positions/tables/mask) and the sampled tokens down (``(capacity,)``
plain; ``(capacity, k+1)`` + per-slot acceptance for a speculative round).
Pool accounting stays host-side (:mod:`~chainermn_tpu.serving.kv_pool`) —
no device sync beyond the token readback serving fundamentally needs for
EOS detection.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

from chainermn_tpu.serving.kv_pool import PagedKVPool
from chainermn_tpu.serving.prefix_cache import PrefixCache


class DecodeEngine:
    """Continuous-batching decode over a :class:`PagedKVPool`.

    Args:
      model: a :class:`~chainermn_tpu.models.TransformerLM`.  Works with
        either ``decode_attention`` setting — "fused" runs the paged Pallas
        kernel in the hot loop, "einsum" the gathered fallback.
      params: the model's parameter pytree.
      capacity: decode slots per step (the fixed batch dimension).
      num_blocks: physical blocks in the pool (block 0 stays reserved).
      block_len: positions per block.
      max_blocks_per_slot: block-table width — caps a request at
        ``max_blocks_per_slot * block_len`` total positions.  Defaults to
        covering ``model.max_len``.
      prefill_chunk: largest prompt-tokens-per-prefill-call geometry.
      prefill_ladder: the full set of allowed prefill chunk sizes
        (must contain its max == ``prefill_chunk``).  Defaults to
        ``prefill_chunk`` and its successive halves down to 8.  Each
        size is one compiled prefill variant (admission path only — the
        decode step stays a single variant).
      top_k: engine-wide sampling truncation (0 = off; static — part of
        the compiled program).
      draft_model: optional draft :class:`TransformerLM` for speculative
        decoding (same vocab; depth/width free).  Requires ``spec_k``.
      draft_params: the draft's parameter pytree.
      spec_k: draft proposals per round (0 = speculation off).
      prefix_cache: share identical prompt prefixes through a refcounted
        block trie (on by default).  Cached blocks survive their writers
        until pool pressure or :meth:`drop_prefix_cache` releases them.
      mesh: optional 1-D ``jax.sharding.Mesh`` with a ``"model"`` axis
        (:func:`~chainermn_tpu.serving.sharding.serving_mesh`): the
        engine becomes TENSOR-PARALLEL over it — params sharded per
        :func:`~chainermn_tpu.serving.sharding.param_spec`, the paged KV
        pools (target AND draft) sharded kv-head-major on axis 0, block
        tables / allocator / prefix trie untouched (pure host
        bookkeeping over block ids), control vectors uploaded
        replicated.  Both decode paths work under a mesh:
        ``decode_attention="fused"`` (the default fast path) runs the
        Pallas kernels per shard under ``shard_map`` on the KV-head
        cut — bit-identical to the unsharded kernel, no new
        collectives — while ``"einsum"`` remains the gathered GSPMD
        fallback.  The geometry must divide the mesh on the KV-head
        axis (checked at construction).  The one-compile contract is
        unchanged: input shardings are stable across steps, so the jit
        caches never see a second signature.
      device: optional ``jax.Device`` pinning a single-device engine's
        pools and control uploads (the router's N-replicas-on-N-chips
        layout without sharding).  Mutually exclusive with ``mesh``.
        Default ``None`` keeps the classic implicit-default-device fast
        path: no extra transfers anywhere.
    """

    def __init__(self, model, params, capacity: int, num_blocks: int,
                 block_len: int = 16,
                 max_blocks_per_slot: Optional[int] = None,
                 prefill_chunk: int = 32, top_k: int = 0,
                 prefill_ladder: Optional[List[int]] = None,
                 draft_model=None, draft_params=None, spec_k: int = 0,
                 prefix_cache: bool = True, mesh=None, device=None):
        import jax
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if (draft_model is None) != (spec_k == 0):
            raise ValueError(
                "speculative decoding needs BOTH draft_model and "
                f"spec_k >= 1 (got draft_model={draft_model is not None}, "
                f"spec_k={spec_k})"
            )
        if draft_model is not None:
            if draft_params is None:
                raise ValueError("draft_model needs draft_params")
            if draft_model.vocab != model.vocab:
                raise ValueError(
                    f"draft vocab {draft_model.vocab} != target vocab "
                    f"{model.vocab} — proposals would be meaningless"
                )
            from chainermn_tpu.ops import MAX_VERIFY_T

            if not 1 <= spec_k <= MAX_VERIFY_T - 1:
                raise ValueError(
                    f"spec_k must be in [1, {MAX_VERIFY_T - 1}] "
                    f"(verify chunk is k + 1 positions), got {spec_k}"
                )
        if mesh is not None and device is not None:
            raise ValueError(
                "mesh and device are mutually exclusive — a sharded "
                "engine's placement IS its mesh"
            )
        self.mesh = mesh
        self.device = device
        placement = None
        if mesh is not None:
            from chainermn_tpu.serving import sharding as _sharding

            _sharding.validate_geometry(model, mesh)
            params = _sharding.shard_params(params, mesh)
            # Fused engines run the Pallas decode kernels per shard
            # under shard_map (ops.sharded_paged_decode_attention) —
            # the mesh threads into the model's dispatch as a static
            # field.  Einsum engines come back unchanged.
            model = _sharding.attach_decode_mesh(model, mesh)
            if draft_model is not None:
                _sharding.validate_geometry(draft_model, mesh)
                draft_params = _sharding.shard_params(draft_params, mesh)
                draft_model = _sharding.attach_decode_mesh(
                    draft_model, mesh
                )
            placement = _sharding.pool_placement(mesh)
            #: where small per-step host arrays (control vectors, RNG
            #: lanes) go: replicated on the mesh — one upload, every
            #: chip reads the same block tables.
            self._ctrl = _sharding.replicated(mesh)
        elif device is not None:
            placement = (lambda arr: jax.device_put(arr, device))
            self._ctrl = device
        else:
            self._ctrl = None
        self.model = model
        self.params = params
        self.capacity = capacity
        self.pool = PagedKVPool(model, num_blocks, block_len,
                                placement=placement)
        self.block_len = block_len
        self.spec_k = spec_k
        self.draft_model = draft_model
        self.max_blocks = (
            max_blocks_per_slot
            if max_blocks_per_slot is not None
            else max(
                1, math.ceil((model.max_len + spec_k) / block_len)
            )
        )
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefill_chunk = prefill_chunk
        if prefill_ladder is None:
            ladder = {prefill_chunk}
            c = prefill_chunk // 2
            while c >= 8:
                ladder.add(c)
                c //= 2
        else:
            ladder = set(int(c) for c in prefill_ladder)
            if not ladder or min(ladder) < 1:
                raise ValueError(f"bad prefill_ladder {prefill_ladder}")
            if max(ladder) != prefill_chunk:
                raise ValueError(
                    f"prefill_ladder max ({max(ladder)}) must equal "
                    f"prefill_chunk ({prefill_chunk}) — the scheduler's "
                    "padding bound at submit() assumes it"
                )
        #: allowed prefill chunk geometries, ascending; the scheduler
        #: picks the smallest size covering a prompt's tail so short
        #: remainders don't pay a full ``prefill_chunk`` of padded
        #: compute.
        self.prefill_ladder = tuple(sorted(ladder))
        self.top_k = top_k
        # The engine OWNS the live pool buffers: they are donated through
        # the jitted step every iteration, so any alias held elsewhere
        # (e.g. on the PagedKVPool) would dangle on deleted arrays after
        # the first step.
        self.pools = self.pool.pools
        self.pool.pools = None
        if draft_model is not None:
            # The draft's pools mirror the target's block geometry and
            # SHARE its allocator + block tables: one physical block id
            # addresses both pools, so admission/sharing/eviction/COW
            # remain a single accounting decision.
            dpool = PagedKVPool(draft_model, num_blocks, block_len,
                                placement=placement)
            self.draft_pools = dpool.pools
            #: HBM bytes per block across target + draft pools.
            self.pool.bytes_per_block += dpool.bytes_per_block
        else:
            self.draft_pools = None
        #: prefix trie over this engine's allocator (None = sharing off).
        self.prefix = (
            PrefixCache(block_len, self.pool.allocator)
            if prefix_cache else None
        )
        #: per-slot RNG BASE keys + temperatures, HOST numpy mirrors
        #: written only at admission (never in the steady loop) and
        #: uploaded lazily — an eager device scatter per admission would
        #: cost more than the whole control-vector upload of a step.
        #: Sampling derives each token's key STATELESSLY as
        #: ``fold_in(base, position)``, so a request's sampled sequence
        #: depends only on its seed and its own token positions —
        #: invisible to co-scheduling, slot placement, and
        #: eviction/recompute (the re-admission re-derives the exact
        #: keys the uninterrupted run would have used).
        self.rng = np.zeros((capacity, 2), np.uint32)
        self.temp = np.zeros((capacity,), np.float32)
        self._rng_temp_dev = None  # lazy device copy, dropped on seed_slot

        def pick(logits, base, position, t):
            """One slot's token: greedy at t <= 0, else temperature/top-k
            sampling keyed by (base key, absolute position)."""
            greedy = jnp.argmax(logits).astype(jnp.int32)
            scaled = logits / jnp.maximum(t, 1e-6)
            if self.top_k:
                k = min(self.top_k, logits.shape[-1])
                # lax.top_k, not a full-vocab sort — this runs per slot
                # inside the hot decode step.
                kth = jax.lax.top_k(scaled, k)[0][-1]
                scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
            key = jax.random.fold_in(base, position)
            samp = jax.random.categorical(key, scaled).astype(jnp.int32)
            return jnp.where(t > 0, samp, greedy)

        # Both programs CLOSE over `params` instead of taking them as an
        # argument: jit dispatch flattens every call's argument pytree,
        # and re-flattening hundreds of parameter leaves per generated
        # token is pure host overhead in the hot loop.  Captured params
        # are flattened once at trace time; per-step arguments are just
        # the pools + a handful of small control vectors.
        def step_impl(pools, tokens, pos, tables, active, rng, temp):
            logits, new_pools = model.apply(
                {"params": params}, tokens[:, None], cache=pools,
                decode_pos=pos, block_tables=tables, slot_mask=active,
            )
            nxt = jax.vmap(pick)(logits[:, 0], rng, pos, temp)
            return new_pools, nxt

        # Prefill stays a SINGLE-ROW program (one slot's chunk per call):
        # a fixed-capacity variant would pay the full ``capacity x chunk``
        # forward even when one slot is refilling, and prefill compute —
        # unlike the 1-token decode step — scales with every padded row.
        # ``last_idx >= 0`` marks the final chunk; the first generated
        # token is sampled from that in-chunk position's logits.  A
        # speculative engine's prefill ALSO runs the draft model over the
        # chunk (headless) so the draft cache tracks the target's.
        def prefill_impl(pools, dpools, tokens, p0, table, last_idx, rng,
                         temp):
            h, new_pools = model.apply(
                {"params": params}, tokens, cache=pools, decode_pos=p0,
                block_tables=table, return_hidden=True,
            )
            if draft_model is not None:
                _, dpools = draft_model.apply(
                    {"params": draft_params}, tokens, cache=dpools,
                    decode_pos=p0, block_tables=table, return_hidden=True,
                )
            li = jnp.maximum(last_idx, 0)
            # LM head at the sampled position ONLY: the other chunk
            # rows' logits are never read, and a full (chunk, vocab)
            # head matmul is a third of prefill compute.  Same manual
            # fp32 head application as models.lm_loss_chunked.
            hx = jax.lax.dynamic_slice_in_dim(h, li, 1, axis=1)
            head = params["lm_head"]
            logits = (
                hx[0].astype(jnp.float32)
                @ head["kernel"].astype(jnp.float32)
                + head["bias"].astype(jnp.float32)
            )
            nxt = pick(logits[0], rng, p0 + li, temp)
            return new_pools, dpools, nxt

        # One speculative ROUND, one jitted program: k + 1 sequential
        # draft steps (the last backfills the final proposal's K/V — a
        # permanent zero-K/V row after an all-accept round would poison
        # the draft's context forever, same hazard
        # models.lm_speculative_generate documents), then ONE target
        # verify forward over the (k + 1)-position chunk with per-row
        # positions, greedy prefix acceptance per slot.  Sampling slots
        # (t > 0) accept zero drafts and sample position-0's logits —
        # which ARE the plain step's logits under the same fold_in key,
        # so sampling semantics are unchanged by speculation.
        def spec_impl(pools, dpools, tokens, pos, tables, active, rng,
                      temp):
            k = spec_k

            def dstep(carry, i):
                tok, dp = carry
                dlogits, dp = draft_model.apply(
                    {"params": draft_params}, tok[:, None], cache=dp,
                    decode_pos=pos + i, block_tables=tables,
                    slot_mask=active,
                )
                nxt = jnp.argmax(dlogits[:, 0], axis=-1).astype(jnp.int32)
                return (nxt, dp), nxt

            (_, dpools), drafts = jax.lax.scan(
                dstep, (tokens, dpools), jnp.arange(k + 1)
            )
            drafts = drafts[:k]  # step k only backfilled K/V
            chunk = jnp.concatenate(
                [tokens[None], drafts], axis=0
            ).T  # (S, k+1): [last, d1..dk]
            logits, pools = model.apply(
                {"params": params}, chunk, cache=pools, decode_pos=pos,
                block_tables=tables, slot_mask=active,
            )
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, k+1)
            agree = (g[:, :k] == chunk[:, 1:]).astype(jnp.int32)
            n_accept = jnp.cumprod(agree, axis=1).sum(axis=1)
            tok0 = jax.vmap(pick)(logits[:, 0], rng, pos, temp)
            g = g.at[:, 0].set(tok0)
            n_accept = jnp.where(temp > 0.0, 0, n_accept)
            return pools, dpools, g, n_accept

        # KV-block migration device half (serving/disagg.py): read ONE
        # physical block's contents out of every layer of every pool
        # (target + draft), and write one back.  Traced block index —
        # one compiled variant each, ever, so migration churn can never
        # threaten the decode step's one-compile contract.  The gather
        # does NOT donate (the pools stay live for the next step); the
        # put donates exactly like the cow copy.
        def gather_impl(pools, dpools, idx):
            def one(layer):
                return {
                    n: jax.lax.dynamic_index_in_dim(
                        layer[n], idx, axis=1, keepdims=False
                    )
                    for n in layer
                }

            t = [one(p) for p in pools]
            d = [one(p) for p in dpools] if draft_model is not None else None
            return t, d

        def put_impl(pools, dpools, idx, tdata, ddata):
            def one(layer, data):
                return {n: layer[n].at[:, idx].set(data[n]) for n in layer}

            pools = [one(p, x) for p, x in zip(pools, tdata)]
            if draft_model is not None:
                dpools = [one(p, x) for p, x in zip(dpools, ddata)]
            return pools, dpools

        # Copy-on-write: duplicate ONE physical block across every layer
        # of every pool (target + draft) so a borrower of a shared
        # partial block can diverge without scribbling the cached
        # original.  Traced src/dst — one compiled variant, ever.
        def cow_impl(pools, dpools, src, dst):
            def dup(layer):
                return {
                    n: layer[n].at[:, dst].set(layer[n][:, src])
                    for n in layer
                }

            pools = [dup(p) for p in pools]
            if draft_model is not None:
                dpools = [dup(p) for p in dpools]
            return pools, dpools

        # Every engine program rides the compile watcher (PR 11): each
        # compilation is recorded with the triggering argument signature,
        # a recompile emits a structured blame diff instead of a bare
        # counter bump, and the declared budgets below feed the
        # ``compile.budget_exceeded`` gauge the recompile-guard tests
        # pin at 0.  The watcher consults CMN_OBS at wrap time — with
        # observability off these are the raw jits (zero overhead) and
        # the ``*_compiles`` properties read ``_cache_size()`` exactly
        # as before.
        from chainermn_tpu.observability import device as _odevice

        _w = _odevice.watch()
        self._step = _w.wrap(
            jax.jit(step_impl, donate_argnums=(0,)),
            program="decode_step", budget=1,
        )
        self._prefill = _w.wrap(
            jax.jit(prefill_impl, donate_argnums=(0, 1)),
            program="prefill", budget=len(self.prefill_ladder),
        )
        self._spec = (
            _w.wrap(
                jax.jit(spec_impl, donate_argnums=(0, 1)),
                program="spec_round", budget=1,
            )
            if draft_model is not None else None
        )
        self._cow = _w.wrap(
            jax.jit(cow_impl, donate_argnums=(0, 1)),
            program="cow", budget=1,
        )
        self._gather = _w.wrap(
            jax.jit(gather_impl), program="kv_gather", budget=1,
        )
        self._put = _w.wrap(
            jax.jit(put_impl, donate_argnums=(0, 1)),
            program="kv_put", budget=1,
        )

    # ----------------------------------------------------------- uploads
    def _up(self, x):
        """One control-vector upload: committed to the engine's injected
        placement (replicated on the mesh / pinned device) when one was
        given, else the classic uncommitted ``jnp.asarray`` fast path.
        A stable upload sharding is part of the one-compile contract —
        the jit caches key on input shardings.  The placed path goes
        host→target directly (``device_put`` on the host array) — an
        intermediate ``jnp.asarray`` would land on the DEFAULT device
        first and pay a second device→device hop per step."""
        import jax
        import jax.numpy as jnp

        if self._ctrl is None:
            return jnp.asarray(x)
        return jax.device_put(np.asarray(x), self._ctrl)

    # ------------------------------------------------------------- slots
    def seed_slot(self, slot: int, seed: int, temperature: float) -> None:
        """Arm a slot's RNG base key + temperature (admission-time only)."""
        # The key derivation itself (threefry seed hash) stays jax's so
        # fold_in(base, position) matches any other PRNGKey(seed) user.
        import jax

        self.rng[slot] = np.asarray(jax.random.PRNGKey(seed), np.uint32)
        self.temp[slot] = float(temperature)
        self._rng_temp_dev = None

    def _rng_temp(self):
        import jax.numpy as jnp

        if self._rng_temp_dev is None:
            self._rng_temp_dev = (
                self._up(self.rng), self._up(self.temp)
            )
        return self._rng_temp_dev

    # ----------------------------------------------------------- prefill
    def prefill(self, slot: int, chunk: np.ndarray, p0: int,
                table: np.ndarray, last_idx: int = -1) -> Optional[int]:
        """Run one prefill chunk for ``slot``.

        ``chunk`` is one of the ``prefill_ladder`` geometries
        (right-padded past the prompt — pad positions inside the slot's
        allocated blocks are masked by ``valid_len`` until real tokens
        overwrite them; pads past the allocation fall through the
        zero-initialized tail of ``table`` into reserved parking block
        0, which is never read).  ``p0`` may start mid-block (a
        prefix-cache hit resumes at the first unmatched token).
        ``last_idx >= 0`` marks the final chunk: the first generated
        token is sampled from the logits at that in-chunk index and
        returned.
        """
        if chunk.ndim != 1 or chunk.shape[0] not in self.prefill_ladder:
            raise ValueError(
                f"chunk must be 1-D with a ladder size "
                f"{self.prefill_ladder}, got {chunk.shape}"
            )
        self.pools, self.draft_pools, tok = self._prefill(
            self.pools,
            self.draft_pools,
            self._up(np.asarray(chunk, np.int32)[None]),
            np.int32(p0),
            self._up(np.asarray(table, np.int32)[None]),
            np.int32(last_idx),
            self.rng[slot],
            np.float32(self.temp[slot]),
        )
        return int(tok) if last_idx >= 0 else None

    # ------------------------------------------------------------ decode
    def step(self, tokens: np.ndarray, pos: np.ndarray,
             tables: np.ndarray, active: np.ndarray) -> np.ndarray:
        """One fixed-capacity decode iteration.

        Args (all host arrays, shapes fixed by construction):
          tokens: ``(capacity,)`` int32 — each slot's last token.
          pos: ``(capacity,)`` int32 — each slot's current length (the
            position this step writes).
          tables: ``(capacity, max_blocks)`` int32 block tables.
          active: ``(capacity,)`` bool — live slots.

        Returns ``(capacity,)`` int32 sampled tokens (garbage at inactive
        slots — callers must mask by ``active``).
        """
        rng, temp = self._rng_temp()
        self.pools, nxt = self._step(
            self.pools,
            self._up(np.asarray(tokens, np.int32)),
            self._up(np.asarray(pos, np.int32)),
            self._up(np.asarray(tables, np.int32)),
            self._up(np.asarray(active, bool)),
            rng, temp,
        )
        return np.asarray(nxt)

    def spec_step(self, tokens: np.ndarray, pos: np.ndarray,
                  tables: np.ndarray, active: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """One speculative round (requires a draft; same fixed shapes as
        :meth:`step`).  The slot at ``pos`` must have block-table
        coverage for positions up to ``pos + spec_k`` (the verify chunk's
        writes) — the scheduler allocates ahead.

        Returns ``(tokens, n_accept)``: ``(capacity, spec_k + 1)`` int32
        round tokens and ``(capacity,)`` int32 per-slot accepted draft
        counts — slot ``s`` emits ``tokens[s, :n_accept[s] + 1]``
        (greedy: accepted drafts + the target's correction/bonus;
        sampling slots always emit exactly ``tokens[s, :1]``).
        """
        if self._spec is None:
            raise RuntimeError(
                "spec_step on a non-speculative engine — construct with "
                "draft_model/draft_params/spec_k"
            )
        rng, temp = self._rng_temp()
        self.pools, self.draft_pools, toks, n_accept = self._spec(
            self.pools,
            self.draft_pools,
            self._up(np.asarray(tokens, np.int32)),
            self._up(np.asarray(pos, np.int32)),
            self._up(np.asarray(tables, np.int32)),
            self._up(np.asarray(active, bool)),
            rng, temp,
        )
        return np.asarray(toks), np.asarray(n_accept)

    # ----------------------------------------------------- prefix sharing
    def cow_copy(self, src: int, dst: int) -> None:
        """Copy physical block ``src`` onto ``dst`` across every layer of
        every pool (target + draft) — the device half of copy-on-write.
        Pure block-table/refcount surgery stays with the caller."""
        self.pools, self.draft_pools = self._cow(
            self.pools, self.draft_pools, np.int32(src), np.int32(dst)
        )

    def drop_prefix_cache(self) -> int:
        """Release every trie-held block reference (gc/retire pass);
        returns the number of blocks released.  With no live slots the
        allocator is back at its construction baseline afterwards."""
        return self.prefix.clear() if self.prefix is not None else 0

    # ------------------------------------------------------- kv migration
    def read_block(self, block: int) -> dict:
        """One physical block's live KV contents as HOST numpy arrays:
        ``{"target": [per-layer {name: (KH, block_len, Dh)}...],
        "draft": same or None}`` — the serializable unit
        :mod:`~chainermn_tpu.serving.disagg` ships over the hostcomm p2p
        plane.  Pure read: the pools stay live for the next step."""
        import jax

        t, d = self._gather(
            self.pools, self.draft_pools, np.int32(block)
        )
        return jax.tree_util.tree_map(np.asarray, {"target": t, "draft": d})

    def write_block(self, block: int, data: dict) -> None:
        """Install :meth:`read_block` data into physical ``block`` across
        every layer of every pool — the destination half of a KV-block
        migration.  Byte-preserving: the written block re-reads exactly
        as the source's :meth:`read_block` bytes (same dtypes, same
        layout).  A plain engine refuses draft data and vice versa —
        migration requires role-homogeneous engine geometry."""
        if (data.get("draft") is not None) != (self.draft_model is not None):
            raise ValueError(
                "migration payload draft pools do not match this engine "
                f"(payload draft={data.get('draft') is not None}, engine "
                f"draft={self.draft_model is not None}) — prefill and "
                "decode roles must run the same engine construction"
            )
        self.pools, self.draft_pools = self._put(
            self.pools, self.draft_pools, np.int32(block),
            data["target"], data["draft"],
        )

    def sync(self) -> None:
        """Block until every dispatched program against the KV pools has
        retired (``kv_put`` installs included).  Migration installers
        call this so the NEXT decode step's token readback cannot absorb
        install work into its timed window — ``serve.decode_ms`` stays
        pure decode."""
        import jax

        jax.block_until_ready(self.pools)
        if self.draft_pools is not None:
            jax.block_until_ready(self.draft_pools)

    # ------------------------------------------------------- introspection
    @property
    def hot_program(self):
        """The steady-state loop's (watched) program: the speculative
        round when a draft is armed — the plain step is never dispatched
        then — else the decode step.  What the scheduler's ``device.*``
        roofline gauges attribute to."""
        return self._spec if self._spec is not None else self._step

    @property
    def decode_compiles(self) -> int:
        """Compiled-variant count of the hot-loop decode program — the
        recompile guard's subject: must stay 1 under arbitrary slot
        churn.  Backed by the compile watcher since PR 11 (same number
        as the jit cache's ``_cache_size()`` — the watcher additionally
        records WHAT signature change triggered any recompile); for a
        speculative engine the hot loop is the fused draft+verify round
        program, so that is what is counted."""
        return int(self.hot_program._cache_size())

    @property
    def verify_compiles(self) -> int:
        """Speculative round variants (0 on a plain engine) — the "at
        most one additional cached executable" the speculation feature
        is allowed."""
        return int(self._spec._cache_size()) if self._spec else 0

    @property
    def cow_compiles(self) -> int:
        """Copy-on-write block-copy variants (must stay <= 1)."""
        return int(self._cow._cache_size())

    @property
    def gather_compiles(self) -> int:
        """KV-block gather variants (migration export; must stay <= 1)."""
        return int(self._gather._cache_size())

    @property
    def put_compiles(self) -> int:
        """KV-block put variants (migration import; must stay <= 1)."""
        return int(self._put._cache_size())

    @property
    def prefill_compiles(self) -> int:
        return int(self._prefill._cache_size())

    def free_blocks(self) -> int:
        return self.pool.allocator.free_blocks

    def stats(self) -> dict:
        """Host-side engine state for flight records / dashboards —
        never touches a device buffer."""
        free = self.pool.allocator.free_blocks
        allocatable = self.pool.num_blocks - 1  # block 0 reserved
        out = {
            "capacity": self.capacity,
            "num_blocks": self.pool.num_blocks,
            "block_len": self.block_len,
            "free_blocks": free,
            "blocks_in_use": allocatable - free,
            "block_occupancy": (
                (allocatable - free) / allocatable if allocatable else 0.0
            ),
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
        }
        if self.prefix is not None:
            out["prefix_cached_blocks"] = self.prefix.cached_blocks
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["verify_compiles"] = self.verify_compiles
        # Watched programs over their declared compile budget (empty on a
        # healthy engine; absent when CMN_OBS=0 left the programs as raw
        # jits).  The flight record's "compile" section carries the full
        # per-program ledger + blame diffs.
        over = [
            getattr(p, "program", "?")
            for p in (self._step, self._prefill, self._spec, self._cow,
                      self._gather, self._put)
            if p is not None and getattr(p, "over_budget", False)
        ]
        if over:
            out["compile_over_budget"] = over
        return out

    def alloc_blocks(self, n: int) -> Optional[List[int]]:
        return self.pool.allocator.alloc(n)

    def release_blocks(self, blocks) -> None:
        self.pool.allocator.free(blocks)
