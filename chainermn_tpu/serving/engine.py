"""Fixed-shape continuous-batching decode engine over the paged KV pool.

The TPU-idiomatic serving loop is ONE jitted decode step whose shapes never
change: ``capacity`` slots × 1 token, every iteration, forever.  Slot churn
(requests finishing, new prompts admitted) only changes the *contents* of
the step's inputs — the block tables, position vector, live mask, RNG lanes
and temperatures — never their shapes or dtypes, so the steady-state loop
compiles **exactly once** (``tests/serving_tests/test_engine.py`` pins this
with a compilation-count guard).  Idle slots ride along masked: their cache
writes are parked on reserved block 0 and their sampled tokens discarded.

One step = gather block tables → paged decode attention
(:func:`~chainermn_tpu.ops.paged_decode_attention` under
``decode_attention="fused"``, the gathered einsum fallback otherwise) →
per-slot sampling (independent RNG lanes, per-slot temperature, engine-wide
``top_k``).

Prefill runs through a second single-row jitted program in chunks drawn
from a small fixed **ladder** of geometries (``prefill_ladder``, by
default ``prefill_chunk`` and its halves down to 8 — one slot per call;
prefill compute scales with every padded row, so a capacity-wide
variant would pay the full ``capacity x chunk`` forward even when a
single slot is refilling): each chunk writes its K/V into the slot's
blocks and the final chunk samples the first generated token from the
last real prompt position's logits.  Chunking bounds prefill's latency
footprint so the scheduler can interleave decode steps between chunks
(iteration-level scheduling, Yu et al. 2022, *Orca*); the ladder bounds
the final chunk's padding waste (a short tail pays the nearest ladder
size, not the full ``prefill_chunk``) at a bounded, admission-path-only
compile cost — at most ``len(prefill_ladder)`` prefill variants, ever,
and still exactly ONE decode-step variant.

Host↔device traffic per decode step: small int32 control vectors up
(tokens/positions/tables/mask) and the ``(capacity,)`` sampled tokens down.
Pool accounting stays host-side (:mod:`~chainermn_tpu.serving.kv_pool`) —
no device sync beyond the token readback serving fundamentally needs for
EOS detection.
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from chainermn_tpu.serving.kv_pool import PagedKVPool


class DecodeEngine:
    """Continuous-batching decode over a :class:`PagedKVPool`.

    Args:
      model: a :class:`~chainermn_tpu.models.TransformerLM`.  Works with
        either ``decode_attention`` setting — "fused" runs the paged Pallas
        kernel in the hot loop, "einsum" the gathered fallback.
      params: the model's parameter pytree.
      capacity: decode slots per step (the fixed batch dimension).
      num_blocks: physical blocks in the pool (block 0 stays reserved).
      block_len: positions per block.
      max_blocks_per_slot: block-table width — caps a request at
        ``max_blocks_per_slot * block_len`` total positions.  Defaults to
        covering ``model.max_len``.
      prefill_chunk: largest prompt-tokens-per-prefill-call geometry.
      prefill_ladder: the full set of allowed prefill chunk sizes
        (must contain its max == ``prefill_chunk``).  Defaults to
        ``prefill_chunk`` and its successive halves down to 8.  Each
        size is one compiled prefill variant (admission path only — the
        decode step stays a single variant).
      top_k: engine-wide sampling truncation (0 = off; static — part of
        the compiled program).
    """

    def __init__(self, model, params, capacity: int, num_blocks: int,
                 block_len: int = 16,
                 max_blocks_per_slot: Optional[int] = None,
                 prefill_chunk: int = 32, top_k: int = 0,
                 prefill_ladder: Optional[List[int]] = None):
        import jax
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        self.model = model
        self.params = params
        self.capacity = capacity
        self.pool = PagedKVPool(model, num_blocks, block_len)
        self.block_len = block_len
        self.max_blocks = (
            max_blocks_per_slot
            if max_blocks_per_slot is not None
            else max(1, math.ceil(model.max_len / block_len))
        )
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefill_chunk = prefill_chunk
        if prefill_ladder is None:
            ladder = {prefill_chunk}
            c = prefill_chunk // 2
            while c >= 8:
                ladder.add(c)
                c //= 2
        else:
            ladder = set(int(c) for c in prefill_ladder)
            if not ladder or min(ladder) < 1:
                raise ValueError(f"bad prefill_ladder {prefill_ladder}")
            if max(ladder) != prefill_chunk:
                raise ValueError(
                    f"prefill_ladder max ({max(ladder)}) must equal "
                    f"prefill_chunk ({prefill_chunk}) — the scheduler's "
                    "padding bound at submit() assumes it"
                )
        #: allowed prefill chunk geometries, ascending; the scheduler
        #: picks the smallest size covering a prompt's tail so short
        #: remainders don't pay a full ``prefill_chunk`` of padded
        #: compute.
        self.prefill_ladder = tuple(sorted(ladder))
        self.top_k = top_k
        # The engine OWNS the live pool buffers: they are donated through
        # the jitted step every iteration, so any alias held elsewhere
        # (e.g. on the PagedKVPool) would dangle on deleted arrays after
        # the first step.
        self.pools = self.pool.pools
        self.pool.pools = None
        #: per-slot RNG BASE keys + temperatures, HOST numpy mirrors
        #: written only at admission (never in the steady loop) and
        #: uploaded lazily — an eager device scatter per admission would
        #: cost more than the whole control-vector upload of a step.
        #: Sampling derives each token's key STATELESSLY as
        #: ``fold_in(base, position)``, so a request's sampled sequence
        #: depends only on its seed and its own token positions —
        #: invisible to co-scheduling, slot placement, and
        #: eviction/recompute (the re-admission re-derives the exact
        #: keys the uninterrupted run would have used).
        self.rng = np.zeros((capacity, 2), np.uint32)
        self.temp = np.zeros((capacity,), np.float32)
        self._rng_temp_dev = None  # lazy device copy, dropped on seed_slot

        def pick(logits, base, position, t):
            """One slot's token: greedy at t <= 0, else temperature/top-k
            sampling keyed by (base key, absolute position)."""
            greedy = jnp.argmax(logits).astype(jnp.int32)
            scaled = logits / jnp.maximum(t, 1e-6)
            if self.top_k:
                k = min(self.top_k, logits.shape[-1])
                # lax.top_k, not a full-vocab sort — this runs per slot
                # inside the hot decode step.
                kth = jax.lax.top_k(scaled, k)[0][-1]
                scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
            key = jax.random.fold_in(base, position)
            samp = jax.random.categorical(key, scaled).astype(jnp.int32)
            return jnp.where(t > 0, samp, greedy)

        # Both programs CLOSE over `params` instead of taking them as an
        # argument: jit dispatch flattens every call's argument pytree,
        # and re-flattening hundreds of parameter leaves per generated
        # token is pure host overhead in the hot loop.  Captured params
        # are flattened once at trace time; per-step arguments are just
        # the pools + a handful of small control vectors.
        def step_impl(pools, tokens, pos, tables, active, rng, temp):
            logits, new_pools = model.apply(
                {"params": params}, tokens[:, None], cache=pools,
                decode_pos=pos, block_tables=tables, slot_mask=active,
            )
            nxt = jax.vmap(pick)(logits[:, 0], rng, pos, temp)
            return new_pools, nxt

        # Prefill stays a SINGLE-ROW program (one slot's chunk per call):
        # a fixed-capacity variant would pay the full ``capacity x chunk``
        # forward even when one slot is refilling, and prefill compute —
        # unlike the 1-token decode step — scales with every padded row.
        # ``last_idx >= 0`` marks the final chunk; the first generated
        # token is sampled from that in-chunk position's logits.
        def prefill_impl(pools, tokens, p0, table, last_idx, rng, temp):
            h, new_pools = model.apply(
                {"params": params}, tokens, cache=pools, decode_pos=p0,
                block_tables=table, return_hidden=True,
            )
            li = jnp.maximum(last_idx, 0)
            # LM head at the sampled position ONLY: the other chunk
            # rows' logits are never read, and a full (chunk, vocab)
            # head matmul is a third of prefill compute.  Same manual
            # fp32 head application as models.lm_loss_chunked.
            hx = jax.lax.dynamic_slice_in_dim(h, li, 1, axis=1)
            head = params["lm_head"]
            logits = (
                hx[0].astype(jnp.float32)
                @ head["kernel"].astype(jnp.float32)
                + head["bias"].astype(jnp.float32)
            )
            nxt = pick(logits[0], rng, p0 + li, temp)
            return new_pools, nxt

        self._step = jax.jit(step_impl, donate_argnums=(0,))
        self._prefill = jax.jit(prefill_impl, donate_argnums=(0,))

    # ------------------------------------------------------------- slots
    def seed_slot(self, slot: int, seed: int, temperature: float) -> None:
        """Arm a slot's RNG base key + temperature (admission-time only)."""
        # The key derivation itself (threefry seed hash) stays jax's so
        # fold_in(base, position) matches any other PRNGKey(seed) user.
        import jax

        self.rng[slot] = np.asarray(jax.random.PRNGKey(seed), np.uint32)
        self.temp[slot] = float(temperature)
        self._rng_temp_dev = None

    def _rng_temp(self):
        import jax.numpy as jnp

        if self._rng_temp_dev is None:
            self._rng_temp_dev = (
                jnp.asarray(self.rng), jnp.asarray(self.temp)
            )
        return self._rng_temp_dev

    # ----------------------------------------------------------- prefill
    def prefill(self, slot: int, chunk: np.ndarray, p0: int,
                table: np.ndarray, last_idx: int = -1) -> Optional[int]:
        """Run one prefill chunk for ``slot``.

        ``chunk`` is one of the ``prefill_ladder`` geometries
        (right-padded past the prompt — pad positions inside the slot's
        allocated blocks are masked by ``valid_len`` until real tokens
        overwrite them; pads past the allocation fall through the
        zero-initialized tail of ``table`` into reserved parking block
        0, which is never read).  ``last_idx >= 0`` marks the final
        chunk: the first generated token is sampled from the logits at
        that in-chunk index and returned.
        """
        import jax.numpy as jnp

        if chunk.ndim != 1 or chunk.shape[0] not in self.prefill_ladder:
            raise ValueError(
                f"chunk must be 1-D with a ladder size "
                f"{self.prefill_ladder}, got {chunk.shape}"
            )
        self.pools, tok = self._prefill(
            self.pools,
            jnp.asarray(chunk, jnp.int32)[None],
            np.int32(p0),
            jnp.asarray(table, jnp.int32)[None],
            np.int32(last_idx),
            self.rng[slot],
            np.float32(self.temp[slot]),
        )
        return int(tok) if last_idx >= 0 else None

    # ------------------------------------------------------------ decode
    def step(self, tokens: np.ndarray, pos: np.ndarray,
             tables: np.ndarray, active: np.ndarray) -> np.ndarray:
        """One fixed-capacity decode iteration.

        Args (all host arrays, shapes fixed by construction):
          tokens: ``(capacity,)`` int32 — each slot's last token.
          pos: ``(capacity,)`` int32 — each slot's current length (the
            position this step writes).
          tables: ``(capacity, max_blocks)`` int32 block tables.
          active: ``(capacity,)`` bool — live slots.

        Returns ``(capacity,)`` int32 sampled tokens (garbage at inactive
        slots — callers must mask by ``active``).
        """
        import jax.numpy as jnp

        rng, temp = self._rng_temp()
        self.pools, nxt = self._step(
            self.pools,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(active, bool),
            rng, temp,
        )
        return np.asarray(nxt)

    # ------------------------------------------------------- introspection
    @property
    def decode_compiles(self) -> int:
        """Compiled-variant count of the decode step — the recompile
        guard's subject: must stay 1 under arbitrary slot churn."""
        return int(self._step._cache_size())

    @property
    def prefill_compiles(self) -> int:
        return int(self._prefill._cache_size())

    def free_blocks(self) -> int:
        return self.pool.allocator.free_blocks

    def stats(self) -> dict:
        """Host-side engine state for flight records / dashboards —
        never touches a device buffer."""
        free = self.pool.allocator.free_blocks
        allocatable = self.pool.num_blocks - 1  # block 0 reserved
        return {
            "capacity": self.capacity,
            "num_blocks": self.pool.num_blocks,
            "block_len": self.block_len,
            "free_blocks": free,
            "blocks_in_use": allocatable - free,
            "block_occupancy": (
                (allocatable - free) / allocatable if allocatable else 0.0
            ),
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
        }

    def alloc_blocks(self, n: int) -> Optional[List[int]]:
        return self.pool.allocator.alloc(n)

    def release_blocks(self, blocks) -> None:
        self.pool.allocator.free(blocks)
