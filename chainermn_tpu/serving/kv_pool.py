"""Block-granular KV allocator over a fixed device-resident pool.

The static-batch decode path (:func:`~chainermn_tpu.models.lm_generate`)
sizes one contiguous ``(B, L, ...)`` cache to the LONGEST request and holds
it for the whole batch — memory proportional to ``B · max_len`` even when
most rows finished long ago.  The serving engine instead draws from one
physical **block pool** per layer, laid out kv-head major exactly as the
fused/paged decode kernels want it:

    ``{"k", "v"}``:  ``(KH, num_blocks, block_len, Dh)``
    ``{"k_scale", "v_scale"}`` (int8 pools): ``(KH, num_blocks, block_len)``

A decode slot owns an ordered list of physical blocks (its *block table*);
logical position ``p`` lives at ``(table[p // block_len], p % block_len)``.
Blocks are recycled through a host-side free list the moment a request
retires or is evicted — the next admission reuses them without touching the
device (vLLM's PagedAttention memory model, Kwon et al. 2023).

Accounting is **pure host state**: :class:`BlockAllocator` is a Python free
list + per-block refcount map, so allocation/share/free decisions in the
steady decode loop never read device memory and never force a sync.  The
only device work is the engine's jitted step itself.  Refcounts are what
make prefix sharing safe: one physical block can back the same prompt
prefix in many block tables (and stay pinned by the prefix trie after its
requests retire), and it returns to the free list only when the last
holder lets go.

Physical block 0 is reserved as the **parking block**: the paged decode
branch redirects idle slots' scatter writes there (with their own current
value, so duplicate indices carry duplicate values and the scatter stays
deterministic — ``models/transformer.py``).  The allocator never hands it
out.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence


class PoolExhausted(RuntimeError):
    """A request needs more blocks than the pool can ever provide."""


class BlockAllocator:
    """Host-side REFCOUNTED free-list accounting for the physical pool.

    No device syncs, ever: this is plain Python state.  ``alloc`` hands a
    block out at refcount 1; :meth:`share` lends it to another holder
    (prefix sharing — the same physical KV block mapped into several block
    tables, or pinned by the prefix trie); ``free`` drops one reference
    and reclaims the block only when the count hits zero.  Freeing a block
    nobody holds raises — silent accounting drift would surface later as
    two slots scribbling over the same physical block.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is reserved), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-issued first (their
        # pool pages are the most likely to still be warm).
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._ref)

    def refcount(self, block: int) -> int:
        """Current holder count (0 = free or reserved)."""
        return self._ref.get(block, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` physical block ids at refcount 1 each, or ``None`` when
        the pool is exhausted (the scheduler's backpressure/eviction
        signal — never raises)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def share(self, blocks: Sequence[int]) -> None:
        """Add one reference per block (the block must be live — sharing
        a free block would resurrect reclaimed memory)."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(
                    f"sharing block {b} that is not allocated — a borrowed "
                    "reference must come from a live holder"
                )
        for b in blocks:
            self._ref[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; a block is reclaimed to the free
        list when its count reaches zero.  Freeing an unallocated block
        (over-free or foreign id) raises."""
        for b in blocks:
            n = self._ref.get(b, 0)
            if n == 0:
                raise ValueError(
                    f"freeing block {b} that was never allocated (over-"
                    "free or foreign id) — allocator state is corrupt"
                )
            if n == 1:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = n - 1


def blocks_for(tokens: int, block_len: int) -> int:
    """Blocks needed to hold ``tokens`` positions."""
    return max(1, math.ceil(tokens / block_len))


class PagedKVPool:
    """The device-resident pools (one ``{"k","v"[,scales]}`` dict per
    layer) plus their :class:`BlockAllocator`.

    Built from the model's own geometry so the pool entries are exactly
    what :meth:`TransformerLM.__call__`'s paged decode branch expects.
    ``kv_dtype=jnp.int8`` models get int8 pools with fp32 scale planes —
    the same symmetric-absmax convention as the contiguous cache, at half
    the bf16 pool bytes.

    ``placement`` makes device placement EXPLICIT and injected (it used
    to be whatever ``jnp.zeros`` landed on — implicitly
    ``jax.devices()[0]``): a callable applied to every freshly-built
    pool array.  Pass
    :func:`~chainermn_tpu.serving.sharding.pool_placement` for a
    kv-head-major mesh shard, ``lambda a: jax.device_put(a, dev)`` to
    pin a specific device, or ``None`` (the default-constructed
    single-device fast path — no extra transfer, unchanged behavior).
    """

    def __init__(self, model, num_blocks: int, block_len: int,
                 placement=None):
        import jax.numpy as jnp

        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        kvh = model.n_kv_heads or model.n_heads
        dh = model.d_model // model.n_heads
        kvd = model.kv_dtype if model.kv_dtype is not None else model.dtype
        shape = (kvh, num_blocks, block_len, dh)
        self.block_len = block_len
        self.num_blocks = num_blocks
        self.allocator = BlockAllocator(num_blocks)
        if jnp.dtype(kvd) == jnp.int8:
            self.pools: List[Dict] = [
                {"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "k_scale": jnp.zeros(shape[:3], jnp.float32),
                 "v_scale": jnp.zeros(shape[:3], jnp.float32)}
                for _ in range(model.n_layers)
            ]
            per_layer = 2 * kvh * block_len * (dh + 4)  # k+v int8 + scales
        else:
            if not jnp.issubdtype(jnp.dtype(kvd), jnp.floating):
                raise ValueError(
                    f"kv_dtype must be a float dtype or jnp.int8, got {kvd}"
                )
            self.pools = [
                {"k": jnp.zeros(shape, kvd), "v": jnp.zeros(shape, kvd)}
                for _ in range(model.n_layers)
            ]
            per_layer = 2 * kvh * block_len * dh * jnp.dtype(kvd).itemsize
        if placement is not None:
            self.pools = [
                {n: placement(arr) for n, arr in layer.items()}
                for layer in self.pools
            ]
        #: HBM bytes one physical block costs across all layers.  Computed
        #: from geometry, NOT the arrays: the engine donates the pool
        #: buffers to its jitted step, so these initial arrays are deleted
        #: after the first iteration.
        self.bytes_per_block = int(per_layer * model.n_layers)
