"""Serving-fleet failure plane: fault isolation, retry budgets, chaos.

ChainerMN inherits MPI's fail-stop model — one dead rank kills the job —
and the resilience package rebuilt the *training* side of that story
(detector, guard, rollback, preemption).  This module is the *serving*
side (ISSUE 15): a production fleet must survive replica death, runaway
requests, and overload without dropping work on the floor.

Four mechanisms, all host-side (no device state is ever trusted after a
failure — recovery is recompute, the same discipline as eviction):

* **Fault-isolated replicas** — the :class:`~chainermn_tpu.serving.
  router.Router` wraps each replica's ``tick()`` in a fault boundary;
  an escaping exception (real, or ``crash@serve_step``-injected) marks
  the replica **dead** instead of aborting the fleet.  The router then
  *harvests* the dead replica's queued entries and live slots into
  recompute ``_QueueEntry`` s (carried + generated tokens preserved —
  exactly the eviction-requeue discipline, so continuations are
  greedy-identical) and re-dispatches them to survivors.  Nothing is
  lost; survivors never recompile (``decode_compiles`` stays 1).

* **Retry budgets + poison quarantine** — every harvested entry's
  ``retries`` count increments with the replica it just killed.  A
  request that has killed :data:`retry_budget` replicas
  (``CMN_SERVE_RETRY_BUDGET``, default 2) is the likely *cause*, not a
  victim: it is quarantined as a failed
  :class:`~chainermn_tpu.serving.scheduler.Completion` with
  ``status="poisoned"`` and the attributed error, instead of being
  re-dispatched until it kills the whole fleet.  Quarantine files a
  critical ``poison_request`` incident bundle.

* **Probation (circuit breaker)** — :meth:`Router.revive_replica`
  re-registers a replacement engine behind a circuit breaker: the
  revived replica takes only *fresh* admissions at reduced dispatch
  weight (never recovered work, never rebalance steals) until
  ``CMN_SERVE_PROBATION_TICKS`` clean ticks pass, so a flapping replica
  cannot thrash the fleet with repeated harvest storms.

* **Graceful degradation** — per-request ``deadline_ms`` (the scheduler
  cancels over-deadline slots and frees their blocks,
  ``status="deadline"``) and router-level load shedding: when surviving
  capacity leaves the holdback queue deeper than
  ``CMN_ROUTER_SHED_DEPTH`` arrived requests, the newest are refused
  with ``status="shed"`` — a bounded queue instead of unbounded latency
  collapse.  Both are *terminal* outcomes: a degraded request still
  terminates exactly once, with a definite status.

Everything is observable as the ``serve.health.*`` metric family, and
``replica_dead`` / ``poison_request`` ship as default incident rules
(both critical — see :func:`chainermn_tpu.observability.incident.
default_rules`).

The **chaos harness** proves the plane: :class:`ChaosHarness` drives a
multi-replica router under a seeded randomized fault schedule over the
existing sites (``crash@serve_step``, ``skew@serve_step`` on replicas;
``drop@migrate`` on the router's recovery re-dispatch path), revives
dead replicas after a configurable cooldown, and checks the **terminal
invariant** request by request with :func:`verify_terminal_invariant`:
every submitted request terminates exactly once (completed, poisoned,
shed, or deadline), zero lost, zero duplicated.  See
``tests/serving_tests/test_chaos.py`` and ``benchmarks/serving.py
--chaos``.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Sequence

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
    _env_float,
)

#: Replica lifecycle states (see :class:`FleetHealth`).  ``draining``
#: and ``removed`` are the elastic-fleet states (ISSUE 17): a draining
#: replica still ticks (its in-flight work progresses) but is fenced
#: from fresh admissions AND rebalance steals; a removed replica was
#: deregistered after a scale-down drain — its row is a tombstone so
#: historical replica indices stay stable.
STATES = ("live", "probation", "draining", "dead", "removed")


# ----------------------------------------------------------- env knobs
def retry_budget_from_env() -> int:
    """``CMN_SERVE_RETRY_BUDGET`` — how many replicas one request may
    kill before it is quarantined as poisoned (default 2)."""
    return max(1, int(_env_float("CMN_SERVE_RETRY_BUDGET", 2)))


def probation_ticks_from_env() -> int:
    """``CMN_SERVE_PROBATION_TICKS`` — clean ticks a revived replica
    serves at reduced weight before rejoining at full trust
    (default 32)."""
    return max(1, int(_env_float("CMN_SERVE_PROBATION_TICKS", 32)))


def shed_depth_from_env() -> int:
    """``CMN_ROUTER_SHED_DEPTH`` — arrived requests the router holds
    back before shedding the newest (0, the default, disables
    shedding: the holdback queue is unbounded, the pre-ISSUE-15
    behavior)."""
    return max(0, int(_env_float("CMN_ROUTER_SHED_DEPTH", 0)))


def deadline_ms_from_env() -> Optional[float]:
    """``CMN_SERVE_DEADLINE_MS`` — fleet-wide default per-request
    deadline applied to requests that carry none of their own (unset or
    ``0`` = no default deadline)."""
    v = _env_float("CMN_SERVE_DEADLINE_MS", 0.0)
    return v if v > 0 else None


# ---------------------------------------------------------- FleetHealth
class FleetHealth:
    """Per-replica state machine + the ``serve.health.*`` instruments.

    Owned by the :class:`~chainermn_tpu.serving.router.Router`; the
    scheduler-side member of the family (``serve.health.
    deadline_cancels``) publishes from the scheduler because deadlines
    are enforced there.

    States: ``live`` → (tick raised) → ``dead`` → (revive) →
    ``probation`` → (:data:`probation_ticks` clean ticks) → ``live``.
    A probation replica that raises goes straight back to ``dead`` —
    the circuit breaker re-opens.

    The elastic extensions (ISSUE 17): ``live``/``probation`` →
    (:meth:`start_draining`) → ``draining`` → either
    (:meth:`mark_retired` — rolling deploy) → ``dead`` → (revive) →
    ``probation``, or (:meth:`remove_replica` — scale-down) →
    ``removed``, a terminal tombstone.  Rows are dynamic:
    :meth:`add_replica` appends one for a scale-up (the router
    registers the newcomer behind probation).
    """

    def __init__(self, n: int, registry=None,
                 retry_budget: Optional[int] = None,
                 probation_ticks: Optional[int] = None):
        self.retry_budget = (
            retry_budget if retry_budget is not None
            else retry_budget_from_env()
        )
        if self.retry_budget < 1:
            raise ValueError(
                f"retry_budget must be >= 1, got {self.retry_budget}"
            )
        self.probation_ticks = (
            probation_ticks if probation_ticks is not None
            else probation_ticks_from_env()
        )
        self._state = ["live"] * n
        self._probation_left = [0] * n
        #: last attributed error per replica (None while healthy).
        self.errors: List[Optional[str]] = [None] * n
        if registry is None:
            noop = _NoopInstrument()
            self.m_dead = self.m_recovered = self.m_retries = noop
            self.m_poisoned = self.m_shed = self.m_probation = noop
            self.m_draining = noop
        else:
            self.m_dead = registry.counter("serve.health.replica_dead")
            self.m_recovered = registry.counter("serve.health.recovered")
            self.m_retries = registry.counter("serve.health.retries")
            self.m_poisoned = registry.counter("serve.health.poisoned")
            self.m_shed = registry.counter("serve.health.shed")
            self.m_probation = registry.gauge("serve.health.probation")
            self.m_draining = registry.gauge("serve.health.draining")

    # ------------------------------------------------------------ state
    @property
    def replicas(self) -> int:
        return len(self._state)

    def state(self, i: int) -> str:
        return self._state[i]

    def is_up(self, i: int) -> bool:
        """Not dead / removed: the replica's tick loop still runs (a
        DRAINING replica keeps ticking — its in-flight work must finish
        or hand off — it is merely fenced from NEW work)."""
        return self._state[i] not in ("dead", "removed")

    def can_admit(self, i: int) -> bool:
        """May take FRESH work: live or probation only — draining, dead
        and removed replicas are all fenced from admissions (and from
        rebalance steals; the router enforces both on this seam)."""
        return self._state[i] in ("live", "probation")

    def in_probation(self, i: int) -> bool:
        return self._state[i] == "probation"

    def is_draining(self, i: int) -> bool:
        return self._state[i] == "draining"

    @property
    def dead_replicas(self) -> List[int]:
        return [i for i, s in enumerate(self._state) if s == "dead"]

    # ------------------------------------------------------ transitions
    def mark_dead(self, i: int, error: str) -> None:
        self._state[i] = "dead"
        self.errors[i] = error
        self._probation_left[i] = 0
        self.m_dead.inc()
        self._gauge_probation()
        self._gauge_draining()

    def start_probation(self, i: int) -> None:
        if self._state[i] != "dead":
            raise ValueError(
                f"replica {i} is {self._state[i]!r}, not dead — only a "
                "dead replica can be revived into probation"
            )
        self._state[i] = "probation"
        self._probation_left[i] = self.probation_ticks
        self.errors[i] = None
        self._gauge_probation()

    def clean_tick(self, i: int) -> bool:
        """One tick survived without an escaping exception.  Returns
        True when this tick GRADUATED the replica out of probation."""
        if self._state[i] != "probation":
            return False
        self._probation_left[i] -= 1
        if self._probation_left[i] > 0:
            return False
        self._state[i] = "live"
        self._gauge_probation()
        return True

    # ------------------------------------- elastic transitions (ISSUE 17)
    def start_draining(self, i: int) -> None:
        """Fence replica ``i`` for a scale-down / rolling-deploy drain:
        it keeps ticking (in-flight work progresses) but takes no fresh
        admissions and no rebalance steals.  Only a live or probation
        replica can start draining (a dead one's work was already
        harvested; a removed one is gone)."""
        if self._state[i] not in ("live", "probation"):
            raise ValueError(
                f"replica {i} is {self._state[i]!r} — only a live or "
                "probation replica can start draining"
            )
        self._state[i] = "draining"
        self._probation_left[i] = 0
        self._gauge_probation()
        self._gauge_draining()

    def mark_retired(self, i: int) -> None:
        """A DRAINED replica steps aside for a rolling deploy: state
        goes ``dead`` so :meth:`start_probation` (via the router's
        ``revive_replica``) can register its replacement — but this is
        an ORDERLY exit, so ``serve.health.replica_dead`` does not
        count it as a failure."""
        if self._state[i] != "draining":
            raise ValueError(
                f"replica {i} is {self._state[i]!r} — only a draining "
                "replica can retire (drain it first)"
            )
        self._state[i] = "dead"
        self.errors[i] = "retired (rolling deploy)"
        self._gauge_draining()

    def add_replica(self) -> int:
        """Scale-up: append one row (born ``dead`` — the router revives
        it straight into probation, so a newcomer earns full trust the
        same way a replacement does).  Returns the new index."""
        self._state.append("dead")
        self._probation_left.append(0)
        self.errors.append(None)
        return len(self._state) - 1

    def remove_replica(self, i: int) -> None:
        """Scale-down tombstone: a drained (or crashed-mid-drain, hence
        dead) replica leaves the fleet.  The row stays — historical
        replica indices in assignments/snapshots remain valid — but the
        state is terminal: never up, never revivable, never counted in
        the probation/draining gauges."""
        if self._state[i] not in ("draining", "dead"):
            raise ValueError(
                f"replica {i} is {self._state[i]!r} — only a draining "
                "or dead replica can be removed (drain it first)"
            )
        self._state[i] = "removed"
        self._probation_left[i] = 0
        self._gauge_probation()
        self._gauge_draining()

    def _gauge_probation(self) -> None:
        self.m_probation.set(
            sum(1 for s in self._state if s == "probation")
        )

    def _gauge_draining(self) -> None:
        self.m_draining.set(
            sum(1 for s in self._state if s == "draining")
        )

    def snapshot(self) -> List[dict]:
        return [
            {
                "replica": i,
                "state": s,
                "probation_left": self._probation_left[i],
                "error": self.errors[i],
            }
            for i, s in enumerate(self._state)
        ]


# -------------------------------------------------- terminal invariant
def verify_terminal_invariant(requests: Sequence,
                              completions: Sequence) -> dict:
    """The chaos harness's oracle: every submitted request terminates
    EXACTLY once with a definite status — zero lost, zero duplicated.

    Returns a report dict; ``report["holds"]`` is the verdict and the
    rest names the evidence (per-status counts, lost/duplicated ids).
    """
    want = {r.id for r in requests}
    seen: dict = {}
    for c in completions:
        seen[c.id] = seen.get(c.id, 0) + 1
    by_status: dict = {"ok": 0, "poisoned": 0, "shed": 0, "deadline": 0}
    for c in completions:
        by_status[c.status] = by_status.get(c.status, 0) + 1
    lost = sorted(want - set(seen))
    duplicated = sorted(i for i, n in seen.items() if n > 1)
    unknown = sorted(set(seen) - want)
    return {
        "submitted": len(want),
        "terminated": len(seen),
        "by_status": by_status,
        "lost": lost,
        "duplicated": duplicated,
        "unknown": unknown,
        "holds": not lost and not duplicated and not unknown,
    }


# -------------------------------------------------------- chaos harness
def chaos_schedule(seed: int, replicas: int, *,
                   crash_iters: Sequence[int] = (3, 9, 17, 29),
                   crash_p: float = 0.75, skew_p: float = 0.5,
                   skew_ms: int = 5, drops: int = 1,
                   scale_ups: int = 0, scale_downs: int = 0,
                   rollout_at: Optional[int] = None,
                   elastic_ticks: Sequence[int] = (2, 24)) -> dict:
    """A seeded randomized fault schedule over the existing fault sites.

    Per replica, independently: with probability ``crash_p`` a
    ``crash@serve_step:N`` (N drawn from ``crash_iters`` — the replica
    dies mid-stream at decode iteration N) and with probability
    ``skew_p`` a ``skew@serve_step:N:ms`` (fail-slow from iteration N).
    Router-level: ``drops`` one-shot ``drop@migrate`` specs — recovery
    re-dispatch frames lost on the wire, detected immediately and
    retried (see ``Router._redispatch``).

    Same seed → same schedule: the chaos battery is reproducible.
    Returns ``{"seed", "replica_faults": [spec-or-None per replica],
    "router_faults": spec-or-None}`` — spec strings in the
    ``CMN_FAULT`` grammar, buildable with
    :func:`~chainermn_tpu.resilience.faults.parse_fault_spec`.

    Elastic events (ISSUE 17): ``scale_ups`` / ``scale_downs`` draw
    that many fleet-size changes at seeded ticks in ``elastic_ticks``,
    and ``rollout_at`` pins a mid-traffic rolling deploy; they land
    under an ``"elastic"`` key ([{"tick", "event"}] sorted by tick)
    the harness fires between router ticks — so drains, handoffs and
    probation graduations interleave with the crash/skew/drop faults
    above.
    """
    rng = random.Random(seed)
    per_replica: List[Optional[str]] = []
    for _ in range(replicas):
        parts = []
        if rng.random() < crash_p:
            parts.append(f"crash@serve_step:{rng.choice(crash_iters)}")
        if rng.random() < skew_p:
            parts.append(
                f"skew@serve_step:{rng.randint(1, 8)}:{skew_ms}ms"
            )
        per_replica.append(";".join(parts) or None)
    if all(p is None or "crash" not in p for p in per_replica):
        # A chaos run with zero crashes proves nothing — force one on a
        # seeded replica (still deterministic per seed).
        victim = rng.randrange(replicas)
        extra = f"crash@serve_step:{rng.choice(crash_iters)}"
        per_replica[victim] = (
            extra if per_replica[victim] is None
            else per_replica[victim] + ";" + extra
        )
    router_faults = ";".join(
        f"drop@migrate:{rng.randint(1, 3) + 2 * k}"
        for k in range(max(0, drops))
    ) or None
    out = {
        "seed": seed,
        "replica_faults": per_replica,
        "router_faults": router_faults,
    }
    events = [
        {"tick": rng.randint(*elastic_ticks), "event": "scale_up"}
        for _ in range(max(0, scale_ups))
    ] + [
        {"tick": rng.randint(*elastic_ticks), "event": "scale_down"}
        for _ in range(max(0, scale_downs))
    ]
    if rollout_at is not None:
        events.append({"tick": int(rollout_at), "event": "rollout"})
    if events:
        out["elastic"] = sorted(events, key=lambda e: e["tick"])
    return out


class ChaosHarness:
    """Drive a multi-replica router through a seeded fault schedule and
    check the terminal invariant.

    ``engine_factory`` builds one fresh
    :class:`~chainermn_tpu.serving.DecodeEngine` per call — the initial
    fleet AND every revival replacement come from it (a dead replica's
    device state is never reused; its engine is garbage).  Dead
    replicas are revived ``revive_after`` ticks after death (behind the
    probation circuit breaker), up to ``max_revives`` times fleet-wide,
    so the run also exercises readmission; revived replicas run
    fault-free (the schedule belongs to the first incarnation).

    The harness is deliberately a thin loop over public Router seams —
    everything it does (``tick``/``revive_replica``/``completions``) a
    production supervisor could do the same way.

    Elastic events (ISSUE 17): a schedule carrying an ``"elastic"``
    list fires scale-ups (``Router.add_replica`` behind probation),
    scale-downs (fence → drain over the cmn-kvmig-1 path → deregister
    the coldest live replica — skipped when the fleet is at one
    admitting replica), and a mid-traffic rolling deploy
    (:class:`~chainermn_tpu.serving.elastic.RollingDeploy`, driven a
    tick at a time) between router ticks, so the crash/skew/drop
    faults land DURING drains and rollouts and the terminal invariant
    is checked across every elastic transition.
    """

    def __init__(self, engine_factory: Callable[[], object],
                 replicas: int = 3, seed: int = 0, registry=None,
                 revive_after: int = 4, max_revives: int = 8,
                 schedule: Optional[dict] = None, **router_kw):
        from chainermn_tpu.resilience.faults import (
            FaultInjector,
            parse_fault_spec,
        )
        from chainermn_tpu.serving.router import Router

        self.engine_factory = engine_factory
        self.registry = registry
        self.schedule = (
            schedule if schedule is not None
            else chaos_schedule(seed, replicas)
        )
        faults = [
            FaultInjector(parse_fault_spec(s)) if s else None
            for s in self.schedule["replica_faults"]
        ]
        rf = self.schedule["router_faults"]
        router_fault = (
            FaultInjector(parse_fault_spec(rf)) if rf else None
        )
        self.router = Router(
            [engine_factory() for _ in range(replicas)],
            registry=registry, faults=faults, fault=router_fault,
            **router_kw,
        )
        self.revive_after = max(1, revive_after)
        self.max_revives = max_revives
        self.revived = 0
        #: ticks-until-revive countdown per currently-dead replica.
        self._revive_in: dict = {}
        #: pending elastic events, sorted by tick (ISSUE 17).
        self._elastic = sorted(
            self.schedule.get("elastic") or (),
            key=lambda e: e["tick"],
        )
        #: what actually fired (replica picked, skips) — the report's
        #: ``elastic`` evidence.
        self.elastic_log: List[dict] = []
        self.rollout = None
        self._tick_no = 0

    def _poll_revivals(self) -> None:
        health = self.router.health
        for i in health.dead_replicas:
            if i not in self._revive_in:
                self._revive_in[i] = self.revive_after
        for i in list(self._revive_in):
            if health.state(i) == "dead":
                self._revive_in[i] -= 1
                if self._revive_in[i] <= 0 and \
                        self.revived < self.max_revives:
                    self.router.revive_replica(i, self.engine_factory())
                    self.revived += 1
                    del self._revive_in[i]
            else:
                # Revived elsewhere, or deregistered (scale-down of a
                # replica that crashed mid-drain) — stop counting.
                del self._revive_in[i]

    # ------------------------------------------- elastic events (ISSUE 17)
    def _coldest_live(self) -> Optional[int]:
        """The scale-down victim: the least-loaded full-trust live
        admitting replica — but never the last one that can admit (a
        fleet of zero admitting replicas deadlocks by construction)."""
        router = self.router
        admitting = [
            i for i in router._admitting if router.health.can_admit(i)
        ]
        cands = [
            i for i in admitting if router.health.state(i) == "live"
        ]
        if not cands or len(admitting) <= 1:
            return None
        return min(cands, key=router._load)

    def _fire_elastic(self) -> None:
        from chainermn_tpu.serving.elastic import RollingDeploy

        while self._elastic and self._elastic[0]["tick"] <= self._tick_no:
            ev = dict(self._elastic.pop(0))
            if ev["event"] == "scale_up":
                ev["replica"] = self.router.add_replica(
                    self.engine_factory()
                )
            elif ev["event"] == "scale_down":
                victim = self._coldest_live()
                if victim is None:
                    ev["skipped"] = "fleet at minimum"
                else:
                    ev["replica"] = victim
                    ev["drain"] = self.router.drain_replica(victim)
                    self.router.deregister_replica(victim)
                    self._revive_in.pop(victim, None)
            elif ev["event"] == "rollout":
                if self.rollout is None:
                    self.rollout = RollingDeploy(
                        self.router, self.engine_factory,
                        registry=self.registry,
                    )
                    ev["replicas"] = list(self.rollout.pending)
                else:  # pragma: no cover - one rollout per schedule
                    ev["skipped"] = "rollout already running"
            self.elastic_log.append(ev)
        if self.rollout is not None:
            self.rollout.tick()

    def run(self, requests: Sequence) -> dict:
        """Submit ``requests``, drain the fleet under the schedule, and
        return the invariant report (plus harness/run bookkeeping).
        Raises if the fleet deadlocks — a chaos run must always
        terminate."""
        router = self.router
        for r in requests:
            router.submit(r)
        stall = 0
        while router.pending:
            progressed = router.tick()
            self._tick_no += 1
            self._fire_elastic()
            self._poll_revivals()
            if progressed:
                stall = 0
                continue
            now = router.clock.now()
            nxt = [
                t for t in (
                    [r.arrival for r in router.queued_requests()[:1]]
                    + [
                        s.next_arrival()
                        for i, s in enumerate(router.schedulers)
                        if s is not None and router.health.is_up(i)
                    ]
                )
                if t is not None and t > now
            ]
            if nxt:
                router.clock.skip_to(min(nxt))
                stall = 0
            elif self._revive_in and self.revived < self.max_revives:
                # Everything that could serve the remaining work is
                # dead and a revival countdown is running — idle ticks
                # count it down (this IS progress toward recovery).
                stall = 0
            elif self._elastic or (
                self.rollout is not None
                and not self.rollout.done and not self.rollout.paused
            ):
                # A pending elastic event (a scale-up may be the only
                # path to capacity) or an in-flight rollout (probation
                # graduation rides clean ticks) — idle ticks progress it.
                stall = 0
            else:
                stall += 1
                if stall > 3:
                    raise RuntimeError(
                        "chaos fleet deadlocked: no progress, no "
                        "arrivals, no revivals pending "
                        f"(health={router.health.snapshot()})"
                    )
        # Let an in-flight rollout finish on an idle fleet (probation
        # graduation needs clean ticks; bounded by the rollout's own
        # stall watchdog + this guard).
        guard = 0
        while self.rollout is not None and not self.rollout.done \
                and not self.rollout.paused:
            router.tick()
            self._tick_no += 1
            self._fire_elastic()
            guard += 1
            if guard > 4 * router.health.probation_ticks * max(
                    1, router.health.replicas):
                raise RuntimeError(
                    "rollout failed to converge on an idle fleet "
                    f"(state={router.health.snapshot()})"
                )
        router.finish()
        report = verify_terminal_invariant(requests, router.completions)
        report["schedule"] = self.schedule
        report["revived"] = self.revived
        report["health"] = router.health.snapshot()
        if self.elastic_log:
            report["elastic"] = self.elastic_log
        if self.rollout is not None:
            report["rollout"] = {
                "replaced": list(self.rollout.replaced),
                "paused": self.rollout.paused,
                "done": self.rollout.done,
            }
        return report
