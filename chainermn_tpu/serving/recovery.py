"""Serving-fleet failure plane: fault isolation, retry budgets, chaos.

ChainerMN inherits MPI's fail-stop model — one dead rank kills the job —
and the resilience package rebuilt the *training* side of that story
(detector, guard, rollback, preemption).  This module is the *serving*
side (ISSUE 15): a production fleet must survive replica death, runaway
requests, and overload without dropping work on the floor.

Four mechanisms, all host-side (no device state is ever trusted after a
failure — recovery is recompute, the same discipline as eviction):

* **Fault-isolated replicas** — the :class:`~chainermn_tpu.serving.
  router.Router` wraps each replica's ``tick()`` in a fault boundary;
  an escaping exception (real, or ``crash@serve_step``-injected) marks
  the replica **dead** instead of aborting the fleet.  The router then
  *harvests* the dead replica's queued entries and live slots into
  recompute ``_QueueEntry`` s (carried + generated tokens preserved —
  exactly the eviction-requeue discipline, so continuations are
  greedy-identical) and re-dispatches them to survivors.  Nothing is
  lost; survivors never recompile (``decode_compiles`` stays 1).

* **Retry budgets + poison quarantine** — every harvested entry's
  ``retries`` count increments with the replica it just killed.  A
  request that has killed :data:`retry_budget` replicas
  (``CMN_SERVE_RETRY_BUDGET``, default 2) is the likely *cause*, not a
  victim: it is quarantined as a failed
  :class:`~chainermn_tpu.serving.scheduler.Completion` with
  ``status="poisoned"`` and the attributed error, instead of being
  re-dispatched until it kills the whole fleet.  Quarantine files a
  critical ``poison_request`` incident bundle.

* **Probation (circuit breaker)** — :meth:`Router.revive_replica`
  re-registers a replacement engine behind a circuit breaker: the
  revived replica takes only *fresh* admissions at reduced dispatch
  weight (never recovered work, never rebalance steals) until
  ``CMN_SERVE_PROBATION_TICKS`` clean ticks pass, so a flapping replica
  cannot thrash the fleet with repeated harvest storms.

* **Graceful degradation** — per-request ``deadline_ms`` (the scheduler
  cancels over-deadline slots and frees their blocks,
  ``status="deadline"``) and router-level load shedding: when surviving
  capacity leaves the holdback queue deeper than
  ``CMN_ROUTER_SHED_DEPTH`` arrived requests, the newest are refused
  with ``status="shed"`` — a bounded queue instead of unbounded latency
  collapse.  Both are *terminal* outcomes: a degraded request still
  terminates exactly once, with a definite status.

Everything is observable as the ``serve.health.*`` metric family, and
``replica_dead`` / ``poison_request`` ship as default incident rules
(both critical — see :func:`chainermn_tpu.observability.incident.
default_rules`).

The **chaos harness** proves the plane: :class:`ChaosHarness` drives a
multi-replica router under a seeded randomized fault schedule over the
existing sites (``crash@serve_step``, ``skew@serve_step`` on replicas;
``drop@migrate`` on the router's recovery re-dispatch path), revives
dead replicas after a configurable cooldown, and checks the **terminal
invariant** request by request with :func:`verify_terminal_invariant`:
every submitted request terminates exactly once (completed, poisoned,
shed, or deadline), zero lost, zero duplicated.  See
``tests/serving_tests/test_chaos.py`` and ``benchmarks/serving.py
--chaos``.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Sequence

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
    _env_float,
)

#: Replica lifecycle states (see :class:`FleetHealth`).
STATES = ("live", "probation", "dead")


# ----------------------------------------------------------- env knobs
def retry_budget_from_env() -> int:
    """``CMN_SERVE_RETRY_BUDGET`` — how many replicas one request may
    kill before it is quarantined as poisoned (default 2)."""
    return max(1, int(_env_float("CMN_SERVE_RETRY_BUDGET", 2)))


def probation_ticks_from_env() -> int:
    """``CMN_SERVE_PROBATION_TICKS`` — clean ticks a revived replica
    serves at reduced weight before rejoining at full trust
    (default 32)."""
    return max(1, int(_env_float("CMN_SERVE_PROBATION_TICKS", 32)))


def shed_depth_from_env() -> int:
    """``CMN_ROUTER_SHED_DEPTH`` — arrived requests the router holds
    back before shedding the newest (0, the default, disables
    shedding: the holdback queue is unbounded, the pre-ISSUE-15
    behavior)."""
    return max(0, int(_env_float("CMN_ROUTER_SHED_DEPTH", 0)))


def deadline_ms_from_env() -> Optional[float]:
    """``CMN_SERVE_DEADLINE_MS`` — fleet-wide default per-request
    deadline applied to requests that carry none of their own (unset or
    ``0`` = no default deadline)."""
    v = _env_float("CMN_SERVE_DEADLINE_MS", 0.0)
    return v if v > 0 else None


# ---------------------------------------------------------- FleetHealth
class FleetHealth:
    """Per-replica state machine + the ``serve.health.*`` instruments.

    Owned by the :class:`~chainermn_tpu.serving.router.Router`; the
    scheduler-side member of the family (``serve.health.
    deadline_cancels``) publishes from the scheduler because deadlines
    are enforced there.

    States: ``live`` → (tick raised) → ``dead`` → (revive) →
    ``probation`` → (:data:`probation_ticks` clean ticks) → ``live``.
    A probation replica that raises goes straight back to ``dead`` —
    the circuit breaker re-opens.
    """

    def __init__(self, n: int, registry=None,
                 retry_budget: Optional[int] = None,
                 probation_ticks: Optional[int] = None):
        self.retry_budget = (
            retry_budget if retry_budget is not None
            else retry_budget_from_env()
        )
        if self.retry_budget < 1:
            raise ValueError(
                f"retry_budget must be >= 1, got {self.retry_budget}"
            )
        self.probation_ticks = (
            probation_ticks if probation_ticks is not None
            else probation_ticks_from_env()
        )
        self._state = ["live"] * n
        self._probation_left = [0] * n
        #: last attributed error per replica (None while healthy).
        self.errors: List[Optional[str]] = [None] * n
        if registry is None:
            noop = _NoopInstrument()
            self.m_dead = self.m_recovered = self.m_retries = noop
            self.m_poisoned = self.m_shed = self.m_probation = noop
        else:
            self.m_dead = registry.counter("serve.health.replica_dead")
            self.m_recovered = registry.counter("serve.health.recovered")
            self.m_retries = registry.counter("serve.health.retries")
            self.m_poisoned = registry.counter("serve.health.poisoned")
            self.m_shed = registry.counter("serve.health.shed")
            self.m_probation = registry.gauge("serve.health.probation")

    # ------------------------------------------------------------ state
    def state(self, i: int) -> str:
        return self._state[i]

    def is_up(self, i: int) -> bool:
        """Not dead: the replica's tick loop still runs."""
        return self._state[i] != "dead"

    def in_probation(self, i: int) -> bool:
        return self._state[i] == "probation"

    @property
    def dead_replicas(self) -> List[int]:
        return [i for i, s in enumerate(self._state) if s == "dead"]

    # ------------------------------------------------------ transitions
    def mark_dead(self, i: int, error: str) -> None:
        self._state[i] = "dead"
        self.errors[i] = error
        self._probation_left[i] = 0
        self.m_dead.inc()
        self._gauge_probation()

    def start_probation(self, i: int) -> None:
        if self._state[i] != "dead":
            raise ValueError(
                f"replica {i} is {self._state[i]!r}, not dead — only a "
                "dead replica can be revived into probation"
            )
        self._state[i] = "probation"
        self._probation_left[i] = self.probation_ticks
        self.errors[i] = None
        self._gauge_probation()

    def clean_tick(self, i: int) -> bool:
        """One tick survived without an escaping exception.  Returns
        True when this tick GRADUATED the replica out of probation."""
        if self._state[i] != "probation":
            return False
        self._probation_left[i] -= 1
        if self._probation_left[i] > 0:
            return False
        self._state[i] = "live"
        self._gauge_probation()
        return True

    def _gauge_probation(self) -> None:
        self.m_probation.set(
            sum(1 for s in self._state if s == "probation")
        )

    def snapshot(self) -> List[dict]:
        return [
            {
                "replica": i,
                "state": s,
                "probation_left": self._probation_left[i],
                "error": self.errors[i],
            }
            for i, s in enumerate(self._state)
        ]


# -------------------------------------------------- terminal invariant
def verify_terminal_invariant(requests: Sequence,
                              completions: Sequence) -> dict:
    """The chaos harness's oracle: every submitted request terminates
    EXACTLY once with a definite status — zero lost, zero duplicated.

    Returns a report dict; ``report["holds"]`` is the verdict and the
    rest names the evidence (per-status counts, lost/duplicated ids).
    """
    want = {r.id for r in requests}
    seen: dict = {}
    for c in completions:
        seen[c.id] = seen.get(c.id, 0) + 1
    by_status: dict = {"ok": 0, "poisoned": 0, "shed": 0, "deadline": 0}
    for c in completions:
        by_status[c.status] = by_status.get(c.status, 0) + 1
    lost = sorted(want - set(seen))
    duplicated = sorted(i for i, n in seen.items() if n > 1)
    unknown = sorted(set(seen) - want)
    return {
        "submitted": len(want),
        "terminated": len(seen),
        "by_status": by_status,
        "lost": lost,
        "duplicated": duplicated,
        "unknown": unknown,
        "holds": not lost and not duplicated and not unknown,
    }


# -------------------------------------------------------- chaos harness
def chaos_schedule(seed: int, replicas: int, *,
                   crash_iters: Sequence[int] = (3, 9, 17, 29),
                   crash_p: float = 0.75, skew_p: float = 0.5,
                   skew_ms: int = 5, drops: int = 1) -> dict:
    """A seeded randomized fault schedule over the existing fault sites.

    Per replica, independently: with probability ``crash_p`` a
    ``crash@serve_step:N`` (N drawn from ``crash_iters`` — the replica
    dies mid-stream at decode iteration N) and with probability
    ``skew_p`` a ``skew@serve_step:N:ms`` (fail-slow from iteration N).
    Router-level: ``drops`` one-shot ``drop@migrate`` specs — recovery
    re-dispatch frames lost on the wire, detected immediately and
    retried (see ``Router._redispatch``).

    Same seed → same schedule: the chaos battery is reproducible.
    Returns ``{"seed", "replica_faults": [spec-or-None per replica],
    "router_faults": spec-or-None}`` — spec strings in the
    ``CMN_FAULT`` grammar, buildable with
    :func:`~chainermn_tpu.resilience.faults.parse_fault_spec`.
    """
    rng = random.Random(seed)
    per_replica: List[Optional[str]] = []
    for _ in range(replicas):
        parts = []
        if rng.random() < crash_p:
            parts.append(f"crash@serve_step:{rng.choice(crash_iters)}")
        if rng.random() < skew_p:
            parts.append(
                f"skew@serve_step:{rng.randint(1, 8)}:{skew_ms}ms"
            )
        per_replica.append(";".join(parts) or None)
    if all(p is None or "crash" not in p for p in per_replica):
        # A chaos run with zero crashes proves nothing — force one on a
        # seeded replica (still deterministic per seed).
        victim = rng.randrange(replicas)
        extra = f"crash@serve_step:{rng.choice(crash_iters)}"
        per_replica[victim] = (
            extra if per_replica[victim] is None
            else per_replica[victim] + ";" + extra
        )
    router_faults = ";".join(
        f"drop@migrate:{rng.randint(1, 3) + 2 * k}"
        for k in range(max(0, drops))
    ) or None
    return {
        "seed": seed,
        "replica_faults": per_replica,
        "router_faults": router_faults,
    }


class ChaosHarness:
    """Drive a multi-replica router through a seeded fault schedule and
    check the terminal invariant.

    ``engine_factory`` builds one fresh
    :class:`~chainermn_tpu.serving.DecodeEngine` per call — the initial
    fleet AND every revival replacement come from it (a dead replica's
    device state is never reused; its engine is garbage).  Dead
    replicas are revived ``revive_after`` ticks after death (behind the
    probation circuit breaker), up to ``max_revives`` times fleet-wide,
    so the run also exercises readmission; revived replicas run
    fault-free (the schedule belongs to the first incarnation).

    The harness is deliberately a thin loop over public Router seams —
    everything it does (``tick``/``revive_replica``/``completions``) a
    production supervisor could do the same way.
    """

    def __init__(self, engine_factory: Callable[[], object],
                 replicas: int = 3, seed: int = 0, registry=None,
                 revive_after: int = 4, max_revives: int = 8,
                 schedule: Optional[dict] = None, **router_kw):
        from chainermn_tpu.resilience.faults import (
            FaultInjector,
            parse_fault_spec,
        )
        from chainermn_tpu.serving.router import Router

        self.engine_factory = engine_factory
        self.schedule = (
            schedule if schedule is not None
            else chaos_schedule(seed, replicas)
        )
        faults = [
            FaultInjector(parse_fault_spec(s)) if s else None
            for s in self.schedule["replica_faults"]
        ]
        rf = self.schedule["router_faults"]
        router_fault = (
            FaultInjector(parse_fault_spec(rf)) if rf else None
        )
        self.router = Router(
            [engine_factory() for _ in range(replicas)],
            registry=registry, faults=faults, fault=router_fault,
            **router_kw,
        )
        self.revive_after = max(1, revive_after)
        self.max_revives = max_revives
        self.revived = 0
        #: ticks-until-revive countdown per currently-dead replica.
        self._revive_in: dict = {}

    def _poll_revivals(self) -> None:
        health = self.router.health
        for i in health.dead_replicas:
            if i not in self._revive_in:
                self._revive_in[i] = self.revive_after
        for i in list(self._revive_in):
            if not health.is_up(i):
                self._revive_in[i] -= 1
                if self._revive_in[i] <= 0 and \
                        self.revived < self.max_revives:
                    self.router.revive_replica(i, self.engine_factory())
                    self.revived += 1
                    del self._revive_in[i]
            else:  # pragma: no cover - defensive (revived elsewhere)
                del self._revive_in[i]

    def run(self, requests: Sequence) -> dict:
        """Submit ``requests``, drain the fleet under the schedule, and
        return the invariant report (plus harness/run bookkeeping).
        Raises if the fleet deadlocks — a chaos run must always
        terminate."""
        router = self.router
        for r in requests:
            router.submit(r)
        stall = 0
        while router.pending:
            progressed = router.tick()
            self._poll_revivals()
            if progressed:
                stall = 0
                continue
            now = router.clock.now()
            nxt = [
                t for t in (
                    [r.arrival for r in router.queued_requests()[:1]]
                    + [
                        s.next_arrival()
                        for i, s in enumerate(router.schedulers)
                        if router.health.is_up(i)
                    ]
                )
                if t is not None and t > now
            ]
            if nxt:
                router.clock.skip_to(min(nxt))
                stall = 0
            elif self._revive_in and self.revived < self.max_revives:
                # Everything that could serve the remaining work is
                # dead and a revival countdown is running — idle ticks
                # count it down (this IS progress toward recovery).
                stall = 0
            else:
                stall += 1
                if stall > 3:
                    raise RuntimeError(
                        "chaos fleet deadlocked: no progress, no "
                        "arrivals, no revivals pending "
                        f"(health={router.health.snapshot()})"
                    )
        router.finish()
        report = verify_terminal_invariant(requests, router.completions)
        report["schedule"] = self.schedule
        report["revived"] = self.revived
        report["health"] = router.health.snapshot()
        return report
