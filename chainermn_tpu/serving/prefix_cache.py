"""Prefix trie over the paged KV pool: map hot prompt prefixes, don't
recompute them.

Real serving traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn history (the vLLM/PagedAttention
automatic-prefix-caching insight, Kwon et al. 2023).  The block-granular
pool is exactly the right substrate: a prompt's KV lives in whole
physical blocks, so a *trie keyed by per-block token content* can hand a
new request the physical blocks an earlier identical prefix already
filled.  Admission then *maps* those blocks into the new slot's block
table (one refcount increment per block — ``BlockAllocator.share``) and
prefill starts at the first unmatched token: prefill cost for a hot
prefix drops to ~zero, and pool capacity effectively grows by the share
rate (N requests over one system prompt hold ONE copy of its blocks).

Structure: each trie node owns one physical block and is keyed by the
**hash chain** ``(parent node, tokens in this block)`` — children are a
dict keyed by the block's exact token tuple, so a chain of full-block
matches is a plain dict walk and two different prefixes can never
collide (tuples compare by content; no lossy hashing).

Three rules keep the trie honest:

* **Full blocks only.**  A node's KV is immutable history — only blocks
  completely filled by their writer are inserted, so a mapped block is
  never written again by anyone... except through copy-on-write:
  :meth:`match` may also lend the *leading j tokens* of a cached block
  (a partial token-level match).  The borrower must COW that block
  before its first write into it (``scheduler._resolve_cow``) — the
  cached original is never mutated.
* **The trie holds a reference** on every cached block
  (``allocator.share`` at insert).  Retiring requests therefore do NOT
  return cached blocks to the free list; the pool trades free blocks
  for reuse potential.
* **Eviction only at ref == 0 holders-wise**: under pool pressure
  :meth:`evict` releases least-recently-used *leaf* nodes whose block
  the trie alone still holds (refcount 1).  A block actively mapped
  into a live slot (refcount > 1) is never evicted from under it, and
  inner nodes outlive their children so every cached chain stays
  reachable from the root.

Multi-tenant quotas (ISSUE 19): every node remembers the tenant that
first cached it (``owner``), and :attr:`quotas` caps how many trie
blocks each named tenant may pin.  The cap is enforced *at insert
time*: a tenant at its quota recycles its OWN least-recently-used
eligible leaf to make room, and stops inserting when it has none —
one tenant's churn can displace only its own cached prefixes, never
another tenant's trie nodes.  Pool-pressure :meth:`evict` stays
tenant-blind (capacity pressure is everyone's problem; isolation is
about who a CACHE WRITER may displace).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class _Node:
    __slots__ = ("tokens", "block", "parent", "children", "stamp",
                 "owner")

    def __init__(self, tokens: Tuple[int, ...], block: int,
                 parent: Optional["_Node"],
                 owner: Optional[str] = None):
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.stamp = 0
        #: tenant that FIRST cached this block (quota accounting);
        #: None = unattributed (quota-exempt).
        self.owner = owner


class PrefixCache:
    """Trie of cached full blocks over one :class:`BlockAllocator`.

    The cache participates in the allocator's refcounting: every node
    holds one reference on its block (taken at :meth:`insert`, dropped
    at eviction/:meth:`clear`), so cached KV survives its writer and is
    reclaimed exactly when the last user lets go.
    """

    def __init__(self, block_len: int, allocator):
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        self.block_len = block_len
        self.allocator = allocator
        self._root_children: Dict[Tuple[int, ...], _Node] = {}
        self._clock = 0
        #: per-tenant trie block caps (ISSUE 19) — the policy plane
        #: shares its live quota view here by reference; tenants not
        #: listed are uncapped.
        self.quotas: Dict[str, int] = {}
        #: live owned-node counts behind the quota check.
        self._owner_count: Dict[str, int] = {}
        # Incremental node count: the scheduler reads it per admission /
        # retirement (the ``serve.prefix.cached_blocks`` gauge), so it
        # must not be a trie walk.
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def cached_blocks(self) -> int:
        return self._count

    # ------------------------------------------------------------ match
    def match(self, tokens: Sequence[int], limit: Optional[int] = None
              ) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``.

        Walks full-block chain matches, then tries one *partial* match:
        a child block whose leading ``j`` tokens (``0 < j < block_len``)
        continue the prompt — the borrower COWs that block before
        writing into it.  ``limit`` caps the matched length (admission
        passes ``len(prompt) - 1`` so the final prefill chunk always has
        at least one real token to sample the first output from).

        Returns ``(blocks, matched)``: the physical blocks backing the
        first ``matched`` tokens (``len(blocks) ==
        ceil(matched / block_len)``; the last is the partial one iff
        ``matched % block_len != 0``).  References are NOT taken — the
        caller shares what it decides to keep.
        """
        BL = self.block_len
        cap = len(tokens) if limit is None else min(limit, len(tokens))
        blocks: List[int] = []
        matched = 0
        self._clock += 1
        children = self._root_children
        while matched + BL <= cap:
            key = tuple(tokens[matched:matched + BL])
            node = children.get(key)
            if node is None:
                break
            node.stamp = self._clock
            blocks.append(node.block)
            matched += BL
            children = node.children
        # Partial tail: the longest leading run of any child's tokens.
        best_j, best_node = 0, None
        remaining = cap - matched
        if remaining > 0:
            for key, node in children.items():
                j = 0
                m = min(remaining, BL - 1)  # a full match was handled above
                while j < m and key[j] == tokens[matched + j]:
                    j += 1
                if j > best_j:
                    best_j, best_node = j, node
        if best_node is not None:
            best_node.stamp = self._clock
            blocks.append(best_node.block)
            matched += best_j
        return blocks, matched

    # ----------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               owner: Optional[str] = None) -> int:
        """Register the FULL blocks backing ``tokens`` (``blocks[i]``
        holds ``tokens[i*BL:(i+1)*BL]``; a trailing partial block must
        not be passed).  Already-cached chains dedupe in place — the
        existing node's block wins (and keeps its original owner) and
        the duplicate is left to its current holders.  Takes one
        allocator reference per NEW node.  Returns the number of nodes
        added.

        ``owner`` attributes each NEW node to a tenant for quota
        accounting: a tenant at its :attr:`quotas` cap recycles its OWN
        least-recently-used eligible leaf per new node, and the insert
        stops early when it has none to recycle — never touching
        another tenant's nodes (ISSUE 19)."""
        BL = self.block_len
        if len(blocks) * BL > len(tokens):
            raise ValueError(
                f"insert: {len(blocks)} blocks need {len(blocks) * BL} "
                f"tokens, got {len(tokens)} — only FULL blocks are "
                "cacheable"
            )
        self._clock += 1
        quota = self.quotas.get(owner) if owner is not None else None
        added = 0
        children = self._root_children
        parent: Optional[_Node] = None
        for i, b in enumerate(blocks):
            key = tuple(tokens[i * BL:(i + 1) * BL])
            node = children.get(key)
            if node is None:
                if quota is not None and \
                        self._owner_count.get(owner, 0) >= quota:
                    # Over quota: make room from this owner's OWN
                    # cached leaves, or stop inserting.  (A node just
                    # added this call is never a victim — its block is
                    # still slot-held, refcount > 1.)
                    if not self._evict_owner(owner):
                        break
                self.allocator.share([b])
                node = _Node(key, b, parent, owner=owner)
                children[key] = node
                self._count += 1
                added += 1
                if owner is not None:
                    self._owner_count[owner] = (
                        self._owner_count.get(owner, 0) + 1
                    )
            node.stamp = self._clock
            parent = node
            children = node.children
        return added

    # ---------------------------------------------------------- evict
    def evict(self, n_blocks: int) -> int:
        """Release up to ``n_blocks`` least-recently-used LEAF nodes
        whose block only the trie still holds (allocator refcount 1 —
        blocks mapped into live slots are untouchable).  Dropping the
        trie's reference reclaims the block to the free list.  Returns
        the number of blocks actually released.

        One DFS collects EVERY currently-eligible leaf (released oldest
        stamp first); the scan repeats only when releasing a whole wave
        exposed new leaves (their parents) and more blocks are still
        needed — O(trie) per wave, not per block."""
        released = 0
        while released < n_blocks:
            eligible: List[_Node] = []
            stack = list(self._root_children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                elif self.allocator.refcount(node.block) == 1:
                    eligible.append(node)
            if not eligible:
                break
            eligible.sort(key=lambda n: n.stamp)
            for victim in eligible[: n_blocks - released]:
                self._detach(victim)
                self.allocator.free([victim.block])
                released += 1
        return released

    def _evict_owner(self, owner: str) -> bool:
        """Release ``owner``'s least-recently-used LEAF node whose
        block only the trie holds (refcount 1) — the quota-recycle
        move.  Returns whether a block was released."""
        victim: Optional[_Node] = None
        stack = list(self._root_children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node.owner == owner and \
                    self.allocator.refcount(node.block) == 1 and \
                    (victim is None or node.stamp < victim.stamp):
                victim = node
        if victim is None:
            return False
        self._detach(victim)
        self.allocator.free([victim.block])
        return True

    def _detach(self, node: _Node) -> None:
        siblings = (
            node.parent.children if node.parent is not None
            else self._root_children
        )
        del siblings[node.tokens]
        self._count -= 1
        if node.owner is not None:
            self._owner_count[node.owner] -= 1

    def clear(self) -> int:
        """Drop every cached reference (gc/retire pass): the allocator
        returns to whatever the live slots alone hold.  Returns the
        number of blocks released."""
        released = 0
        stack = list(self._root_children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.allocator.free([node.block])
            released += 1
        self._root_children = {}
        self._count = 0
        self._owner_count = {}
        return released
