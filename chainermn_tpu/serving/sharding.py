"""GSPMD sharding plan for the pod-scale serving engine.

One engine spanning chips (ROADMAP item 1): the model's parameters and
the paged KV pools are laid out over a 1-D ``jax.sharding.Mesh`` with a
single ``"model"`` axis, and the engine's jitted programs run unchanged —
XLA's GSPMD partitioner propagates the input shardings through the whole
decode/prefill/verify computation, inserting the (two) cross-chip
reductions tensor parallelism fundamentally needs (the attention output
projection and the FFN down-projection, Megatron-LM's classic cut).

The plan, axis by axis:

* **Attention** is sharded head-major: ``q``/``qkv`` kernels on the query
  -head axis, ``kv`` kernels on the KV-head axis, the output projection on
  its (contracted) head axis.  Each chip computes its own heads end to
  end; the ``proj`` contraction is the first psum.
* **FFN / MoE** is sharded on the hidden axis: ``ff1`` column-parallel,
  ``ff2`` row-parallel (the second psum).  MoE expert weights shard the
  same way on their per-expert hidden axis — every chip holds a slice of
  EVERY expert, so routing stays host-invisible.
* **LM head** is vocab-sharded (column-parallel); greedy argmax over the
  sharded vocab is a cheap per-shard argmax + cross-chip max.
* **The paged KV pool** is sharded **kv-head-major**: the pool layout
  ``(KH, num_blocks, block_len, Dh)`` was chosen in PR 4 with exactly
  this cut in mind — axis 0 is the natural shard axis, so each chip owns
  ``KH / n`` heads of EVERY physical block.  Block ids mean the same
  thing on every chip, which is what keeps the host-side bookkeeping
  replicated-trivially:
* **Block tables, the refcounted allocator and the prefix-cache trie
  stay host-side and replicated** — they are pure Python accounting over
  physical block *ids* (never touching pool bytes), so sharding the
  pools leaves them untouched.  The same table upload drives every
  chip's scatter.
* **Everything small** (embeddings, layernorms, positional tables,
  biases of row-parallel layers, control vectors, RNG lanes) is
  replicated.

Embeddings are deliberately replicated rather than vocab-sharded: the
decode step gathers one row per slot per token, and a sharded gather
would turn that into a collective on the hot path for a table that is a
rounding error next to the KV pool.

The Pallas fused/paged decode kernels do not carry GSPMD partitioning
rules, so GSPMD alone cannot propagate through ``pallas_call`` — instead
a sharded ``decode_attention="fused"`` engine runs the kernels **per
shard under** ``shard_map`` (:func:`~chainermn_tpu.ops.
sharded_paged_decode_attention`): queries cut on the head axis, pools on
the KV-head axis 0 (the placement above), block tables replicated.
Attention never crosses KV heads, so the per-shard outputs are
bit-identical to the unsharded kernel's and no new collective lands on
the decode hot path — the row-parallel ``proj`` psum that already exists
completes the reduction.  :func:`attach_decode_mesh` wires the mesh into
the model's dispatch; ``decode_attention="einsum"`` remains an explicit
fallback knob (the gathered path partitions cleanly under plain GSPMD).
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = [
    "serving_mesh",
    "mesh_model_size",
    "validate_geometry",
    "attach_decode_mesh",
    "param_spec",
    "shard_params",
    "pool_placement",
    "replicated",
]

#: The serving mesh's single axis name.  The training-side 3-D mesh
#: (ROADMAP item 5) reuses this vocabulary — ``"model"`` means tensor
#: parallel there too.
MODEL_AXIS = "model"


def serving_mesh(n_model: int, devices: Optional[Sequence] = None):
    """A 1-D ``Mesh`` of ``n_model`` devices on the ``"model"`` axis.

    ``devices`` defaults to the first ``n_model`` of ``jax.devices()``;
    pass an explicit slice to pin a replica to its own device group
    (the router's N-engines-by-M-chips layout).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if n_model < 1:
        raise ValueError(f"n_model must be >= 1, got {n_model}")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if len(devices) < n_model:
        raise ValueError(
            f"serving_mesh(n_model={n_model}) needs {n_model} devices, "
            f"only {len(devices)} available — on CPU, force a pod with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    return Mesh(np.asarray(devices[:n_model]), (MODEL_AXIS,))


def mesh_model_size(mesh) -> int:
    """The ``"model"`` axis extent (1 = effectively unsharded)."""
    return int(mesh.shape[MODEL_AXIS])


def validate_geometry(model, mesh) -> None:
    """Fail fast when ``model``'s geometry cannot split ``n`` ways.

    Only the KV-head axis is MANDATORY: :func:`pool_placement` shards
    every pool on axis 0 and the per-shard Pallas kernels
    (``decode_attention="fused"``) need a whole number of local KV
    heads, so ``KH % n`` must hold (and with GQA, ``H = KH * groups``,
    so the query heads divide whenever KH does).  Any OTHER indivisible
    parameter axis (an odd vocab, a prime ``d_ff``) simply falls back to
    replication leaf-by-leaf in :func:`shard_params` — correct, just
    less parallel — rather than refusing the model.  Both decode paths
    ("fused" shard_map kernels, "einsum" gathered fallback) are legal
    under a mesh.
    """
    n = mesh_model_size(mesh)
    if n == 1:
        return
    kvh = model.n_kv_heads or model.n_heads
    if kvh % n:
        raise ValueError(
            f"model kv heads ({kvh}, the pools' shard axis 0) are not "
            f"divisible by the mesh's '{MODEL_AXIS}' axis ({n}) — the "
            "paged pools shard kv-head-major and the per-shard decode "
            "kernels need whole local KV heads, so KH is the one axis "
            "that must split"
        )


def attach_decode_mesh(model, mesh):
    """Return ``model`` with the serving mesh wired into its decode
    dispatch (``decode_mesh`` static field), so ``decode_attention=
    "fused"`` steps run the Pallas kernels per shard under ``shard_map``.

    A no-op (the same model comes back) for size-1 meshes and for
    einsum engines — their decode path never consults the mesh.
    """
    if mesh_model_size(mesh) == 1 or model.decode_attention != "fused":
        return model
    return model.clone(decode_mesh=mesh)


def param_spec(path: Sequence[str], leaf):
    """``PartitionSpec`` for one parameter leaf, by its flax path.

    The rules mirror the Megatron cut described in the module docstring;
    anything unrecognized is replicated (safe — GSPMD only needs the big
    tensors annotated, propagation does the rest).
    """
    from jax.sharding import PartitionSpec as P

    name = path[-2] if len(path) >= 2 else ""
    leafname = path[-1]
    M = MODEL_AXIS
    if leafname == "kernel":
        if name == "qkv":        # (D, 3, H, Dh) — fused MHA projection
            return P(None, None, M, None)
        if name == "q":          # (D, H, Dh)
            return P(None, M, None)
        if name == "kv":         # (D, 2, KH, Dh)
            return P(None, None, M, None)
        if name == "proj":       # (H, Dh, D) — row-parallel (psum)
            return P(M, None, None)
        if name == "ff1":        # (D, F) — column-parallel
            return P(None, M)
        if name == "ff2":        # (F, D) — row-parallel (psum)
            return P(M, None)
        if name == "lm_head":    # (D, V) — vocab-sharded head
            return P(None, M)
    elif leafname == "bias":
        if name == "qkv":        # (3, H, Dh)
            return P(None, M, None)
        if name == "q":          # (H, Dh)
            return P(M, None)
        if name == "kv":         # (2, KH, Dh)
            return P(None, M, None)
        if name == "ff1":        # (F,)
            return P(M)
        if name == "lm_head":    # (V,)
            return P(M)
        # proj / ff2 biases add AFTER the psum — replicated.
    elif leafname == "moe_w1":   # (E, D, F) — per-expert column cut
        return P(None, None, M)
    elif leafname == "moe_b1":   # (E, F)
        return P(None, M)
    elif leafname == "moe_w2":   # (E, F, D) — per-expert row cut (psum)
        return P(None, M, None)
    # embed / pos / layernorms / router / moe_b2 / scalars: replicated.
    return P()


def shard_params(params, mesh):
    """``device_put`` every parameter leaf onto ``mesh`` under
    :func:`param_spec` — the one-time layout step a sharded engine pays
    at construction.  A leaf whose nominated axis does not divide the
    mesh (odd vocab, prime ``d_ff``) falls back to replication: always
    correct, just less parallel.  Idempotent for already-sharded
    trees."""
    import jax
    from flax import traverse_util
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n = mesh_model_size(mesh)
    flat = traverse_util.flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        spec = param_spec(path, leaf)
        for dim, axis in enumerate(spec):
            if axis is not None and leaf.shape[dim] % n:
                spec = P()
                break
        out[path] = jax.device_put(leaf, NamedSharding(mesh, spec))
    return traverse_util.unflatten_dict(out)


def pool_placement(mesh):
    """Placement callable for :class:`~chainermn_tpu.serving.kv_pool.
    PagedKVPool`: pool entries (rank >= 3 — ``(KH, num_blocks,
    block_len[, Dh])``) shard kv-head-major on axis 0; anything smaller
    replicates."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def place(arr):
        if arr.ndim >= 3:
            spec = P(MODEL_AXIS, *([None] * (arr.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return place


def replicated(mesh):
    """The replicated ``NamedSharding`` control vectors / RNG lanes ride
    up on (one upload, every chip sees the same tables)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return NamedSharding(mesh, P())
