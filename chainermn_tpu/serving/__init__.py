"""Continuous-batching serving engine over a paged KV cache.

The inference-side answer to the ROADMAP's "heavy traffic" north star:
instead of one static ``lm_generate`` batch that pads every request to the
longest member, a fixed-shape decode step runs ``capacity`` slots forever
while the scheduler streams requests through them — admission the moment a
slot and pool blocks free up, retirement the moment EOS lands (Orca-style
iteration-level scheduling over a vLLM-style paged KV pool).

Six layers:

* :mod:`~chainermn_tpu.serving.kv_pool` — the fixed device-resident block
  pool + host-side REFCOUNTED free-list allocator (zero device syncs;
  one physical block can back many block tables).
* :mod:`~chainermn_tpu.serving.prefix_cache` — the prefix trie: hot
  prompt prefixes (system prompts, few-shot templates, multi-turn
  history) are MAPPED into new requests' block tables instead of
  recomputed, with copy-on-write at the first divergent write into a
  shared partial block.
* :mod:`~chainermn_tpu.serving.engine` — the jitted fixed-capacity decode
  step (compiles exactly once; slot churn never recompiles) + chunked
  prefill; optionally one jitted SPECULATIVE round instead (K draft
  proposals verified by one multi-position target forward — up to K+1
  tokens per sequential step, greedy-exact).
* :mod:`~chainermn_tpu.serving.scheduler` — admission queue, prefill/decode
  interleaving, eviction-based backpressure, ``serve.*`` metrics, plus the
  request-lifecycle observability layer: per-request timeline events
  (exportable as Perfetto-loadable Chrome trace JSON via
  :meth:`~chainermn_tpu.serving.scheduler.Scheduler.export_trace`), the
  streaming SLO monitor (``serve.slo.*`` — see
  :mod:`chainermn_tpu.observability.slo`), and a ``"serving"``
  flight-record provider (live slot map + allocator occupancy in every
  crash/preemption/SIGUSR1 snapshot).
* :mod:`~chainermn_tpu.serving.sharding` — the pod-scale GSPMD plan: one
  engine tensor-parallel over a 1-D ``Mesh(("model",))`` — params on the
  Megatron cut, the paged KV pools (target and draft) sharded
  kv-head-major on the layout's purpose-built ``(KH, ...)`` axis, all
  host-side bookkeeping untouched (``DecodeEngine(mesh=...)``).
* :mod:`~chainermn_tpu.serving.router` — N engines × M chips behind
  least-loaded dispatch off each replica's live gauges, per-replica
  admission backpressure (zero requests lost), queued-work rebalance,
  ``serve.router.*`` metrics, and a merged fleet trace that shows one
  request's life across replicas.  Role-aware: a disaggregated fleet's
  decode ranks take migrated slots only, never fresh admissions.
* :mod:`~chainermn_tpu.serving.recovery` — the serving-fleet failure
  plane: the router's per-replica fault boundary state (live /
  probation / dead), retry budgets with poison quarantine, per-request
  deadlines + router load shedding, the ``serve.health.*`` metric
  family, and the seeded chaos harness that proves the terminal
  invariant (every submitted request terminates exactly once).
* :mod:`~chainermn_tpu.serving.elastic` — the elastic fleet: a
  closed-loop :class:`~chainermn_tpu.serving.elastic.Autoscaler`
  (watch-rule signals → scale-up behind probation / scale-down via
  zero-loss drain, hysteresis + cooldown against flapping) and a
  :class:`~chainermn_tpu.serving.elastic.RollingDeploy` controller
  (fence → drain → revive, one replica at a time, health-gated on
  probation graduation; a mid-rollout death pauses and files a
  critical incident).
* :mod:`~chainermn_tpu.serving.policy` — the multi-tenant policy plane:
  one :class:`~chainermn_tpu.serving.policy.PolicyPlane` the Scheduler
  and Router consult at every admission/eviction/steal decision —
  weighted fair queuing over a VTC-style service clock charged from the
  ledger's cost seams, priority preemption through the
  recompute-requeue path, drift-driven chunked-prefill budgeting
  (Sarathi-style, hysteresis-latched), and per-tenant isolation knobs
  (rate limits, prefix-cache quotas, deadline/shed defaults).  All
  host-side: ``decode_compiles == 1`` holds with policy ON.
* :mod:`~chainermn_tpu.serving.disagg` — disaggregated prefill/decode:
  the KV-block migration primitive (live blocks + block table + carried
  tokens shipped as framed ``send_obj`` payloads over the hostcomm p2p
  plane, tables rewritten against the destination allocator —
  byte-identical KV, sharing and hot prefixes survive the move), the
  prefill/decode role loops on top of it, and preemption-aware draining
  (SIGTERM → migrate every live slot to a peer instead of dropping
  requests).

See ``docs/serving.md`` and ``benchmarks/serving.py``.
"""

from chainermn_tpu.serving.disagg import (
    DecodeRole,
    LocalComm,
    MigrationError,
    MigrationTransport,
    PrefillRole,
    drain_all,
    serve_disaggregated,
)
from chainermn_tpu.serving.elastic import Autoscaler, RollingDeploy
from chainermn_tpu.serving.engine import DecodeEngine
from chainermn_tpu.serving.policy import PolicyPlane, TenantPolicy
from chainermn_tpu.serving.kv_pool import (
    BlockAllocator,
    PagedKVPool,
    PoolExhausted,
    blocks_for,
)
from chainermn_tpu.serving.prefix_cache import PrefixCache
from chainermn_tpu.serving.recovery import (
    ChaosHarness,
    FleetHealth,
    chaos_schedule,
    verify_terminal_invariant,
)
from chainermn_tpu.serving.router import Router
from chainermn_tpu.serving.scheduler import (
    Completion,
    Request,
    Scheduler,
)
from chainermn_tpu.serving.sharding import serving_mesh

__all__ = [
    "BlockAllocator",
    "PagedKVPool",
    "PoolExhausted",
    "PrefixCache",
    "blocks_for",
    "DecodeEngine",
    "DecodeRole",
    "LocalComm",
    "MigrationError",
    "MigrationTransport",
    "PrefillRole",
    "Autoscaler",
    "ChaosHarness",
    "RollingDeploy",
    "Completion",
    "FleetHealth",
    "PolicyPlane",
    "Request",
    "Router",
    "Scheduler",
    "TenantPolicy",
    "chaos_schedule",
    "drain_all",
    "serve_disaggregated",
    "serving_mesh",
    "verify_terminal_invariant",
]
