"""Iteration-level scheduler: admission, interleaved prefill, eviction.

Continuous batching (Yu et al. 2022, *Orca*): scheduling decisions happen
every *iteration* (one engine decode step), not once per batch.  A request
joins the running step the moment a slot and enough pool blocks free up,
and leaves the instant it emits EOS or its token budget — the fixed-shape
step never waits for stragglers the way a static ``lm_generate`` batch
pads to its longest member.

Loop shape (one :meth:`Scheduler.run` iteration):

1. **Admit** — FIFO over arrived requests while a slot is free and the
   allocator covers the first prefill chunk.
2. **Prefill one chunk per prefilling slot** (oldest first; chunked so a
   long prompt cannot stall running decodes for its whole length —
   iteration-level interleave — while refilled slots rejoin the decode
   step as fast as the chunking allows).
3. **Decode step** for every live slot, then retire finished ones and
   recycle their blocks.

Backpressure: blocks are allocated lazily (per prefill chunk; one block
per ``block_len`` decoded tokens).  When the pool is exhausted the
scheduler **evicts the youngest-admitted slot** — its blocks return to the
free list and the request re-queues at the FRONT carrying the tokens it
already generated (recompute-style preemption: the re-admission prefills
prompt + carried tokens and continues).  Evicting the youngest keeps the
oldest requests' work; a request that cannot fit the pool even alone
raises :class:`~chainermn_tpu.serving.kv_pool.PoolExhausted` at submit.

Everything observable publishes into the PR-3 metrics registry
(``serve.queue_depth``, ``serve.slot_occupancy``, ``serve.tokens``,
``serve.prefill_ms``/``serve.decode_ms``/``serve.mixed_ms`` on the
registry's FIXED default edges — the cross-rank merge contract holds).
Attribution caveat under async dispatch: only ops with a device readback
are timed end-to-end — the decode step (token readback every iteration)
and FINAL prefill chunks (first-token readback).  A non-final chunk's
timing brackets just its dispatch; its compute drains into the next
synced op, so a decode step that follows un-synced prefill dispatches
would absorb the queued prefill work.  Those iterations are *tagged*:
their step time books to ``serve.mixed_ms``, so ``serve.decode_ms``
holds only clean decode iterations and its p95 is trustworthy (the SLO
monitor's ``token`` stream reads exactly the clean iterations).
Forcing a readback per chunk instead would add real latency to the
admission path, so the scheduler tags rather than syncs.

Request-lifecycle observability (all riding the ``CMN_OBS`` master
switch; ISSUE 6):

* every lifecycle transition (submitted → admitted → each prefill chunk
  → eviction/readmission → per-iteration decode → retired) lands in a
  :class:`~chainermn_tpu.observability.tracing.RequestTimeline` (and is
  mirrored as ``serve.*`` spans into the process span ring, so flight
  records show recent scheduling activity);
  :meth:`Scheduler.export_trace` writes the whole run as Chrome
  trace-event JSON — load it at ui.perfetto.dev (slots as tracks,
  requests as nested slices, evictions as instant events);
* a :class:`~chainermn_tpu.observability.slo.SLOMonitor` tracks TTFT,
  queue-wait, and per-token latency (``serve.slo.*``) with rolling
  p50/p95 and p95-drift detection, checked every
  ``slo.check_every`` decode iterations;
* the scheduler registers a ``"serving"`` flight-record provider: any
  crash / exit-75 preemption / SIGUSR1 snapshot captures the live slot
  map, allocator occupancy, queue depth, and in-flight request ids.

The decode step is also a ``CMN_FAULT`` hook point (site
``serve_step``, counted by decode iteration): ``skew@serve_step:N:ms``
stretches every step from iteration N on — the deterministic way to
test that the SLO drift detector fires.

The clock is injectable; the default counts real seconds from scheduler
construction and can *skip* idle gaps (no busy-waiting between Poisson
arrivals — benchmarks get open-loop arrival semantics with real measured
service times).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
)
from chainermn_tpu.serving.kv_pool import PoolExhausted, blocks_for


@dataclass
class Request:
    """One generation request."""

    id: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_token: Optional[int] = None
    #: arrival time on the scheduler clock (0 = available immediately).
    arrival: float = 0.0
    #: per-request RNG lane seed (sampling only).
    seed: int = 0


@dataclass
class Completion:
    """A finished request: generated tokens + latency accounting.

    ``first_admitted_at`` is when the request FIRST started service;
    ``admitted_at`` is the final admission (they differ only when the
    request was evicted and re-admitted — queueing delay is
    ``first_admitted_at - arrival``, never ``admitted_at - arrival``,
    which would book time already spent in service to the queue).
    """

    id: int
    tokens: List[int]
    reason: str  # "eos" | "length"
    prompt_len: int
    arrival: float
    admitted_at: float
    finished_at: float
    evictions: int = 0
    first_admitted_at: float = 0.0


@dataclass
class _QueueEntry:
    req: Request
    #: tokens generated before an eviction — re-prefilled and kept.
    carried: List[int] = field(default_factory=list)
    evictions: int = 0
    #: when the request FIRST entered a slot (survives evictions).
    first_admit: Optional[float] = None


class _Slot:
    def __init__(self, idx: int, entry: _QueueEntry, max_blocks: int,
                 admit_time: float, admit_seq: int):
        self.idx = idx
        self.entry = entry
        self.text = list(entry.req.prompt) + list(entry.carried)
        self.table = np.zeros((max_blocks,), np.int32)
        self.blocks: List[int] = []
        self.pos = 0                    # positions prefilled so far
        self.generated: List[int] = []  # this admission's new tokens
        self.last_token: int = 0
        self.prefilling = True
        self.admit_time = admit_time
        self.admit_seq = admit_seq

    @property
    def total_generated(self) -> int:
        return len(self.entry.carried) + len(self.generated)


class _Clock:
    """Real seconds since construction, with idle gaps skippable."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._skew = 0.0

    def now(self) -> float:
        return time.perf_counter() - self._t0 + self._skew

    def skip_to(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            self._skew += delta


class Scheduler:
    """Admission queue + iteration-level scheduling over a
    :class:`~chainermn_tpu.serving.engine.DecodeEngine`."""

    def __init__(self, engine, registry=None, clock: Optional[_Clock] = None,
                 slo=None, timeline=None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability import flight as _flight
        from chainermn_tpu.observability import tracing as _tracing
        from chainermn_tpu.observability.metrics import (
            DEFAULT_MS_EDGES,
            registry as global_registry,
        )
        from chainermn_tpu.observability.slo import SLOMonitor
        from chainermn_tpu.resilience import faults as _faults

        self.engine = engine
        self.clock = clock or _Clock()
        self._queue: List[_QueueEntry] = []
        self._slots: List[Optional[_Slot]] = [None] * engine.capacity
        self._admit_seq = 0
        self.completions: List[Completion] = []
        self._iterations = 0
        #: True while non-final prefill chunks dispatched since the last
        #: device readback may still be draining — the next decode step's
        #: wall time would absorb them (the ``serve.mixed_ms`` tag).
        self._unsynced_prefill = False
        self._fault = _faults.process_injector()
        enabled = _obs.enabled()
        # An explicitly passed registry always publishes; the ambient
        # global registry rides the CMN_OBS master switch like every
        # other publisher (latched here, same as resilience/guard.py).
        if registry is None and not enabled:
            noop = _NoopInstrument()
            self._m_queue = self._m_occ = self._m_tokens = noop
            self._m_prefill = self._m_decode = self._m_mixed = noop
            reg = None
        else:
            reg = registry if registry is not None else global_registry()
            self._m_queue = reg.gauge("serve.queue_depth")
            self._m_occ = reg.gauge("serve.slot_occupancy")
            self._m_tokens = reg.counter("serve.tokens")
            self._m_prefill = reg.histogram(
                "serve.prefill_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_decode = reg.histogram(
                "serve.decode_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_mixed = reg.histogram(
                "serve.mixed_ms", edges=DEFAULT_MS_EDGES
            )
        #: SLO monitor: an explicit one always wins; otherwise it shares
        #: the scheduler's publishing decision (same registry, no-op
        #: when the master switch turned metrics off).
        self.slo = slo if slo is not None else (
            SLOMonitor(registry=reg) if reg is not None else None
        )
        #: Request-lifecycle timeline: explicit wins; else ride the
        #: master switch, mirroring events into the process span ring
        #: (flight records then show recent serving activity).
        if timeline is not None:
            self.timeline = timeline
        elif enabled:
            self.timeline = _tracing.RequestTimeline(
                ring=_tracing.tracer().ring
            )
        else:
            self.timeline = None
        # Flight-record provider — ungated by CMN_OBS, like the recorder
        # itself (it answers only to CMN_OBS_FLIGHT*).  Keyed, so the
        # newest scheduler replaces a finished one's state; held via
        # weakref so the provider registry never pins a dropped
        # scheduler (and through it the engine's device KV pools).
        import weakref

        ref = weakref.ref(self)
        _flight.register_provider(
            "serving",
            lambda: (
                s._flight_state() if (s := ref()) is not None
                else {"released": True}
            ),
        )
        # Arm the env-configured recorder (same as Trainer.__init__): a
        # pure serving process would otherwise never install the SIGUSR1
        # live-snapshot handler — the signal's default action KILLS the
        # engine instead of snapshotting it.  No-op when
        # CMN_OBS_FLIGHT_DIR is unset.
        _flight.recorder()

    # ---------------------------------------------------------- admission
    def submit(self, req: Request) -> None:
        """Enqueue; raises :class:`PoolExhausted` if the request could
        never fit the pool/slot geometry even running alone."""
        plen = len(req.prompt)
        if plen < 1:
            raise ValueError(f"request {req.id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(f"request {req.id}: max_new_tokens < 1")
        eng = self.engine
        cap = eng.max_blocks * eng.block_len
        total = plen + req.max_new_tokens
        # Worst-case prefill END over every possible (re-)admission: a
        # slot prefills prompt + carried tokens (carried grows to
        # max_new - 1 under eviction/recompute), full-size chunks while
        # more than prefill_chunk remains, then the smallest ladder size
        # covering the tail.  The padded tail must stay inside the block
        # table (pad writes past it would clamp onto real blocks) and,
        # for learned-pos models, inside the position table (the
        # dynamic_slice would clamp and embed real tokens at wrong
        # positions).  Rounding total up to a full prefill_chunk
        # overstates this (the ladder tail is tighter) and would reject
        # servable requests whenever the cap is not a chunk multiple.
        worst_end = self._worst_prefill_end(plen, total - 1)
        if total > cap or worst_end > cap:
            raise PoolExhausted(
                f"request {req.id}: {plen}+{req.max_new_tokens} tokens "
                f"(worst padded prefill end {worst_end}) exceeds the "
                f"per-slot cap {cap} (max_blocks={eng.max_blocks} x "
                f"block_len={eng.block_len})"
            )
        if blocks_for(total, eng.block_len) > eng.pool.num_blocks - 1:
            raise PoolExhausted(
                f"request {req.id}: needs "
                f"{blocks_for(total, eng.block_len)} blocks, pool has "
                f"{eng.pool.num_blocks - 1} allocatable"
            )
        if eng.model.pos_enc == "learned" and worst_end > eng.model.max_len:
            raise ValueError(
                f"request {req.id}: worst padded prefill end {worst_end} "
                f"exceeds the learned position table "
                f"({eng.model.max_len}); use a rope model or shorter "
                "requests"
            )
        self._queue.append(_QueueEntry(req))
        if self.timeline is not None:
            # Stamped at the request's logical availability (its arrival
            # on the scheduler clock) — the same origin the queue-wait
            # metric uses, so the queue slice and the histogram agree.
            self.timeline.record(
                "submit", t=float(req.arrival), req=req.id,
                info={"prompt_len": plen, "max_new": req.max_new_tokens},
            )

    def _worst_prefill_end(self, lo: int, hi: int) -> int:
        """Max padded prefill end over admission text lengths in
        ``[lo, hi]`` (prompt alone up to prompt + max_new - 1 carried).

        For text length ``t``: full chunks cover ``t - t % C`` positions
        (``C = prefill_chunk``), the tail pays the smallest ladder size
        covering ``t % C``.  The end is residue-monotone in ``t``, so
        scanning the top ``C`` lengths covers every residue's maximum —
        O(prefill_chunk) per submit, host-side only.
        """
        ladder = self.engine.prefill_ladder
        C = ladder[-1]
        worst = 0
        for t in range(max(lo, hi - C + 1), hi + 1):
            r = t % C
            end = t if r == 0 else t - r + next(
                c for c in ladder if c >= r
            )
            worst = max(worst, end)
        return worst

    def _try_admit(self) -> bool:
        if not self._queue:
            return False
        now = self.clock.now()
        entry = self._queue[0]
        if entry.req.arrival > now:
            return False
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return False
        text_len = len(entry.req.prompt) + len(entry.carried)
        first = blocks_for(
            min(self.engine.prefill_chunk, text_len),
            self.engine.block_len,
        )
        if not self.engine.pool.allocator.can_alloc(first):
            return False
        self._queue.pop(0)
        if entry.first_admit is None:
            entry.first_admit = now
            if self.slo is not None:
                self.slo.observe(
                    "queue_wait", (now - entry.req.arrival) * 1e3
                )
        slot = _Slot(free[0], entry, self.engine.max_blocks, now,
                     self._admit_seq)
        self._admit_seq += 1
        self._slots[free[0]] = slot
        self.engine.seed_slot(free[0], entry.req.seed,
                              entry.req.temperature)
        if self.timeline is not None:
            self.timeline.record(
                "admit", t=now, req=entry.req.id, slot=free[0],
                info={"readmit": entry.evictions > 0} if entry.evictions
                else None,
            )
        return True

    # ----------------------------------------------------------- eviction
    def _evict_youngest(self) -> bool:
        live = [s for s in self._slots if s is not None]
        if not live:
            return False
        victim = max(live, key=lambda s: s.admit_seq)
        self.engine.release_blocks(victim.blocks)
        victim.entry.carried = (
            list(victim.entry.carried) + list(victim.generated)
        )
        victim.entry.evictions += 1
        self._queue.insert(0, victim.entry)
        self._slots[victim.idx] = None
        if self.timeline is not None:
            self.timeline.record(
                "evict", t=self.clock.now(), req=victim.entry.req.id,
                slot=victim.idx,
                info={"carried": len(victim.entry.carried)},
            )
        return True

    def _alloc_for(self, slot: _Slot, n_needed: int) -> None:
        """Grow ``slot`` to ``n_needed`` blocks, evicting under pressure."""
        while len(slot.blocks) < n_needed:
            if self._slots[slot.idx] is not slot:
                # Already evicted — e.g. a co-slot's allocation earlier in
                # the same step chose it as the youngest victim.  Growing
                # it now would orphan the new blocks (the re-admission
                # builds a fresh slot), i.e. leak pool memory.
                return
            got = self.engine.alloc_blocks(n_needed - len(slot.blocks))
            if got is not None:
                for b in got:
                    slot.table[len(slot.blocks)] = b
                    slot.blocks.append(b)
                return
            # Pool exhausted: evict the youngest slot (possibly `slot`
            # itself — then this allocation is moot) and retry.
            victim_was_self = (
                self._slots[slot.idx] is slot
                and max(
                    (s.admit_seq for s in self._slots if s is not None),
                ) == slot.admit_seq
            )
            if victim_was_self and sum(
                s is not None for s in self._slots
            ) == 1:
                raise PoolExhausted(
                    f"request {slot.entry.req.id} cannot fit the pool "
                    "even running alone — grow num_blocks"
                )
            self._evict_youngest()
            if self._slots[slot.idx] is not slot:
                return  # the needy slot evicted itself; re-queued

    # ------------------------------------------------------------ prefill
    def _prefill_round(self) -> bool:
        """One chunk for EVERY currently-prefilling slot (oldest first).

        One chunk per slot per iteration keeps the interleave bound — a
        long prompt still cannot stall running decodes for its whole
        length — while refilled slots rejoin the decode step as fast as
        the chunking allows.  Prefilling only one slot per iteration
        would serialize re-admissions: after a near-simultaneous batch of
        retirements (common when similar-length requests were admitted
        together), the decode step would run under-occupied for several
        extra iterations.
        """
        progressed = False
        for slot in sorted(
            (s for s in self._slots if s is not None and s.prefilling),
            key=lambda s: s.admit_seq,
        ):
            if self._slots[slot.idx] is not slot:
                continue  # evicted by an earlier candidate's allocation
            progressed = self._prefill_chunk(slot) or progressed
        return progressed

    def _prefill_chunk(self, slot: _Slot) -> bool:
        eng = self.engine
        p0 = slot.pos
        # Ladder policy: full-size chunks while more than prefill_chunk
        # tokens remain, then the smallest ladder geometry covering the
        # tail — one final call with minimal padded compute instead of a
        # full prefill_chunk of mostly-pad forward.
        remaining = len(slot.text) - p0
        ladder = eng.prefill_ladder
        if remaining >= ladder[-1]:
            size = ladder[-1]
        else:
            size = next(c for c in ladder if c >= remaining)
        end = min(p0 + size, len(slot.text))
        self._alloc_for(slot, blocks_for(end, eng.block_len))
        if self._slots[slot.idx] is not slot:
            return True  # evicted itself under pressure; progress made
        chunk = np.zeros((size,), np.int32)
        chunk[: end - p0] = slot.text[p0:end]
        last = end == len(slot.text)
        tc = self.clock.now()
        t0 = time.perf_counter()
        tok = eng.prefill(
            slot.idx, chunk, p0, slot.table,
            last_idx=(end - p0 - 1) if last else -1,
        )
        dur_ms = (time.perf_counter() - t0) * 1e3
        self._m_prefill.observe(dur_ms)
        # A final chunk's first-token readback drains every dispatch
        # queued before it; a non-final chunk is dispatch-only and its
        # compute drains into the NEXT synced op (the mixed-iteration
        # tag the decode step reads).
        self._unsynced_prefill = not last
        if self.timeline is not None:
            self.timeline.record(
                "prefill", t=tc, req=slot.entry.req.id, slot=slot.idx,
                dur_ms=dur_ms,
                info={"p0": p0, "end": end, "final": last},
            )
        slot.pos = end
        if last:
            slot.prefilling = False
            first_token_ever = not slot.entry.carried
            self._emit(slot, int(tok))
            if first_token_ever and self.slo is not None:
                self.slo.observe(
                    "ttft",
                    (self.clock.now() - slot.entry.req.arrival) * 1e3,
                )
        return True

    # ------------------------------------------------------------- decode
    def _decode_step(self) -> bool:
        live = [
            s for s in self._slots if s is not None and not s.prefilling
        ]
        if not live:
            return False
        S = self.engine.capacity
        tokens = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        tables = np.zeros((S, self.engine.max_blocks), np.int32)
        active = np.zeros((S,), bool)
        for s in live:
            # The step writes position `pos` — make sure its block exists.
            self._alloc_for(
                s, blocks_for(s.pos + 1, self.engine.block_len)
            )
        live = [
            s for s in self._slots if s is not None and not s.prefilling
        ]
        if not live:
            return True  # everything evicted itself; still progress
        for s in live:
            tokens[s.idx] = s.last_token
            pos[s.idx] = s.pos
            tables[s.idx] = s.table
            active[s.idx] = True
        mixed = self._unsynced_prefill
        self._iterations += 1
        tc = self.clock.now()
        t0 = time.perf_counter()
        if self._fault is not None:
            # ``skew@serve_step:N:ms`` — inside the timed window, so an
            # injected stretch lands in this iteration's histogram
            # exactly like a real slowdown would.
            self._fault.hook("serve_step", count=self._iterations)
        out = self.engine.step(tokens, pos, tables, active)
        dur_ms = (time.perf_counter() - t0) * 1e3
        # The token readback above drained the dispatch queue: any
        # prefill work queued before this step has now been absorbed
        # into dur_ms — book the contaminated iteration separately so
        # serve.decode_ms (and the SLO token stream) stay clean.
        self._unsynced_prefill = False
        if mixed:
            self._m_mixed.observe(dur_ms)
        else:
            self._m_decode.observe(dur_ms)
            if self.slo is not None:
                self.slo.observe("token", dur_ms)
        if self.timeline is not None:
            self.timeline.record(
                "decode", t=tc, dur_ms=dur_ms,
                info={"reqs": [(s.idx, s.entry.req.id) for s in live],
                      "mixed": mixed},
            )
        if self.slo is not None and \
                self._iterations % self.slo.check_every == 0:
            self.slo.check()
        for s in live:
            s.pos += 1
            self._emit(s, int(out[s.idx]))
        return True

    def _emit(self, slot: _Slot, tok: int) -> None:
        """Account one generated token; retire the slot when done."""
        self._m_tokens.inc()
        slot.generated.append(tok)
        slot.last_token = tok
        req = slot.entry.req
        reason = None
        if req.eos_token is not None and tok == req.eos_token:
            reason = "eos"
        elif slot.total_generated >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        self.engine.release_blocks(slot.blocks)
        self._slots[slot.idx] = None
        now = self.clock.now()
        self.completions.append(Completion(
            id=req.id,
            tokens=list(slot.entry.carried) + list(slot.generated),
            reason=reason,
            prompt_len=len(req.prompt),
            arrival=req.arrival,
            admitted_at=slot.admit_time,
            finished_at=now,
            evictions=slot.entry.evictions,
            first_admitted_at=slot.entry.first_admit,
        ))
        if self.timeline is not None:
            self.timeline.record(
                "retire", t=now, req=req.id, slot=slot.idx,
                info={"reason": reason,
                      "tokens": slot.total_generated},
            )

    # --------------------------------------------------------------- run
    def run(self, requests: Optional[Sequence[Request]] = None
            ) -> List[Completion]:
        """Submit ``requests`` (optional) and drain queue + slots."""
        for r in requests or ():
            self.submit(r)
        while self._queue or any(s is not None for s in self._slots):
            progressed = False
            while self._try_admit():
                progressed = True
            if self._prefill_round():
                progressed = True
            if self._decode_step():
                progressed = True
            self._m_queue.set(len(self._queue))
            self._m_occ.set(
                sum(s is not None for s in self._slots)
                / self.engine.capacity
            )
            if not progressed:
                if not any(s is not None for s in self._slots):
                    # Idle: jump the clock to the HEAD entry's arrival —
                    # admission is strictly FIFO, so the head is the only
                    # entry whose arrival can unblock anything; skipping
                    # to a later entry's earlier arrival would leave the
                    # loop spinning until the head's time on the real
                    # clock.
                    self.clock.skip_to(self._queue[0].req.arrival)
                else:  # pragma: no cover - defensive
                    raise RuntimeError(
                        "scheduler made no progress with live slots"
                    )
        self._m_queue.set(0)
        self._m_occ.set(0.0)
        if self.slo is not None:
            self.slo.check()
        return list(self.completions)

    # ------------------------------------------------------- observability
    def _flight_state(self) -> dict:
        """The ``"serving"`` flight-record section: what this engine is
        serving *right now* — readable even while :meth:`run` is live
        (every field is a host-side scalar or small list; worst case a
        torn read shows one admission ago)."""
        slots = []
        for i, s in enumerate(self._slots):
            if s is None:
                slots.append(None)
                continue
            slots.append({
                "req": s.entry.req.id,
                "pos": int(s.pos),
                "prefilling": bool(s.prefilling),
                "generated": len(s.generated),
                "carried": len(s.entry.carried),
                "blocks": len(s.blocks),
            })
        state = {
            "iterations": self._iterations,
            "queue_depth": len(self._queue),
            "queued_requests": [e.req.id for e in self._queue[:64]],
            "in_flight_requests": [
                s["req"] for s in slots if s is not None
            ],
            "slots": slots,
            "completions": len(self.completions),
            "clock": round(self.clock.now(), 6),
            "engine": self.engine.stats(),
        }
        if self.slo is not None and self.slo.last_report:
            state["slo"] = self.slo.last_report
        if self.timeline is not None:
            state["timeline_events"] = len(self.timeline)
            state["timeline_dropped"] = self.timeline.dropped
        return state

    def export_trace(self, path: str, rank: int = 0) -> Optional[str]:
        """Write this run's request timeline as Chrome trace-event JSON
        (Perfetto-loadable); returns the path, or None when lifecycle
        tracing is off (``CMN_OBS=0`` and no explicit timeline)."""
        if self.timeline is None:
            return None
        from chainermn_tpu.observability.tracing import write_chrome_trace

        return write_chrome_trace(path, self.timeline.events(), rank=rank)
