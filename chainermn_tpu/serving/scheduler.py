"""Iteration-level scheduler: admission, interleaved prefill, eviction.

Continuous batching (Yu et al. 2022, *Orca*): scheduling decisions happen
every *iteration* (one engine decode step), not once per batch.  A request
joins the running step the moment a slot and enough pool blocks free up,
and leaves the instant it emits EOS or its token budget — the fixed-shape
step never waits for stragglers the way a static ``lm_generate`` batch
pads to its longest member.

Loop shape (one :meth:`Scheduler.run` iteration):

1. **Admit** — FIFO over arrived requests while a slot is free and the
   allocator covers the first prefill chunk.
2. **Prefill one chunk per prefilling slot** (oldest first; chunked so a
   long prompt cannot stall running decodes for its whole length —
   iteration-level interleave — while refilled slots rejoin the decode
   step as fast as the chunking allows).
3. **Decode step** for every live slot, then retire finished ones and
   recycle their blocks.

**Prefix sharing** (engines built with ``prefix_cache=True``, the
default): admission asks the engine's
:class:`~chainermn_tpu.serving.prefix_cache.PrefixCache` for the longest
cached prefix of ``prompt + carried`` and MAPS those physical blocks into
the new slot's table (one refcount each — never a copy, never a
recompute); prefill resumes at the first unmatched token.  A *partial*
match lends the leading tokens of a cached block — the slot carries a
pending **copy-on-write** and resolves it at its first write into that
block (fresh block allocated, one jitted whole-block copy across every
pool, borrowed reference dropped), so the cached original is never
mutated.  Completed prefixes are inserted back: full prompt blocks when
prefill finishes, full ``prompt + generated`` blocks at retirement
(multi-turn reuse — the next turn's prompt embeds this turn's history).

**Speculative decoding** (engines built with ``draft_model``/``spec_k``):
the decode step becomes one speculative *round* — ``k`` draft proposals
per slot verified by ONE multi-position target forward — emitting
1..``k + 1`` tokens per slot per iteration.  EOS/budget retirement is
checked token-by-token inside the round (over-accepted tails are
dropped; their K/V is causally masked and rewritten later — rollback is
the block table simply not advancing, refcounts make that safe under
sharing).  Per-slot acceptance feeds ``serve.spec.*``.

Backpressure: blocks are allocated lazily (per prefill chunk; one block
per ``block_len`` decoded tokens; a speculative engine allocates
``spec_k`` positions ahead for the verify chunk's writes).  When the
free list runs dry the scheduler first **drains the prefix cache**
(least-recently-used trie leaves nobody else holds — cached blocks are
reuse *potential*, a live request beats them), then **evicts the
youngest-admitted slot** — its references return to the allocator and
the request re-queues at the FRONT carrying the tokens it already
generated (recompute-style preemption: the re-admission re-matches the
trie — usually its own just-cached prefix — then prefills the remainder
and continues).  Evicting the youngest keeps the oldest requests' work;
a request that cannot fit the pool even alone raises
:class:`~chainermn_tpu.serving.kv_pool.PoolExhausted` at submit.

Everything observable publishes into the PR-3 metrics registry
(``serve.queue_depth``, ``serve.slot_occupancy``, ``serve.tokens``,
``serve.prefill_ms``/``serve.decode_ms``/``serve.mixed_ms`` on the
registry's FIXED default edges — the cross-rank merge contract holds).
Attribution caveat under async dispatch: only ops with a device readback
are timed end-to-end — the decode step (token readback every iteration)
and FINAL prefill chunks (first-token readback).  A non-final chunk's
timing brackets just its dispatch; its compute drains into the next
synced op, so a decode step that follows un-synced prefill dispatches
would absorb the queued prefill work.  Those iterations are *tagged*:
their step time books to ``serve.mixed_ms``, so ``serve.decode_ms``
holds only clean decode iterations and its p95 is trustworthy (the SLO
monitor's ``token`` stream reads exactly the clean iterations).
Forcing a readback per chunk instead would add real latency to the
admission path, so the scheduler tags rather than syncs.

Request-lifecycle observability (all riding the ``CMN_OBS`` master
switch; ISSUE 6):

* every lifecycle transition (submitted → admitted → each prefill chunk
  → eviction/readmission → per-iteration decode → retired) lands in a
  :class:`~chainermn_tpu.observability.tracing.RequestTimeline` (and is
  mirrored as ``serve.*`` spans into the process span ring, so flight
  records show recent scheduling activity);
  :meth:`Scheduler.export_trace` writes the whole run as Chrome
  trace-event JSON — load it at ui.perfetto.dev (slots as tracks,
  requests as nested slices, evictions as instant events);
* a :class:`~chainermn_tpu.observability.slo.SLOMonitor` tracks TTFT,
  queue-wait, and per-token latency (``serve.slo.*``) with rolling
  p50/p95 and p95-drift detection, checked every
  ``slo.check_every`` decode iterations;
* the scheduler registers a ``"serving"`` flight-record provider: any
  crash / exit-75 preemption / SIGUSR1 snapshot captures the live slot
  map, allocator occupancy, queue depth, and in-flight request ids;
* the incident plane (ISSUE 12): the scheduler evaluates the process
  :class:`~chainermn_tpu.observability.incident.IncidentManager`'s
  watch rules on the same SLO-check cadence (and once at drain) — a
  breaching ``serve.slo.p95_drift`` captures ONE deduplicated debug
  bundle (flight record, span-ring trace window, metrics snapshot, the
  newest SLO report and live slot map) under ``CMN_OBS_INCIDENT_DIR``.

The decode step is also a ``CMN_FAULT`` hook point (site
``serve_step``, counted by decode iteration): ``skew@serve_step:N:ms``
stretches every step from iteration N on — the deterministic way to
test that the SLO drift detector fires.

The clock is injectable; the default counts real seconds from scheduler
construction and can *skip* idle gaps (no busy-waiting between Poisson
arrivals — benchmarks get open-loop arrival semantics with real measured
service times).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
)
from chainermn_tpu.serving.kv_pool import PoolExhausted, blocks_for


@dataclass
class Request:
    """One generation request."""

    id: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_token: Optional[int] = None
    #: arrival time on the scheduler clock (0 = available immediately).
    arrival: float = 0.0
    #: per-request RNG lane seed (sampling only).
    seed: int = 0
    #: optional deadline, milliseconds after ``arrival``: a request
    #: still unfinished past it is CANCELLED (slot freed, blocks
    #: released, ``Completion.status == "deadline"``) — graceful
    #: degradation under overload instead of unbounded latency.  None
    #: defers to the fleet-wide ``CMN_SERVE_DEADLINE_MS`` default
    #: (itself off unless set).
    deadline_ms: Optional[float] = None
    #: tenant label for cost attribution (ISSUE 16): the usage ledger
    #: aggregates per-tenant totals under it (``serve.tenant.*``).
    #: Additive like ``deadline_ms`` — old callers and pre-ISSUE-16
    #: ``cmn-kvmig-1`` frames default to ``"default"``.
    tenant: str = "default"
    #: priority class (ISSUE 19): under a
    #: :class:`~chainermn_tpu.serving.policy.PolicyPlane`, a strictly
    #: higher class may preempt a running lower-class slot through the
    #: recompute-requeue path; 0 defers to the tenant's default class.
    #: Additive like ``tenant`` — old callers and pre-ISSUE-19
    #: ``cmn-kvmig-1`` frames default to 0, and the field rides the
    #: codec so a harvested/migrated entry keeps its class.
    priority: int = 0


@dataclass
class Completion:
    """A finished request: generated tokens + latency accounting.

    ``first_admitted_at`` is when the request FIRST started service;
    ``admitted_at`` is the final admission (they differ only when the
    request was evicted and re-admitted — queueing delay is
    ``first_admitted_at - arrival``, never ``admitted_at - arrival``,
    which would book time already spent in service to the queue).

    ``prefix_hit_tokens`` counts prompt+carried tokens served from the
    prefix cache, summed over every admission of this request;
    ``spec_proposed``/``spec_accepted`` are this request's own draft
    bookkeeping (greedy slots only — sampling slots never accept).
    """

    id: int
    tokens: List[int]
    reason: str  # "eos" | "length" | "poisoned" | "shed" | "deadline"
    prompt_len: int
    arrival: float
    admitted_at: float
    finished_at: float
    evictions: int = 0
    first_admitted_at: float = 0.0
    prefix_hit_tokens: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: terminal outcome (ISSUE 15): ``"ok"`` is a normal completion;
    #: ``"poisoned"`` exhausted its retry budget killing replicas,
    #: ``"shed"`` was refused by router load shedding, ``"deadline"``
    #: was cancelled past its deadline.  Every submitted request gets
    #: exactly one Completion with a definite status — the chaos
    #: harness's terminal invariant.
    status: str = "ok"
    #: attributed error for non-ok statuses (e.g. the replica-killing
    #: exception a poisoned request carries).
    error: Optional[str] = None
    #: replica deaths this request was harvested from (recovery
    #: re-dispatch count — see ``CMN_SERVE_RETRY_BUDGET``).
    retries: int = 0
    #: the finalized :class:`~chainermn_tpu.observability.ledger.
    #: UsageRecord` for this request (ISSUE 16) — per-tenant cost
    #: attribution (prefill/decode/block-seconds/migration/retries).
    #: ``None`` when the ledger is off (``CMN_OBS_LEDGER=0`` or
    #: observability disabled); additive, so every existing constructor
    #: and the disagg/recovery paths stay green.
    usage: Optional[object] = None


@dataclass
class _QueueEntry:
    req: Request
    #: tokens generated before an eviction — re-prefilled and kept.
    carried: List[int] = field(default_factory=list)
    evictions: int = 0
    #: when the request FIRST entered a slot (survives evictions).
    first_admit: Optional[float] = None
    #: lifetime accounting carried across evictions.
    prefix_hit_tokens: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: replica deaths this entry has been harvested from (the retry
    #: budget's counter — incremented by the router's fault boundary).
    retries: int = 0
    #: the most recent replica-killing error, attributed to this entry
    #: if it exhausts the budget and is quarantined.
    last_error: Optional[str] = None


def terminal_completion(entry: _QueueEntry, status: str, now: float,
                        error: Optional[str] = None) -> Completion:
    """The ONE terminal-Completion shape for requests that end without
    serving to completion (poisoned / shed / deadline) — the scheduler
    AND the router both build through here so the accounting can never
    diverge between the three terminal paths (ISSUE 15)."""
    return Completion(
        id=entry.req.id,
        tokens=list(entry.carried),
        reason=status,
        prompt_len=len(entry.req.prompt),
        arrival=entry.req.arrival,
        admitted_at=(
            entry.first_admit if entry.first_admit is not None else now
        ),
        finished_at=now,
        evictions=entry.evictions,
        first_admitted_at=entry.first_admit or 0.0,
        prefix_hit_tokens=entry.prefix_hit_tokens,
        spec_proposed=entry.spec_proposed,
        spec_accepted=entry.spec_accepted,
        status=status,
        error=error if error is not None else entry.last_error,
        retries=entry.retries,
    )


class _Slot:
    def __init__(self, idx: int, entry: _QueueEntry, max_blocks: int,
                 admit_time: float, admit_seq: int):
        self.idx = idx
        self.entry = entry
        self.text = list(entry.req.prompt) + list(entry.carried)
        self.table = np.zeros((max_blocks,), np.int32)
        self.blocks: List[int] = []
        self.pos = 0                    # positions prefilled so far
        self.generated: List[int] = []  # this admission's new tokens
        self.last_token: int = 0
        self.prefilling = True
        self.admit_time = admit_time
        self.admit_seq = admit_seq
        #: table index of a borrowed PARTIAL prefix block (copy-on-write
        #: pending: resolved before this slot's first write into it).
        self.cow_idx: Optional[int] = None

    @property
    def total_generated(self) -> int:
        return len(self.entry.carried) + len(self.generated)


class _Clock:
    """Real seconds since construction, with idle gaps skippable."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._skew = 0.0

    def now(self) -> float:
        return time.perf_counter() - self._t0 + self._skew

    def skip_to(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            self._skew += delta


class Scheduler:
    """Admission queue + iteration-level scheduling over a
    :class:`~chainermn_tpu.serving.engine.DecodeEngine`."""

    def __init__(self, engine, registry=None, clock: Optional[_Clock] = None,
                 slo=None, timeline=None, memory=None, incidents=None,
                 fault=None, deadline_ms: Optional[float] = None,
                 ledger=None, policy=None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability import flight as _flight
        from chainermn_tpu.observability import tracing as _tracing
        from chainermn_tpu.observability.memory import MemoryMonitor
        from chainermn_tpu.observability.metrics import (
            DEFAULT_MS_EDGES,
            registry as global_registry,
        )
        from chainermn_tpu.observability.slo import SLOMonitor
        from chainermn_tpu.resilience import faults as _faults

        self.engine = engine
        self.clock = clock or _Clock()
        self._queue: List[_QueueEntry] = []
        self._slots: List[Optional[_Slot]] = [None] * engine.capacity
        self._admit_seq = 0
        self.completions: List[Completion] = []
        self._iterations = 0
        #: True while non-final prefill chunks dispatched since the last
        #: device readback may still be draining — the next decode step's
        #: wall time would absorb them (the ``serve.mixed_ms`` tag).
        self._unsynced_prefill = False
        #: fault-injection seam: an explicit injector wins (the chaos
        #: harness gives each replica its own seeded schedule); default
        #: is the process-wide ``CMN_FAULT`` injector.
        self._fault = (
            fault if fault is not None else _faults.process_injector()
        )
        #: fleet-wide default deadline (ms past arrival) for requests
        #: that carry none of their own; explicit arg wins over
        #: ``CMN_SERVE_DEADLINE_MS`` (None there too = no deadline).
        from chainermn_tpu.serving.recovery import deadline_ms_from_env

        self._default_deadline_ms = (
            deadline_ms if deadline_ms is not None
            else deadline_ms_from_env()
        )
        #: Multi-tenant policy plane (ISSUE 19): consulted at every
        #: admission / eviction / steal decision.  The router passes
        #: ONE fleet plane into every replica (revivals and scale-ups
        #: included) so the fair-share clocks and rate limits are
        #: fleet-coherent, exactly like the shared ledger.  None keeps
        #: the original FIFO behavior bit-for-bit.
        self.policy = policy
        if policy is not None and getattr(engine, "prefix", None) is not None:
            # The prefix trie enforces per-tenant block quotas at
            # insert time — hand it the plane's live quota view (one
            # dict, shared by reference across replicas).
            engine.prefix.quotas = policy.prefix_quotas
        enabled = _obs.enabled()
        # An explicitly passed registry always publishes; the ambient
        # global registry rides the CMN_OBS master switch like every
        # other publisher (latched here, same as resilience/guard.py).
        if registry is None and not enabled:
            noop = _NoopInstrument()
            self._m_queue = self._m_occ = self._m_tokens = noop
            self._m_prefill = self._m_decode = self._m_mixed = noop
            self._m_px_lookups = self._m_px_hit = self._m_px_rate = noop
            self._m_px_cached = self._m_px_cow = noop
            self._m_px_evicted = self._m_mig_install = noop
            self._m_spec_prop = self._m_spec_acc = noop
            self._m_spec_rate = self._m_deadline = noop
            reg = None
        else:
            reg = registry if registry is not None else global_registry()
            self._m_queue = reg.gauge("serve.queue_depth")
            self._m_occ = reg.gauge("serve.slot_occupancy")
            self._m_tokens = reg.counter("serve.tokens")
            self._m_prefill = reg.histogram(
                "serve.prefill_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_decode = reg.histogram(
                "serve.decode_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_mixed = reg.histogram(
                "serve.mixed_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_px_lookups = reg.counter("serve.prefix.lookups")
            self._m_px_hit = reg.counter("serve.prefix.hit_tokens")
            self._m_px_rate = reg.gauge("serve.prefix.hit_rate")
            self._m_px_cached = reg.gauge("serve.prefix.cached_blocks")
            self._m_px_cow = reg.counter("serve.prefix.cow_copies")
            self._m_px_evicted = reg.counter("serve.prefix.evicted_blocks")
            self._m_mig_install = reg.histogram(
                "serve.migration.install_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_spec_prop = reg.counter("serve.spec.proposed")
            self._m_spec_acc = reg.counter("serve.spec.accepted")
            self._m_spec_rate = reg.gauge("serve.spec.accept_rate")
            self._m_deadline = reg.counter(
                "serve.health.deadline_cancels"
            )
        #: lifetime host-side accounting (benchmarks read these directly;
        #: the gauges above mirror the derived rates).
        self.prefix_lookup_tokens = 0
        self.prefix_hit_tokens = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        #: Usage ledger (ISSUE 16): an explicit ledger always wins — the
        #: router passes ONE fleet ledger into every replica (revivals
        #: included) so a request migrated or harvested across replicas
        #: keeps one record — and ``ledger=False`` forces OFF (the
        #: router's obs-off/CMN_OBS_LEDGER=0 decision must not be
        #: overridden by a replica self-building against its private
        #: registry); otherwise cost attribution follows the scheduler's
        #: publishing decision, gated by ``CMN_OBS_LEDGER``.  Pure
        #: host-side dict arithmetic — never a device sync, so the
        #: one-compile contract and the obs overhead budget hold.
        from chainermn_tpu.observability import ledger as _oledger

        if ledger is False:
            self.ledger = None
        elif ledger is not None:
            self.ledger = ledger
        elif reg is not None and _oledger.ledger_enabled():
            self.ledger = _oledger.CostLedger(registry=reg)
        else:
            self.ledger = None
        #: SLO monitor: an explicit one always wins; otherwise it shares
        #: the scheduler's publishing decision (same registry, no-op
        #: when the master switch turned metrics off).
        self.slo = slo if slo is not None else (
            SLOMonitor(registry=reg) if reg is not None else None
        )
        #: Device-memory monitor (HBM watermarks + KV-pool occupancy /
        #: fragmentation timeline): explicit wins; else it shares the
        #: scheduler's publishing decision.  Sampled on the SLO check
        #: cadence — a handful of gauge sets off allocator counters,
        #: never a device sync.
        self.memory = memory if memory is not None else (
            MemoryMonitor(registry=reg) if reg is not None else None
        )
        self._mem_every = (
            self.slo.check_every if self.slo is not None else 16
        )
        #: Incident manager (ISSUE 12): explicit wins; otherwise the
        #: process manager rides the ambient-registry publishing
        #: decision (an explicit registry's gauges live where the
        #: process rules cannot see them, so no default there).  Rule
        #: evaluation runs on the SLO-check cadence + at drain — the
        #: already-paid moments; steady state never captures.
        if incidents is not None:
            self.incidents = incidents
        elif registry is None and enabled:
            from chainermn_tpu.observability import incident as _oincident

            self.incidents = _oincident.manager()
        else:
            self.incidents = None
        if self.incidents is not None:
            import weakref as _weakref

            _iref = _weakref.ref(self)
            self.incidents.register_source(
                "serving",
                lambda: (
                    s._flight_state() if (s := _iref()) is not None
                    else {"released": True}
                ),
            )
            # The newest SLO report rides every bundle (same weakref
            # discipline as the flight provider: a dropped scheduler —
            # and through it the engine's device pools — is never
            # pinned by the incident plane).
            self.incidents.register_source(
                "slo",
                lambda: (
                    {"report": s.slo.last_report}
                    if (s := _iref()) is not None and s.slo is not None
                    else {"released": True}
                ),
            )
            # Usage snapshot (ISSUE 16): a bundle names who was hogging
            # — per-tenant totals + top consumers — at fire time.
            if self.ledger is not None:
                self.incidents.register_source(
                    "usage",
                    lambda: (
                        s.ledger.usage_state()
                        if (s := _iref()) is not None
                        and s.ledger is not None
                        else {"released": True}
                    ),
                )
        #: Device-plane roofline gauges (PR 11): on the same cadence as
        #: the memory sample, publish achieved TFLOP/s / MFU / arithmetic
        #: intensity for the engine's HOT program (decode step or
        #: speculative round) from its captured cost model and the mean
        #: CLEAN decode iteration time since the last publish.  Shares
        #: the scheduler's publishing latch; ``CMN_OBS_DEVICE=0`` turns
        #: just this feed off (the one-time cost capture lowers the
        #: program once more — steady state is untouched).
        import os as _os

        self._dev_enabled = (
            reg is not None
            and _os.environ.get("CMN_OBS_DEVICE", "1") != "0"
        )
        self._dev_reg = reg
        self._dev_ms_sum = 0.0
        self._dev_ms_n = 0
        #: Request-lifecycle timeline: explicit wins; else ride the
        #: master switch, mirroring events into the process span ring
        #: (flight records then show recent serving activity).
        if timeline is not None:
            self.timeline = timeline
        elif enabled:
            self.timeline = _tracing.RequestTimeline(
                ring=_tracing.tracer().ring
            )
        else:
            self.timeline = None
        # Flight-record provider — ungated by CMN_OBS, like the recorder
        # itself (it answers only to CMN_OBS_FLIGHT*).  Keyed, so the
        # newest scheduler replaces a finished one's state; held via
        # weakref so the provider registry never pins a dropped
        # scheduler (and through it the engine's device KV pools).
        import weakref

        ref = weakref.ref(self)
        _flight.register_provider(
            "serving",
            lambda: (
                s._flight_state() if (s := ref()) is not None
                else {"released": True}
            ),
        )
        # Arm the env-configured recorder (same as Trainer.__init__): a
        # pure serving process would otherwise never install the SIGUSR1
        # live-snapshot handler — the signal's default action KILLS the
        # engine instead of snapshotting it.  No-op when
        # CMN_OBS_FLIGHT_DIR is unset.
        _flight.recorder()

    # ---------------------------------------------------------- admission
    def submit(self, req: Request) -> None:
        """Enqueue; raises :class:`PoolExhausted` if the request could
        never fit the pool/slot geometry even running alone."""
        self.check_fit(req)
        self._queue.append(_QueueEntry(req))
        if self.ledger is not None:
            self.ledger.begin(req, self.clock.now())
        if self.timeline is not None:
            # Stamped at the request's logical availability (its arrival
            # on the scheduler clock) — the same origin the queue-wait
            # metric uses, so the queue slice and the histogram agree.
            self.timeline.record(
                "submit", t=float(req.arrival), req=req.id,
                info={"prompt_len": len(req.prompt),
                      "max_new": req.max_new_tokens},
            )

    def check_fit(self, req: Request) -> None:
        """The submit-time geometry gate, callable without enqueueing
        (the router validates against one replica before dispatch —
        replicas are assumed geometry-homogeneous)."""
        plen = len(req.prompt)
        if plen < 1:
            raise ValueError(f"request {req.id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(f"request {req.id}: max_new_tokens < 1")
        eng = self.engine
        cap = eng.max_blocks * eng.block_len
        total = plen + req.max_new_tokens
        # A speculative round can probe/write up to spec_k positions past
        # the final generated token (the verify chunk), so the slot's
        # geometry must cover that headroom too.
        probe_end = total + eng.spec_k
        # Worst-case prefill END over every possible (re-)admission: a
        # slot prefills prompt + carried tokens (carried grows to
        # max_new - 1 under eviction/recompute), full-size chunks while
        # more than prefill_chunk remains, then the smallest ladder size
        # covering the tail.  The padded tail must stay inside the block
        # table (pad writes past it would clamp onto real blocks) and,
        # for learned-pos models, inside the position table (the
        # dynamic_slice would clamp and embed real tokens at wrong
        # positions).  Rounding total up to a full prefill_chunk
        # overstates this (the ladder tail is tighter) and would reject
        # servable requests whenever the cap is not a chunk multiple.
        # (Prefix-cache hits can move the prefill start mid-chunk and
        # change the padded end; admission caps the MATCH to fit —
        # ``_cap_match`` — so the m=0 bound checked here is the one that
        # must hold.)
        worst_end = self._worst_prefill_end(plen, total - 1)
        if max(probe_end, worst_end) > cap:
            raise PoolExhausted(
                f"request {req.id}: {plen}+{req.max_new_tokens} tokens "
                f"(worst padded prefill end {worst_end}, speculative "
                f"probe end {probe_end}) exceeds the per-slot cap {cap} "
                f"(max_blocks={eng.max_blocks} x "
                f"block_len={eng.block_len})"
            )
        if blocks_for(probe_end, eng.block_len) > eng.pool.num_blocks - 1:
            raise PoolExhausted(
                f"request {req.id}: needs "
                f"{blocks_for(probe_end, eng.block_len)} blocks, pool has "
                f"{eng.pool.num_blocks - 1} allocatable"
            )
        if eng.model.pos_enc == "learned" and \
                max(probe_end, worst_end) > eng.model.max_len:
            raise ValueError(
                f"request {req.id}: worst padded prefill end {worst_end} "
                f"(speculative probe end {probe_end}) exceeds the learned "
                f"position table ({eng.model.max_len}); use a rope model "
                "or shorter requests"
            )

    # --------------------------------------------- router integration
    def submit_entry(self, entry: _QueueEntry) -> None:
        """Re-enqueue an entry migrated from a peer replica (router
        rebalance): carried tokens, eviction counts and prefix/spec
        accounting ride along, so the destination engine recomputes the
        carried text through its own prefill/prefix-cache and the
        request continues exactly where it left off.  Geometry was
        validated at the original :meth:`submit` (homogeneous
        replicas)."""
        self._queue.append(entry)
        if self.ledger is not None:
            # Idempotent by id: on the fleet-shared ledger the record
            # already exists; a role-split destination with its own
            # ledger opens one here (tenant rides the codec).
            self.ledger.begin(entry.req, self.clock.now())
        if self.timeline is not None:
            self.timeline.record(
                "submit", t=self.clock.now(), req=entry.req.id,
                info={"migrated": True,
                      "carried": len(entry.carried)},
            )

    def steal_queued(self) -> Optional[_QueueEntry]:
        """Pop the YOUNGEST queued entry whose arrival has passed, for
        migration to a less-loaded replica (router work rebalance).
        Returns ``None`` when nothing stealable is queued.  The
        youngest is the right victim for the same reason eviction picks
        it: the head of the queue is the oldest waiter (possibly an
        evicted re-admission carrying generated tokens) and keeps its
        position.

        Under a policy plane the victim is instead the weighted-fair
        admission HEAD — the entry this scheduler would serve next.  The
        steal's destination is an idle replica, so moving the fair head
        only accelerates the fair schedule; stealing the youngest
        regardless of tenant would let an adversarial tenant's backlog
        ride a rebalance ahead of an SLO tenant's queue (ISSUE 19)."""
        if not self._queue:
            return None
        if self.policy is not None:
            idx = self.policy.steal_index(
                [e.req for e in self._queue], self.clock.now()
            )
            if idx is None:
                return None
            entry = self._queue.pop(idx)
            if self.timeline is not None:
                self.timeline.record(
                    "steal", t=self.clock.now(), req=entry.req.id,
                )
            return entry
        entry = self._queue[-1]
        if entry.req.arrival > self.clock.now():
            return None
        self._queue.pop()
        if self.timeline is not None:
            self.timeline.record(
                "steal", t=self.clock.now(), req=entry.req.id,
            )
        return entry

    def ready_slots(self) -> List["_Slot"]:
        """Live DECODE-READY slots (prefill finished) — the set a
        cmn-kvmig-1 pack may ship with live KV (``disagg.pack_slots``
        raises on a still-prefilling slot).  The drain/scale-down
        handoff (ISSUE 17) moves these; still-prefilling slots and the
        queue travel as recompute entries via :meth:`harvest_entries`
        instead."""
        return [
            s for s in self._slots if s is not None and not s.prefilling
        ]

    def harvest_entries(self) -> List[_QueueEntry]:
        """Strip EVERYTHING this replica holds — live slots and queued
        entries — into recompute ``_QueueEntry`` s, for the router's
        fault boundary after this replica's tick escaped (ISSUE 15).

        Live slots fold their generated tokens into ``carried`` exactly
        like an eviction (recompute-requeue: the re-admission prefills
        ``prompt + carried`` on a survivor and the continuation is
        greedy-identical), ordered oldest admission first so the
        longest-served work re-dispatches ahead.  Block releases are
        host-side allocator bookkeeping only (the dead engine's device
        state is garbage anyway) and best-effort — a corrupted
        allocator must not lose the harvest."""
        out: List[_QueueEntry] = []
        now = self.clock.now()
        for slot in sorted(
            (s for s in self._slots if s is not None),
            key=lambda s: s.admit_seq,
        ):
            try:
                self.engine.release_blocks(slot.blocks)
            except Exception:
                pass
            slot.entry.carried = (
                list(slot.entry.carried) + list(slot.generated)
            )
            slot.entry.evictions += 1
            if self.ledger is not None:
                # The dead engine's blocks are garbage, but their
                # occupancy UNTIL NOW was real — settle the integral,
                # book the recompute-requeue.
                self.ledger.set_blocks(slot.entry.req.id, 0, now)
                self.ledger.book(slot.entry.req.id, "evictions", 1)
            if self.policy is not None:
                self.policy.set_blocks(
                    slot.entry.req.id, slot.entry.req.tenant, 0, now
                )
            self._slots[slot.idx] = None
            out.append(slot.entry)
            if self.timeline is not None:
                self.timeline.record(
                    "evict", t=now, req=slot.entry.req.id,
                    slot=slot.idx,
                    info={"harvested": True,
                          "carried": len(slot.entry.carried)},
                )
        out.extend(self._queue)
        self._queue = []
        return out

    def complete_terminal(self, entry: _QueueEntry, status: str,
                          error: Optional[str] = None) -> Completion:
        """Terminate ``entry`` WITHOUT serving it (poisoned / shed /
        deadline): one definite Completion carrying whatever tokens were
        generated before the terminal verdict.  The entry must already
        be off the queue and out of any slot."""
        now = self.clock.now()
        comp = terminal_completion(entry, status, now, error=error)
        if self.ledger is not None:
            comp.usage = self.ledger.finalize(entry.req.id, status, now)
        self.completions.append(comp)
        if self.timeline is not None:
            self.timeline.record(
                "retire", t=now, req=entry.req.id,
                info={"reason": status},
            )
        return comp

    # ----------------------------------------------------------- deadline
    def _deadline_s(self, req: Request) -> Optional[float]:
        # Specificity order: the request's own deadline, then its
        # tenant's policy default (ISSUE 19), then the fleet default.
        dl = req.deadline_ms
        if dl is None and self.policy is not None:
            dl = self.policy.deadline_ms(req.tenant)
        if dl is None:
            dl = self._default_deadline_ms
        return dl / 1e3 if dl is not None and dl > 0 else None

    def _cancel_deadlines(self) -> bool:
        """Cancel every over-deadline request — live slots (blocks
        freed, the graceful-degradation half of ISSUE 15) and queued
        entries (they would only get staler waiting).  Terminal:
        ``status="deadline"``, counted by
        ``serve.health.deadline_cancels``."""
        now = self.clock.now()
        progressed = False
        for slot in [s for s in self._slots if s is not None]:
            dl = self._deadline_s(slot.entry.req)
            if dl is None or now - slot.entry.req.arrival <= dl:
                continue
            self.engine.release_blocks(slot.blocks)
            if self.policy is not None:
                self.policy.set_blocks(
                    slot.entry.req.id, slot.entry.req.tenant, 0, now
                )
            self._slots[slot.idx] = None
            slot.entry.carried = (
                list(slot.entry.carried) + list(slot.generated)
            )
            self.complete_terminal(slot.entry, "deadline")
            self._m_deadline.inc()
            progressed = True
        kept = []
        for entry in self._queue:
            dl = self._deadline_s(entry.req)
            if dl is not None and now - entry.req.arrival > dl:
                self.complete_terminal(entry, "deadline")
                self._m_deadline.inc()
                progressed = True
            else:
                kept.append(entry)
        if len(kept) != len(self._queue):
            self._queue = kept
        return progressed

    @property
    def pending(self) -> bool:
        """Work outstanding: anything queued or resident in a slot."""
        return bool(
            self._queue or any(s is not None for s in self._slots)
        )

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def slot_occupancy(self) -> float:
        """Live slots / capacity — the host-side truth behind the
        ``serve.slot_occupancy`` gauge (the router's cold-start
        fallback before a replica's first tick publishes)."""
        return (
            sum(s is not None for s in self._slots)
            / self.engine.capacity
        )

    @property
    def has_free_slot(self) -> bool:
        return any(s is None for s in self._slots)

    def next_arrival(self) -> Optional[float]:
        """The next time an admission can unblock, or None on an empty
        queue.  FIFO: the head entry's arrival (the head is the only
        entry whose arrival can unblock anything).  Under a policy
        plane any queued entry is pickable, so the bound is the min
        future arrival — and when every ARRIVED tenant is
        rate-throttled, the earliest throttle release (otherwise an
        idle-skip loop would jump to an already-past arrival and
        spin)."""
        if not self._queue:
            return None
        if self.policy is None:
            return self._queue[0].req.arrival
        now = self.clock.now()
        cands = [
            e.req.arrival for e in self._queue if e.req.arrival > now
        ]
        rel = self.policy.next_release(
            [e.req for e in self._queue], now
        )
        if rel is not None:
            cands.append(rel)
        if not cands:
            # Everything has arrived and nobody is throttled — the old
            # contract (an already-past time: no skip, admission is
            # gated on slots, not the clock).
            return min(e.req.arrival for e in self._queue)
        return min(cands)

    def _worst_prefill_end(self, lo: int, hi: int) -> int:
        """Max padded prefill end over admission text lengths in
        ``[lo, hi]`` (prompt alone up to prompt + max_new - 1 carried).

        For text length ``t``: full chunks cover ``t - t % C`` positions
        (``C = prefill_chunk``), the tail pays the smallest ladder size
        covering ``t % C``.  The end is residue-monotone in ``t``, so
        scanning the top ``C`` lengths covers every residue's maximum —
        O(prefill_chunk) per submit, host-side only.
        """
        C = self.engine.prefill_ladder[-1]
        return max(
            self._padded_end(0, t)
            for t in range(max(lo, hi - C + 1), hi + 1)
        )

    def _try_admit(self) -> bool:
        if not self._queue:
            return False
        now = self.clock.now()
        if self.policy is None:
            entry = self._queue[0]
            if entry.req.arrival > now:
                return False
        else:
            # Weighted-fair pick (ISSUE 19): the first-queued entry of
            # the arrived, un-throttled tenant with the smallest
            # virtual service clock.  None = nothing arrived, or every
            # arrived tenant is rate-throttled this instant.
            qidx = self.policy.pick_index(
                [e.req for e in self._queue], now
            )
            if qidx is None:
                return False
            entry = self._queue[qidx]
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            if self.policy is None:
                return False
            # Priority preemption: a strictly higher class may evict
            # the lowest-class (youngest among equals) running slot
            # through the recompute-requeue path.  The victim re-queues
            # at the GLOBAL head, which is its tenant's head too — it
            # was admitted before anything still queued from its tenant
            # (per-tenant FIFO) — and `retries` is untouched (that
            # counter means replica deaths, not scheduling decisions).
            victim = self.policy.preempt_pick(
                [s for s in self._slots if s is not None],
                self.policy.effective_priority(entry.req),
            )
            if victim is None:
                return False
            self._evict_slot(victim, preempted=True)
            self.policy.note_preemption(victim.entry.req.tenant)
            free = [i for i, s in enumerate(self._slots) if s is None]
        eng = self.engine
        BL = eng.block_len
        text = list(entry.req.prompt) + list(entry.carried)
        # Match BEFORE the allocator gate: a hot fully-cached prompt
        # borrows nearly all its blocks from the trie, so gating on the
        # unmatched requirement would refuse exactly the admissions
        # sharing makes nearly free.  (The match only touches LRU
        # stamps; references are shared below, after admission commits.)
        matched, blocks, first = self._admission_plan(text)
        if not eng.pool.allocator.can_alloc(first):
            # The free list may be empty only because the prefix trie is
            # hoarding retired blocks — reuse potential never blocks a
            # live admission.
            if eng.prefix is None:
                return False
            need = first - eng.pool.allocator.free_blocks
            self._m_px_evicted.inc(eng.prefix.evict(need))
            # The eviction may have released blocks the match above
            # returned (they were only trie-held) — re-plan against the
            # surviving trie before trusting any block id.
            matched, blocks, first = self._admission_plan(text)
            if not eng.pool.allocator.can_alloc(first):
                return False
        # Remove by identity: a preemption above re-queued its victim
        # at index 0, so the picked entry's index may have shifted.
        self._queue.remove(entry)
        if self.policy is not None:
            self.policy.note_admission(entry.req)
        if entry.first_admit is None:
            entry.first_admit = now
            wait_ms = (now - entry.req.arrival) * 1e3
            if self.slo is not None:
                self.slo.observe("queue_wait", wait_ms)
            if self.policy is not None:
                self.policy.note_queue_wait(entry.req.tenant, wait_ms)
            if self.ledger is not None:
                # First admission FLEET-WIDE: first_admit rides the
                # migration codec, so re-admissions (eviction, harvest,
                # disagg install) never re-book queue wait.
                self.ledger.admitted(entry.req.id, now)
        slot = _Slot(free[0], entry, eng.max_blocks, now,
                     self._admit_seq)
        self._admit_seq += 1
        self._slots[free[0]] = slot
        # Prefix-cache hit: map the matched blocks (borrowed references,
        # never copies) and resume prefill at the first unmatched token.
        # The match was capped at len(text) - 1 — the final prefill chunk
        # must keep at least one real token, whose logits sample the
        # first output — and then shortened until the remainder's padded
        # prefill end fits the slot/table geometry (the submit() bound
        # only covered the unmatched start).
        if eng.prefix is not None:
            if matched:
                eng.pool.allocator.share(blocks)
                for i, b in enumerate(blocks):
                    slot.table[i] = b
                slot.blocks = list(blocks)
                slot.pos = matched
                if matched % BL:
                    # The last mapped block is partially ours: this
                    # slot's first write into it copy-on-writes first.
                    slot.cow_idx = matched // BL
                entry.prefix_hit_tokens += matched
                self._m_px_hit.inc(matched)
                if self.ledger is not None:
                    # Credit/charge split: the SAVED tokens credit the
                    # hitting request; the mapped blocks' pool pressure
                    # charges it too (set_blocks below counts borrowed
                    # references — the pinner pays for occupancy).
                    self.ledger.book(
                        entry.req.id, "prefix_hit_tokens", matched
                    )
            self._m_px_lookups.inc()
            self.prefix_lookup_tokens += len(text)
            self.prefix_hit_tokens += matched
            self._m_px_rate.set(
                self.prefix_hit_tokens
                / max(self.prefix_lookup_tokens, 1)
            )
            self._m_px_cached.set(eng.prefix.cached_blocks)
        if self.ledger is not None:
            # Occupancy integration starts at admission — shared prefix
            # blocks included (each referencing slot pays full freight;
            # sharing saves COMPUTE, the pool pressure is real).
            self.ledger.set_blocks(
                entry.req.id, len(slot.blocks), now
            )
        if self.policy is not None:
            self.policy.set_blocks(
                entry.req.id, entry.req.tenant, len(slot.blocks), now
            )
        self.engine.seed_slot(free[0], entry.req.seed,
                              entry.req.temperature)
        if self.timeline is not None:
            info = {}
            if entry.evictions:
                info["readmit"] = True
            if matched:
                info["prefix_tokens"] = matched
            self.timeline.record(
                "admit", t=now, req=entry.req.id, slot=free[0],
                info=info or None,
            )
        return True

    def _ladder_size(self, remaining: int) -> int:
        """The prefill chunk geometry for ``remaining`` tokens — THE one
        definition of the ladder policy (full-size chunks while more
        than ``prefill_chunk`` remains, then the smallest ladder size
        covering the tail).  `_prefill_chunk` (runtime), `_padded_end`
        (the admission/submit safety bound), and `_admission_plan` (the
        gate's fresh-block estimate) must all read the policy from here
        or the bound silently desynchronizes from the real chunks."""
        ladder = self.engine.prefill_ladder
        if remaining >= ladder[-1]:
            return ladder[-1]
        return next(c for c in ladder if c >= remaining)

    def _padded_end(self, start: int, text_len: int) -> int:
        """Padded prefill end for a prefill that starts at ``start``."""
        remaining = text_len - start
        if remaining <= 0:
            return start
        r = remaining % self.engine.prefill_ladder[-1]
        if r == 0:
            return text_len
        return text_len - r + self._ladder_size(r)

    def _admission_plan(self, text):
        """Admission sizing for ``text`` against the current trie state:
        ``(matched, blocks, first_fresh)`` — the capped prefix match,
        its table blocks, and the FRESH blocks the first prefill chunk
        needs net of the mapped prefix (+1 for the COW copy of a
        partial block)."""
        eng = self.engine
        BL = eng.block_len
        matched, blocks, n_tbl = 0, [], 0
        if eng.prefix is not None:
            blocks, matched = eng.prefix.match(text, limit=len(text) - 1)
            matched = self._cap_match(matched, len(text))
            n_tbl = (matched + BL - 1) // BL
            blocks = blocks[:n_tbl]
        end1 = min(
            matched + self._ladder_size(len(text) - matched), len(text)
        )
        first = max(
            blocks_for(end1, BL) - n_tbl + (1 if matched % BL else 0), 0
        )
        return matched, blocks, first

    def _cap_match(self, matched: int, text_len: int) -> int:
        """Largest usable prefix match <= ``matched``: the remainder's
        padded prefill end must stay inside the block table (pad writes
        past it would clamp onto real blocks) and, for learned-pos
        models, the position table.  ``matched == 0`` always qualifies —
        submit() validated the unmatched geometry."""
        eng = self.engine
        cap = eng.max_blocks * eng.block_len
        if eng.model.pos_enc == "learned":
            cap = min(cap, eng.model.max_len)
        while matched > 0 and self._padded_end(matched, text_len) > cap:
            matched -= 1
        return matched

    # ----------------------------------------------------------- eviction
    def _evict_slot(self, victim: _Slot, preempted: bool = False) -> None:
        """Evict ``victim`` through the recompute-requeue path — THE one
        eviction discipline, shared by pool-pressure eviction and
        priority preemption (ISSUE 19): generated tokens fold into
        ``carried``, the entry re-queues at the head (its tenant's head
        too — it predates everything still queued from its tenant), and
        the re-admission re-matches its own just-cached prefix, so the
        continuation is greedy-identical and nearly free."""
        self.engine.release_blocks(victim.blocks)
        victim.entry.carried = (
            list(victim.entry.carried) + list(victim.generated)
        )
        victim.entry.evictions += 1
        self._queue.insert(0, victim.entry)
        self._slots[victim.idx] = None
        now = self.clock.now()
        if self.ledger is not None:
            # Settle the occupancy integral at release; the re-admission
            # restarts it (recompute cost books as fresh prefill tokens).
            self.ledger.set_blocks(victim.entry.req.id, 0, now)
            self.ledger.book(victim.entry.req.id, "evictions", 1)
        if self.policy is not None:
            self.policy.set_blocks(
                victim.entry.req.id, victim.entry.req.tenant, 0, now
            )
        if self.timeline is not None:
            info = {"carried": len(victim.entry.carried)}
            if preempted:
                info["preempted"] = True
            self.timeline.record(
                "evict", t=now, req=victim.entry.req.id,
                slot=victim.idx, info=info,
            )

    def _evict_youngest(self) -> bool:
        live = [s for s in self._slots if s is not None]
        if not live:
            return False
        self._evict_slot(max(live, key=lambda s: s.admit_seq))
        return True

    def _alloc_blocks(self, slot: _Slot, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks for ``slot`` under pool pressure: drain the
        prefix cache first (LRU leaves nobody else holds), then evict the
        youngest slot — possibly ``slot`` itself, in which case the
        allocation is moot and ``None`` is returned."""
        eng = self.engine
        while True:
            if self._slots[slot.idx] is not slot:
                # Already evicted — e.g. a co-slot's allocation earlier in
                # the same step chose it as the youngest victim.  Growing
                # it now would orphan the new blocks (the re-admission
                # builds a fresh slot), i.e. leak pool memory.
                return None
            got = eng.alloc_blocks(n)
            if got is not None:
                return got
            # Cached-only prefix blocks are reuse POTENTIAL — release
            # them before taking work away from a live request.
            if eng.prefix is not None:
                need = n - eng.pool.allocator.free_blocks
                released = eng.prefix.evict(need)
                if released:
                    self._m_px_evicted.inc(released)
                    continue
            # Evict the youngest slot (possibly `slot` itself) and retry.
            live = [s for s in self._slots if s is not None]
            if len(live) == 1 and live[0] is slot:
                raise PoolExhausted(
                    f"request {slot.entry.req.id} cannot fit the pool "
                    "even running alone — grow num_blocks"
                )
            self._evict_youngest()

    def _alloc_for(self, slot: _Slot, n_needed: int) -> None:
        """Grow ``slot`` to ``n_needed`` blocks, evicting under pressure."""
        grew = False
        while len(slot.blocks) < n_needed:
            got = self._alloc_blocks(slot, n_needed - len(slot.blocks))
            if got is None:
                return  # the needy slot evicted itself; re-queued
            for b in got:
                slot.table[len(slot.blocks)] = b
                slot.blocks.append(b)
            grew = True
        if grew:
            if self.ledger is not None:
                # New occupancy level from here on (piecewise-constant
                # integration: the old level was settled up to now).
                self.ledger.set_blocks(
                    slot.entry.req.id, len(slot.blocks), self.clock.now()
                )
            if self.policy is not None:
                self.policy.set_blocks(
                    slot.entry.req.id, slot.entry.req.tenant,
                    len(slot.blocks), self.clock.now(),
                )

    def _resolve_cow(self, slot: _Slot) -> None:
        """Copy-on-write the slot's borrowed PARTIAL prefix block before
        its first write into it: fresh block, one jitted whole-block
        copy (target + draft pools), borrowed reference dropped.  The
        cached original is never mutated."""
        if slot.cow_idx is None:
            return
        got = self._alloc_blocks(slot, 1)
        if got is None:
            return  # evicted itself under pressure; moot
        idx = slot.cow_idx
        src = slot.blocks[idx]
        self.engine.cow_copy(src, got[0])
        slot.table[idx] = got[0]
        slot.blocks[idx] = got[0]
        self.engine.release_blocks([src])
        slot.cow_idx = None
        self._m_px_cow.inc()
        if self.ledger is not None:
            self.ledger.book(slot.entry.req.id, "cow_copies", 1)

    # ------------------------------------------------------------ prefill
    def _prefill_round(self) -> bool:
        """One chunk for EVERY currently-prefilling slot (oldest first).

        One chunk per slot per iteration keeps the interleave bound — a
        long prompt still cannot stall running decodes for its whole
        length — while refilled slots rejoin the decode step as fast as
        the chunking allows.  Prefilling only one slot per iteration
        would serialize re-admissions: after a near-simultaneous batch of
        retirements (common when similar-length requests were admitted
        together), the decode step would run under-occupied for several
        extra iterations.
        """
        progressed = False
        # Drift-driven chunked-prefill budget (ISSUE 19, Sarathi-style):
        # while the policy's SLO latch is engaged, cap the prefill
        # tokens started per iteration.  The FIRST candidate always
        # runs (prefill can never wedge — progress is guaranteed even
        # with a cap below one chunk), and the cap is chunk-granular:
        # the final chunk that crosses it completes.
        budget = (
            self.policy.prefill_budget() if self.policy is not None
            else None
        )
        spent, first = 0, True
        for slot in sorted(
            (s for s in self._slots if s is not None and s.prefilling),
            key=lambda s: s.admit_seq,
        ):
            if self._slots[slot.idx] is not slot:
                continue  # evicted by an earlier candidate's allocation
            if budget is not None and not first and spent >= budget:
                self.policy.note_prefill_capped()
                break
            p_before = slot.pos
            progressed = self._prefill_chunk(slot) or progressed
            # The slot object survives retirement/eviction, and an
            # eviction-under-pressure bails before advancing pos — the
            # delta is exactly the tokens this chunk computed.
            spent += max(0, slot.pos - p_before)
            first = False
        return progressed

    def _prefill_chunk(self, slot: _Slot) -> bool:
        eng = self.engine
        p0 = slot.pos
        # Ladder policy (one definition: _ladder_size): full-size chunks
        # while more than prefill_chunk tokens remain, then the smallest
        # ladder geometry covering the tail — one final call with
        # minimal padded compute instead of a full prefill_chunk of
        # mostly-pad forward.
        size = self._ladder_size(len(slot.text) - p0)
        end = min(p0 + size, len(slot.text))
        self._alloc_for(slot, blocks_for(end, eng.block_len))
        if self._slots[slot.idx] is not slot:
            return True  # evicted itself under pressure; progress made
        # First write into a borrowed partial prefix block → COW now.
        self._resolve_cow(slot)
        if self._slots[slot.idx] is not slot:
            return True
        chunk = np.zeros((size,), np.int32)
        chunk[: end - p0] = slot.text[p0:end]
        last = end == len(slot.text)
        tc = self.clock.now()
        t0 = time.perf_counter()
        tok = eng.prefill(
            slot.idx, chunk, p0, slot.table,
            last_idx=(end - p0 - 1) if last else -1,
        )
        dur_ms = (time.perf_counter() - t0) * 1e3
        self._m_prefill.observe(dur_ms)
        if self.ledger is not None:
            # Tokens actually COMPUTED this chunk (pad positions are
            # geometry, not work anyone is billed for).  Eviction-
            # recompute naturally re-books here — recompute is real cost.
            self.ledger.book(
                slot.entry.req.id, "prefill_tokens", end - p0
            )
        if self.policy is not None:
            # The fair-share clock charges the SAME computed-token count
            # the ledger books — net of prefix hits by construction
            # (p0 starts past the matched prefix).
            self.policy.charge(
                slot.entry.req.tenant, "prefill_tokens", end - p0
            )
        # A final chunk's first-token readback drains every dispatch
        # queued before it; a non-final chunk is dispatch-only and its
        # compute drains into the NEXT synced op (the mixed-iteration
        # tag the decode step reads).
        self._unsynced_prefill = not last
        if self.timeline is not None:
            self.timeline.record(
                "prefill", t=tc, req=slot.entry.req.id, slot=slot.idx,
                dur_ms=dur_ms,
                info={"p0": p0, "end": end, "final": last},
            )
        slot.pos = end
        if last:
            slot.prefilling = False
            # The full text is now in cache — register its full blocks
            # with the prefix trie so concurrent and future requests map
            # instead of recompute (dedupes against existing chains).
            if eng.prefix is not None:
                eng.prefix.insert(
                    slot.text,
                    slot.blocks[: len(slot.text) // eng.block_len],
                    owner=slot.entry.req.tenant,
                )
                self._m_px_cached.set(eng.prefix.cached_blocks)
            first_token_ever = not slot.entry.carried
            self._emit(slot, int(tok))
            if first_token_ever and self.slo is not None:
                self.slo.observe(
                    "ttft",
                    (self.clock.now() - slot.entry.req.arrival) * 1e3,
                )
        return True

    # ------------------------------------------------------------- decode
    def _decode_step(self) -> bool:
        live = [
            s for s in self._slots if s is not None and not s.prefilling
        ]
        if not live:
            return False
        S = self.engine.capacity
        k = self.engine.spec_k
        tokens = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        tables = np.zeros((S, self.engine.max_blocks), np.int32)
        active = np.zeros((S,), bool)
        for s in live:
            # The step writes position `pos` (a speculative round writes
            # through `pos + spec_k`) — make sure those blocks exist.
            self._alloc_for(
                s, blocks_for(s.pos + 1 + k, self.engine.block_len)
            )
        live = [
            s for s in self._slots if s is not None and not s.prefilling
        ]
        if not live:
            return True  # everything evicted itself; still progress
        for s in live:
            tokens[s.idx] = s.last_token
            pos[s.idx] = s.pos
            tables[s.idx] = s.table
            active[s.idx] = True
        mixed = self._unsynced_prefill
        self._iterations += 1
        tc = self.clock.now()
        t0 = time.perf_counter()
        if self._fault is not None:
            # ``skew@serve_step:N:ms`` — inside the timed window, so an
            # injected stretch lands in this iteration's histogram
            # exactly like a real slowdown would.
            self._fault.hook("serve_step", count=self._iterations)
        if k:
            out, n_accept = self.engine.spec_step(
                tokens, pos, tables, active
            )
        else:
            out = self.engine.step(tokens, pos, tables, active)
        dur_ms = (time.perf_counter() - t0) * 1e3
        # The token readback above drained the dispatch queue: any
        # prefill work queued before this step has now been absorbed
        # into dur_ms — book the contaminated iteration separately so
        # serve.decode_ms (and the SLO token stream) stay clean.
        self._unsynced_prefill = False
        if mixed:
            self._m_mixed.observe(dur_ms)
        else:
            self._m_decode.observe(dur_ms)
            if self.slo is not None:
                self.slo.observe("token", dur_ms)
            if self._dev_enabled:
                self._dev_ms_sum += dur_ms
                self._dev_ms_n += 1
        if self.timeline is not None:
            self.timeline.record(
                "decode", t=tc, dur_ms=dur_ms,
                info={"reqs": [(s.idx, s.entry.req.id) for s in live],
                      "mixed": mixed},
            )
        if self.slo is not None and \
                self._iterations % self.slo.check_every == 0:
            self.slo.check()
            if self.policy is not None:
                # Feed the fresh verdict into the drift latch on the
                # check cadence — hysteresis counts CHECKS, not
                # iterations, mirroring the autoscaler's streaks.
                self.policy.on_slo_check(self.slo.last_report)
        if self.incidents is not None and \
                self._iterations % self._mem_every == 0:
            # Watch-rule evaluation on the SLO-check cadence, AFTER the
            # check refreshed the drift gauge: a breach captures its
            # bundle while the registry still shows the breach.
            self.incidents.evaluate()
        if self.memory is not None and \
                self._iterations % self._mem_every == 0:
            self.memory.sample(kv=self._kv_sample())
        if self._dev_enabled and \
                self._iterations % self._mem_every == 0:
            # capture=False: live requests are between decode steps
            # right here — the one-time cost capture is a synchronous
            # backend compile and belongs at drain, never mid-traffic.
            self._publish_device(capture=False)
        for s in live:
            if self.ledger is not None:
                # Booked AFTER the step completed: a replica crash at
                # serve_step raised before reaching here, so a harvested
                # request is never billed for an iteration that produced
                # nothing (the harvest books the eviction instead).
                self.ledger.book(
                    s.entry.req.id, "decode_iterations", 1
                )
            if self.policy is not None:
                self.policy.charge(
                    s.entry.req.tenant, "decode_iterations", 1
                )
            if k:
                # One speculative round: emit the accepted drafts plus
                # the target's correction/bonus, token by token — EOS or
                # the budget can retire the slot mid-round, and the
                # over-accepted tail is simply dropped (its K/V is
                # causally masked and rewritten by later steps: rollback
                # is the position not advancing, nothing is copied).
                na = int(n_accept[s.idx])
                emitted = 0
                for j in range(na + 1):
                    s.pos += 1
                    self._emit(s, int(out[s.idx, j]))
                    emitted += 1
                    if self._slots[s.idx] is not s:
                        break  # retired mid-round (EOS / budget)
                if s.entry.req.temperature <= 0:
                    # Acceptance capped at what was EMITTED: a mid-run
                    # retirement leaves the tail drafts unused — neither
                    # accepted nor rejected — while a full emission
                    # (correction/bonus included) adjudicated all k.
                    acc = min(emitted, na)
                    prop = acc if emitted <= na else k
                    entry = s.entry
                    entry.spec_proposed += prop
                    entry.spec_accepted += acc
                    self.spec_proposed += prop
                    self.spec_accepted += acc
                    self._m_spec_prop.inc(prop)
                    self._m_spec_acc.inc(acc)
                    if self.ledger is not None:
                        self.ledger.book(
                            entry.req.id, "spec_proposed", prop
                        )
                        self.ledger.book(
                            entry.req.id, "spec_accepted", acc
                        )
                    self._m_spec_rate.set(
                        self.spec_accepted / max(self.spec_proposed, 1)
                    )
            else:
                s.pos += 1
                self._emit(s, int(out[s.idx]))
        return True

    def _emit(self, slot: _Slot, tok: int) -> None:
        """Account one generated token; retire the slot when done."""
        self._m_tokens.inc()
        slot.generated.append(tok)
        slot.last_token = tok
        req = slot.entry.req
        if self.ledger is not None:
            self.ledger.book(req.id, "tokens", 1)
        reason = None
        if req.eos_token is not None and tok == req.eos_token:
            reason = "eos"
        elif slot.total_generated >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        eng = self.engine
        if eng.prefix is not None:
            # Multi-turn reuse: cache the full blocks of prompt +
            # generated history (positions [0, pos) are written — the
            # last emitted token's K/V never is, and a speculative
            # round's rejected tail lies past pos).  The next turn's
            # prompt embeds this text verbatim and maps it.
            seq = slot.text + slot.generated
            eng.prefix.insert(
                seq[: slot.pos],
                slot.blocks[: slot.pos // eng.block_len],
                owner=req.tenant,
            )
            self._m_px_cached.set(eng.prefix.cached_blocks)
        eng.release_blocks(slot.blocks)
        self._slots[slot.idx] = None
        now = self.clock.now()
        if self.policy is not None:
            self.policy.set_blocks(req.id, req.tenant, 0, now)
        usage = (
            self.ledger.finalize(req.id, "ok", now)
            if self.ledger is not None else None
        )
        self.completions.append(Completion(
            id=req.id,
            tokens=list(slot.entry.carried) + list(slot.generated),
            reason=reason,
            prompt_len=len(req.prompt),
            arrival=req.arrival,
            admitted_at=slot.admit_time,
            finished_at=now,
            evictions=slot.entry.evictions,
            first_admitted_at=slot.entry.first_admit,
            prefix_hit_tokens=slot.entry.prefix_hit_tokens,
            spec_proposed=slot.entry.spec_proposed,
            spec_accepted=slot.entry.spec_accepted,
            retries=slot.entry.retries,
            usage=usage,
        ))
        if self.timeline is not None:
            self.timeline.record(
                "retire", t=now, req=req.id, slot=slot.idx,
                info={"reason": reason,
                      "tokens": slot.total_generated},
            )

    # --------------------------------------------------------------- run
    def tick(self) -> bool:
        """ONE scheduling iteration — admit while possible, one prefill
        chunk per refilling slot, one decode step — plus the queue/
        occupancy gauge refresh.  Returns whether anything progressed
        (False = idle: the queue head hasn't arrived yet, or there is no
        work at all).  :meth:`run` is a tick loop over one scheduler;
        the :class:`~chainermn_tpu.serving.router.Router` interleaves
        ticks across replicas on a shared clock."""
        progressed = False
        if self._cancel_deadlines():
            progressed = True
        while self._try_admit():
            progressed = True
        if self._prefill_round():
            progressed = True
        if self._decode_step():
            progressed = True
        self._m_queue.set(len(self._queue))
        self._m_occ.set(self.slot_occupancy)
        if self.policy is not None and not self.policy.fleet:
            # Standalone scheduler: its queue IS the fleet view.  Under
            # a router (policy.fleet) the router publishes the
            # fleet-wide census instead — per-replica publishes would
            # thrash the shared gauges.
            self.policy.publish_queue(
                [e.req.tenant for e in self._queue]
            )
        return progressed

    def run(self, requests: Optional[Sequence[Request]] = None
            ) -> List[Completion]:
        """Submit ``requests`` (optional) and drain queue + slots."""
        for r in requests or ():
            self.submit(r)
        while self.pending:
            if not self.tick():
                if not any(s is not None for s in self._slots):
                    # Idle: jump the clock to the next admission-
                    # unblocking time.  FIFO: the HEAD entry's arrival
                    # (the head is the only entry whose arrival can
                    # unblock anything; skipping to a later entry's
                    # earlier arrival would leave the loop spinning
                    # until the head's time on the real clock).  Policy:
                    # the min future arrival OR the earliest throttle
                    # release — a fully-throttled queue must advance
                    # the clock, never spin (next_arrival covers both).
                    nxt = self.next_arrival()
                    if nxt is None or nxt <= self.clock.now():
                        raise RuntimeError(
                            "scheduler made no progress on arrived work"
                        )
                    self.clock.skip_to(nxt)
                else:  # pragma: no cover - defensive
                    raise RuntimeError(
                        "scheduler made no progress with live slots"
                    )
        self.finish()
        return list(self.completions)

    def finish(self) -> None:
        """The drain epilogue: closing gauge/SLO/memory/incident/device
        publishes.  Split out of :meth:`run` so the router can drive
        replicas tick-by-tick and still close each one's books."""
        self._m_queue.set(0)
        self._m_occ.set(0.0)
        if self.slo is not None:
            self.slo.check()
        if self.memory is not None:
            # Closing sample: the drained pool state (prefix pins only)
            # is the baseline the leak detector measures against.
            self.memory.sample(kv=self._kv_sample())
        if self.incidents is not None:
            # Closing evaluation AFTER the final SLO check and memory
            # sample: a breach that developed after the last on-cadence
            # check (short drains, the final iterations) is judged
            # against the freshest gauges, not one-cadence-stale ones.
            self.incidents.evaluate()
        if self._dev_enabled and self._iterations >= self._mem_every:
            # Closing publish — but only for runs long enough to have
            # meant it (the check cadence): a three-iteration unit drain
            # must not pay the one-time cost capture's extra lowering.
            self._publish_device()

    # ------------------------------------------------------- observability
    def _publish_device(self, capture: bool = True) -> None:
        """``device.*`` roofline gauges for the engine's hot program at
        the mean clean-decode iteration time accumulated since the last
        publish.  Best-effort: any failure must never sink a serving
        loop.  ``capture=True`` (the drain path) may pay the ONE-TIME
        cost capture — an extra lowering+compile, memoized process-wide
        per signature; the on-cadence path passes False so live traffic
        never stalls behind a backend compile (the first run of an
        engine therefore publishes its gauges at drain, and every later
        run publishes on the cadence too, off the memoized model)."""
        if not self._dev_ms_n:
            return
        from chainermn_tpu.observability import device as _odevice

        wf = self.engine.hot_program
        if isinstance(wf, _odevice.WatchedFunction):
            try:
                _odevice.watch().publish_roofline(
                    wf, self._dev_ms_sum / self._dev_ms_n,
                    registry=self._dev_reg, capture=capture,
                )
            except Exception:
                pass
        self._dev_ms_sum = 0.0
        self._dev_ms_n = 0
    def _kv_sample(self) -> dict:
        """KV-pool accounting sample for the memory monitor — live
        slots' written positions vs held capacity feed the
        fragmentation number."""
        from chainermn_tpu.observability.memory import kv_pool_sample

        return kv_pool_sample(
            self.engine,
            [(s.pos, len(s.blocks))
             for s in self._slots if s is not None],
        )

    def _flight_state(self) -> dict:
        """The ``"serving"`` flight-record section: what this engine is
        serving *right now* — readable even while :meth:`run` is live
        (every field is a host-side scalar or small list; worst case a
        torn read shows one admission ago)."""
        slots = []
        for i, s in enumerate(self._slots):
            if s is None:
                slots.append(None)
                continue
            slots.append({
                "req": s.entry.req.id,
                "pos": int(s.pos),
                "prefilling": bool(s.prefilling),
                "generated": len(s.generated),
                "carried": len(s.entry.carried),
                "blocks": len(s.blocks),
                "retries": s.entry.retries,
            })
        by_status: Dict[str, int] = {}
        for c in self.completions:
            by_status[c.status] = by_status.get(c.status, 0) + 1
        state = {
            "iterations": self._iterations,
            "queue_depth": len(self._queue),
            "queued_requests": [e.req.id for e in self._queue[:64]],
            "in_flight_requests": [
                s["req"] for s in slots if s is not None
            ],
            "slots": slots,
            "completions": len(self.completions),
            "completions_by_status": by_status,
            "clock": round(self.clock.now(), 6),
            "engine": self.engine.stats(),
        }
        if self.engine.prefix is not None:
            state["prefix"] = {
                "hit_tokens": self.prefix_hit_tokens,
                "lookup_tokens": self.prefix_lookup_tokens,
                "cached_blocks": self.engine.prefix.cached_blocks,
            }
        if self.engine.spec_k:
            state["spec"] = {
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
            }
        if self.slo is not None and self.slo.last_report:
            state["slo"] = self.slo.last_report
        if self.ledger is not None:
            state["usage"] = self.ledger.usage_state()
        if self.timeline is not None:
            state["timeline_events"] = len(self.timeline)
            state["timeline_dropped"] = self.timeline.dropped
        return state

    def export_trace(self, path: str, rank: int = 0) -> Optional[str]:
        """Write this run's request timeline as Chrome trace-event JSON
        (Perfetto-loadable); returns the path, or None when lifecycle
        tracing is off (``CMN_OBS=0`` and no explicit timeline)."""
        if self.timeline is None:
            return None
        from chainermn_tpu.observability.tracing import write_chrome_trace

        return write_chrome_trace(path, self.timeline.events(), rank=rank)
