"""Disaggregated prefill/decode serving: role-split ranks with live
KV-block migration over the hostcomm p2p object plane.

Chunked prefill bounds how long a prompt can stall running decodes, but
it cannot make the steal zero: every iteration that interleaves a
prefill chunk books to ``serve.mixed_ms`` instead of ``serve.decode_ms``
(the PR-6 attribution), and under prompt-heavy load that mass is decode
latency the SLO monitor eats.  The production-proven fix (DistServe,
OSDI'24; Splitwise, ISCA'24) is to split the two phases across ranks —
this framework's *native* MPMD mode per the communicator/p2p design:

* **prefill roles** run admission + the chunked-prefill ladder and never
  take a decode step;
* **decode roles** run *clean* fixed-shape decode steps only — the
  engine's one-compile contract (``decode_compiles == 1``) holds under
  arbitrary migration churn, because the migration device half is two
  dedicated one-variant programs (``kv_gather``/``kv_put``), never a
  new decode-step signature;
* between them, the **KV-block migration primitive**: a finished slot's
  live physical blocks (target and spec-draft pools alike), block
  table, carried tokens and position are serialized, shipped as framed
  ``send_obj`` payloads over the hostcomm plane, and the block table is
  rewritten against the destination allocator on arrival — byte-
  identical KV, so a migrated request's continuation is exactly the
  continuation the source engine would have produced (greedy tokens
  identical; sampling identical too, since the per-request RNG is
  stateless in ``(seed, position)``).

Shared physical blocks migrate ONCE per payload: the wire format dedupes
by source block id, and the installer maps every referencing slot onto
one destination block via ``BlockAllocator.share`` — refcounted sharing
(and its no-double-free discipline) survives the move.  Migrated full
prompt/history blocks are inserted into the destination's prefix trie,
so hot-prefix sharing survives migration as well: the next identical
prompt admitted at the destination maps the migrated blocks instead of
recomputing them.

The same primitive gives serving-side **resilience for free**: a
SIGTERM'd serving rank drains every live slot (decode-ready slots ship
their KV; still-prefilling slots and queued entries ship as recompute
entries) to a designated peer before exiting with the preemption code —
zero in-flight requests lost (:func:`drain_all`, wired into
:class:`~chainermn_tpu.resilience.preemption.PreemptionGuard` via
``attach_drain``/``poll_serving``).

Failure accounting rides the ``CMN_FAULT`` grammar: the transport is a
``migrate`` hook site (``drop@migrate:N`` loses the Nth migration frame
on the wire), and a dropped or torn frame is detected by the receiver's
sequence/checksum validation — :class:`MigrationError`, counted by
``serve.migration.failed``, watched by the ``migration_failed`` default
incident rule (severity critical).  A decode rank killed mid-stream is
``crash@serve_step:N`` (the scheduler's existing per-iteration hook
site).

Metrics (``serve.migration.*``): ``slots_migrated``, ``blocks_moved``,
``bytes``, ``migrate_ms`` histogram, ``failed`` — same publishing latch
as the scheduler (explicit registry always publishes; otherwise
``CMN_OBS``).

Env knobs (``docs/serving.md`` knob table): ``CMN_DISAGG_ROLES`` (comma
role-per-rank spec for :func:`roles_from_env`), ``CMN_DISAGG_DRAIN_PEER``
(preemption drain destination), ``CMN_DISAGG_TIMEOUT_MS`` (migration
recv deadline).
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
)
from chainermn_tpu.resilience import faults as _faults
from chainermn_tpu.serving.scheduler import (
    Completion,
    Request,
    Scheduler,
    _QueueEntry,
    _Slot,
)

#: Migration wire-format tag; bump on breaking layout changes (a peer
#: running older code must fail loudly, not misinstall blocks).
MIGRATION_SCHEMA = "cmn-kvmig-1"

#: The roles a serving rank can take.
ROLES = ("mixed", "prefill", "decode")


class MigrationError(RuntimeError):
    """A migration frame was dropped, torn, or malformed.  Counted by
    ``serve.migration.failed`` and watched by the ``migration_failed``
    default incident rule.  ``frame`` carries the received frame when it
    is itself INTACT (a sequence gap means an *earlier* frame was lost
    — this one's slots are still salvageable); ``None`` for a torn or
    malformed frame."""

    def __init__(self, msg: str, frame: Optional[dict] = None):
        super().__init__(msg)
        self.frame = frame


def roles_from_env(size: int) -> List[str]:
    """Per-rank roles from ``CMN_DISAGG_ROLES`` (comma-separated, e.g.
    ``"prefill,decode,decode"``); default: every rank ``mixed`` (no
    disaggregation).  A short spec repeats its last role to ``size``."""
    spec = os.environ.get("CMN_DISAGG_ROLES", "")
    if not spec:
        return ["mixed"] * size
    roles = [r.strip() for r in spec.split(",") if r.strip()]
    for r in roles:
        if r not in ROLES:
            raise ValueError(
                f"CMN_DISAGG_ROLES: unknown role {r!r} (one of {ROLES})"
            )
    if not roles:
        return ["mixed"] * size
    while len(roles) < size:
        roles.append(roles[-1])
    return roles[:size]


def drain_peer_from_env(rank: int, size: int,
                        roles: Optional[Sequence[str]] = None
                        ) -> Optional[int]:
    """The preemption drain destination for ``rank``:
    ``CMN_DISAGG_DRAIN_PEER`` when set (must name another live rank),
    else the next rank round-robin that can actually RECEIVE a
    migration stream — prefill ranks have no receive path, so with
    ``roles`` given (typically :func:`roles_from_env`) they are skipped
    and never chosen.  ``None`` when nobody is left to drain to
    (single-rank jobs; an all-prefill remainder).  The chosen peer must
    poll this rank — a :class:`DecodeRole` destination lists every rank
    that can drain to it in ``peer_ranks`` (wire the INVERSE of this
    function's choices, or simply every other non-prefill rank)."""
    spec = os.environ.get("CMN_DISAGG_DRAIN_PEER", "")
    if spec:
        peer = int(spec)
        if not (0 <= peer < size) or peer == rank:
            raise ValueError(
                f"CMN_DISAGG_DRAIN_PEER={peer} invalid for rank {rank} "
                f"of {size}"
            )
        if roles is not None and roles[peer] == "prefill":
            raise ValueError(
                f"CMN_DISAGG_DRAIN_PEER={peer} is a prefill rank — it "
                "never polls the migration plane, so a drained stream "
                "would be silently lost; pick a decode/mixed rank"
            )
        return peer
    for step in range(1, size):
        peer = (rank + step) % size
        if roles is None or roles[peer] != "prefill":
            return peer
    return None


# --------------------------------------------------------------- codec
def _pack_entry(entry: _QueueEntry) -> dict:
    r = entry.req
    return {
        "req": {
            "id": r.id, "prompt": list(r.prompt),
            "max_new_tokens": r.max_new_tokens,
            "temperature": r.temperature, "eos_token": r.eos_token,
            "arrival": r.arrival, "seed": r.seed,
            "deadline_ms": r.deadline_ms,
            # Additive (like deadline_ms / retries were): a frame from
            # a pre-ISSUE-16 sender simply lacks the key and Request's
            # dataclass default fills "default" at unpack.
            "tenant": r.tenant,
            # Additive (ISSUE 19): priority class survives migration,
            # recovery re-dispatch and disagg handoff; pre-ISSUE-19
            # frames lack the key and the dataclass default fills 0.
            "priority": r.priority,
        },
        "carried": list(entry.carried),
        "evictions": entry.evictions,
        "first_admit": entry.first_admit,
        "prefix_hit_tokens": entry.prefix_hit_tokens,
        "spec_proposed": entry.spec_proposed,
        "spec_accepted": entry.spec_accepted,
        "retries": entry.retries,
    }


def _unpack_entry(rec: dict) -> _QueueEntry:
    return _QueueEntry(
        req=Request(**rec["req"]),
        carried=list(rec["carried"]),
        evictions=rec["evictions"],
        first_admit=rec["first_admit"],
        prefix_hit_tokens=rec["prefix_hit_tokens"],
        spec_proposed=rec["spec_proposed"],
        spec_accepted=rec["spec_accepted"],
        # .get(): a cmn-kvmig-1 frame from a pre-ISSUE-15 sender still
        # installs (additive schema change).
        retries=rec.get("retries", 0),
    )


def pack_slots(sched: Scheduler, slots: Sequence[_Slot]) -> dict:
    """Serialize live DECODE-READY slots (prefill finished) into one
    migration body: per-slot continuation state + the deduped physical
    blocks backing their tables (target and draft pools alike, gathered
    through the engine's one-variant ``kv_gather`` program).  Blocks
    shared across the packed slots (prefix sharing) appear ONCE."""
    eng = sched.engine
    blocks: Dict[int, dict] = {}
    recs = []
    for slot in slots:
        if slot.prefilling:
            raise ValueError(
                f"slot {slot.idx} (request {slot.entry.req.id}) is still "
                "prefilling — migrate it as a recompute entry instead "
                "(pack_slots ships finished KV only)"
            )
        for b in slot.blocks:
            if b not in blocks:
                blocks[b] = eng.read_block(b)
        if sched.ledger is not None:
            # Booked at pack (the send side — once per migration): each
            # slot pays for ITS blocks' bytes, shared blocks charged to
            # every referencing slot (pinner-pays, same stance as
            # block-seconds) — so the ledger total can exceed the
            # deduped wire bytes ``serve.migration.bytes`` counts.
            sched.ledger.book(
                slot.entry.req.id, "migration_bytes",
                sum(_block_nbytes(blocks[b]) for b in slot.blocks),
            )
        recs.append({
            **_pack_entry(slot.entry),
            "generated": list(slot.generated),
            "pos": int(slot.pos),
            "last_token": int(slot.last_token),
            "blocks": list(slot.blocks),
        })
    return {"slots": recs, "entries": [], "blocks": blocks}


def _block_nbytes(data: dict) -> int:
    """KV bytes one packed block carries (target + draft pools)."""
    total = 0
    for pool in ("target", "draft"):
        if data.get(pool) is None:
            continue
        for layer in data[pool]:
            for arr in layer.values():
                total += arr.nbytes
    return total


def payload_bytes(body: dict) -> int:
    """KV bytes a migration body moves (the ``serve.migration.bytes``
    feed) — block array bytes only; the host-side slot records are
    noise next to them."""
    return sum(_block_nbytes(d) for d in body["blocks"].values())


def _crc(body: dict) -> int:
    """Checksum over every block's bytes, in deterministic order — the
    torn-frame detector (a frame whose KV bytes were corrupted in
    flight must not be installed as if byte-identical)."""
    c = 0
    for b in sorted(body["blocks"]):
        data = body["blocks"][b]
        for pool in ("target", "draft"):
            if data.get(pool) is None:
                continue
            for layer in data[pool]:
                for name in sorted(layer):
                    c = zlib.crc32(layer[name].tobytes(), c)
    return c


def detach_slots(sched: Scheduler, slots: Sequence[_Slot]) -> None:
    """Release migrated slots from the SOURCE scheduler: their block
    references return to the allocator (shared/trie-held blocks survive
    by refcount, exactly as retirement) and the slots free up.  Call
    only after the payload is on the wire."""
    for slot in slots:
        if sched._slots[slot.idx] is not slot:
            continue
        sched.engine.release_blocks(slot.blocks)
        sched._slots[slot.idx] = None
        if sched.ledger is not None:
            # Settle source-side occupancy; the install restarts the
            # integral at the destination (a fleet-shared ledger sees a
            # clean handoff; role-split ledgers each stay consistent).
            sched.ledger.set_blocks(
                slot.entry.req.id, 0, sched.clock.now()
            )
        if sched.timeline is not None:
            sched.timeline.record(
                "migrate_out", t=sched.clock.now(),
                req=slot.entry.req.id, slot=slot.idx,
                info={"pos": int(slot.pos), "blocks": len(slot.blocks)},
            )


def install_payload(sched: Scheduler, body: dict, defer: bool = False
                    ) -> Tuple[int, int, Optional[dict]]:
    """Install a migration body into the DESTINATION scheduler.

    Per slot: allocate fresh physical blocks (first referencing slot
    owns them; later slots :meth:`~chainermn_tpu.serving.kv_pool.
    BlockAllocator.share` — sharing survives migration with no
    double-free), write the KV through the engine's one-variant
    ``kv_put`` program, REWRITE the block table against the destination
    allocator's ids, rebuild the slot's host state, and insert the full
    prompt/history blocks into the destination prefix trie so the
    migrated prefix is mappable by future admissions.

    A slot the destination cannot place right now (no free slot / pool
    blocks): with ``defer=True`` (the decode role) its record and block
    data move to a REMAINDER body the caller retries when a slot frees
    — the KV was already paid for, and re-prefilling it on a decode
    rank would put mixed iterations right back on the clean histograms;
    with ``defer=False`` it falls back to a recompute ENTRY (carried
    tokens ride along).  Either way nothing is ever lost.

    Returns ``(slots_installed, entries_queued, remainder_or_None)``.
    """
    eng = sched.engine
    now = sched.clock.now()
    t0 = time.perf_counter()
    dst_map: Dict[int, int] = {}
    claimed: Dict[int, bool] = {}
    installed = queued = 0
    deferred: List[dict] = []
    for rec in body["slots"]:
        entry = _unpack_entry(rec)
        free = [i for i, s in enumerate(sched._slots) if s is None]
        fresh = [b for b in rec["blocks"] if b not in dst_map]
        if free and not eng.pool.allocator.can_alloc(len(fresh)) and \
                eng.prefix is not None:
            # Cached-only trie blocks are reuse potential — a live
            # migrated slot beats them, same policy as admission.  Only
            # when a slot is actually available: with every slot busy
            # the record defers regardless, and a deferred-retry loop
            # that evicted the trie each tick would strip exactly the
            # migrated hot prefixes this installer exists to preserve.
            sched._m_px_evicted.inc(eng.prefix.evict(
                len(fresh) - eng.pool.allocator.free_blocks
            ))
        if not free or not eng.pool.allocator.can_alloc(len(fresh)):
            if defer:
                deferred.append(rec)
            else:
                # Recompute fallback: requeue with everything generated
                # so far carried — the destination prefills it back
                # (usually a trie hit on blocks installed moments ago).
                entry.carried = (
                    list(entry.carried) + list(rec["generated"])
                )
                sched.submit_entry(entry)
                queued += 1
            continue
        got = eng.alloc_blocks(len(fresh))
        for src, dst in zip(fresh, got):
            dst_map[src] = dst
            eng.write_block(dst, body["blocks"][src])
            claimed[dst] = False
        slot = _Slot(free[0], entry, eng.max_blocks, now,
                     sched._admit_seq)
        sched._admit_seq += 1
        slot.blocks = []
        for b in rec["blocks"]:
            dst = dst_map[b]
            if claimed[dst]:
                eng.pool.allocator.share([dst])
            claimed[dst] = True
            slot.table[len(slot.blocks)] = dst
            slot.blocks.append(dst)
        slot.pos = int(rec["pos"])
        slot.generated = list(rec["generated"])
        slot.last_token = int(rec["last_token"])
        slot.prefilling = False
        sched._slots[free[0]] = slot
        if sched.ledger is not None:
            # begin() is idempotent: on a fleet-shared ledger the record
            # exists; a role-split destination with its own ledger opens
            # one here (tenant rides the codec).  Occupancy integration
            # restarts at the installed block count.
            sched.ledger.begin(entry.req, now)
            sched.ledger.set_blocks(
                entry.req.id, len(slot.blocks), now
            )
        eng.seed_slot(free[0], entry.req.seed, entry.req.temperature)
        if eng.prefix is not None:
            # Positions [0, pos) are written — same insertable span as
            # retirement's: the migrated hot prefix becomes a trie hit
            # for the next identical prompt at the destination.
            seq = slot.text + slot.generated
            eng.prefix.insert(
                seq[: slot.pos],
                slot.blocks[: slot.pos // eng.block_len],
            )
        if sched.timeline is not None:
            sched.timeline.record(
                "migrate_in", t=now, req=entry.req.id, slot=free[0],
                info={"pos": slot.pos, "blocks": len(slot.blocks)},
            )
        installed += 1
    for rec in body["entries"]:
        sched.submit_entry(_unpack_entry(rec))
        queued += 1
    if eng.prefix is not None:
        # Same gauge refresh as the scheduler's own insert/evict sites:
        # the trie pins migration just created (or the eviction it
        # forced) must show in ``serve.prefix.cached_blocks`` NOW, not
        # at the next local retirement — the memory watermark sampler
        # reads this exactly in the migration-churn window.
        sched._m_px_cached.set(eng.prefix.cached_blocks)
    if installed:
        # Drain the ``kv_put`` dispatches NOW: left queued, the next
        # decode step's token readback would absorb them into its timed
        # window, and the clean-decode histograms / SLO token p95 would
        # silently carry migration-install cost (exactly the attribution
        # leak ``serve.mixed_ms`` exists to prevent for prefill).  The
        # install cost books to ``serve.migration.install_ms`` instead.
        eng.sync()
        sched._m_mig_install.observe((time.perf_counter() - t0) * 1e3)
    remainder = None
    if deferred:
        need = {b for rec in deferred for b in rec["blocks"]}
        # A deferred slot sharing a block with one just installed gets
        # its own copy on retry (dst_map is per-call): byte-identical
        # content, just without the refcount link — correct, merely less
        # shared.
        remainder = {
            "slots": deferred, "entries": [],
            "blocks": {b: body["blocks"][b] for b in need},
        }
    return installed, queued, remainder


# ----------------------------------------------------------- transport
class MigrationTransport:
    """Framed slot migration over any ``send_obj``/``recv_obj`` object
    plane (:class:`~chainermn_tpu.hostcomm.HostComm`, or an in-process
    :class:`LocalComm` endpoint).

    Each frame carries the schema tag, a per-destination sequence
    number, and a CRC over the KV bytes; the receiver validates all
    three, so a dropped frame (``CMN_FAULT=drop@migrate:N`` — the wire
    loses the Nth migration send) surfaces as a sequence gap on the
    next frame and a torn frame as a checksum mismatch — both raise
    :class:`MigrationError` and count ``serve.migration.failed``.

    Publishing follows the scheduler's latch: an explicit ``registry``
    always publishes ``serve.migration.*``; otherwise the ambient
    global registry rides the ``CMN_OBS`` master switch.
    """

    def __init__(self, comm, registry=None, timeout_ms: Optional[int] = None,
                 injector=None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.metrics import (
            DEFAULT_MS_EDGES,
            registry as global_registry,
        )

        self.comm = comm
        if timeout_ms is None:
            env = os.environ.get("CMN_DISAGG_TIMEOUT_MS", "")
            timeout_ms = int(env) if env else None
        self.timeout_ms = timeout_ms
        self._fault = (
            injector if injector is not None
            else _faults.process_injector()
        )
        self._seq_out: Dict[int, int] = {}
        self._seq_in: Dict[int, int] = {}
        if registry is None and not _obs.enabled():
            noop = _NoopInstrument()
            self._m_slots = self._m_blocks = self._m_bytes = noop
            self._m_ms = self._m_failed = noop
        else:
            reg = registry if registry is not None else global_registry()
            self._m_slots = reg.counter("serve.migration.slots_migrated")
            self._m_blocks = reg.counter("serve.migration.blocks_moved")
            self._m_bytes = reg.counter("serve.migration.bytes")
            self._m_ms = reg.histogram(
                "serve.migration.migrate_ms", edges=DEFAULT_MS_EDGES
            )
            self._m_failed = reg.counter("serve.migration.failed")

    # ------------------------------------------------------------- send
    def send(self, body: dict, dest: int) -> None:
        """Frame and ship one migration body (schema + seq + crc)."""
        seq = self._seq_out.get(dest, 0)
        self._seq_out[dest] = seq + 1
        frame = {
            "schema": MIGRATION_SCHEMA, "seq": seq, "kind": "slots",
            "crc": _crc(body), "body": body,
        }
        self._m_slots.inc(len(body["slots"]))
        self._m_blocks.inc(len(body["blocks"]))
        self._m_bytes.inc(payload_bytes(body))
        if self._fault is not None and \
                self._fault.hook("migrate") == "drop":
            # Injected drop: the frame is lost ON THE WIRE — the sender
            # proceeds as delivered (seq consumed), the receiver sees a
            # sequence gap on the next frame.
            return
        self.comm.send_obj(frame, dest, op="migrate")

    def send_eof(self, dest: int) -> None:
        """Signal this source has no more migrations (role shutdown /
        drain complete) — receivers stop polling it."""
        seq = self._seq_out.get(dest, 0)
        self._seq_out[dest] = seq + 1
        self.comm.send_obj(
            {"schema": MIGRATION_SCHEMA, "seq": seq, "kind": "eof"},
            dest, op="migrate",
        )

    def observe_ms(self, ms: float) -> None:
        """Book one end-to-end migration latency (pack + send +
        detach — the source-side cost of moving the slots)."""
        self._m_ms.observe(ms)

    # ------------------------------------------------------------- recv
    def recv(self, source: int, timeout_ms: Optional[int] = None) -> dict:
        """Receive + validate one migration frame.  Raises
        :class:`MigrationError` (and counts ``serve.migration.failed``)
        on schema mismatch, sequence gap (a dropped frame's slots are
        gone — the sender released them), or CRC mismatch (torn KV)."""
        if timeout_ms is None:
            timeout_ms = self.timeout_ms
        kw = {} if timeout_ms is None else {"timeout_ms": timeout_ms}
        frame = self.comm.recv_obj(source, op="migrate", **kw)
        if not isinstance(frame, dict) or \
                frame.get("schema") != MIGRATION_SCHEMA:
            self._m_failed.inc()
            # Consume the bad frame's slot in the sequence when it has
            # one: the NEXT valid frame must not be condemned as a gap
            # (a second failed count + a "slots lost" log for a frame
            # that arrived intact).
            if isinstance(frame, dict) and \
                    isinstance(frame.get("seq"), int):
                self._seq_in[source] = frame["seq"] + 1
            raise MigrationError(
                f"migration frame from rank {source} has schema "
                f"{frame.get('schema') if isinstance(frame, dict) else type(frame).__name__!r}"
                f" (want {MIGRATION_SCHEMA}) — peer version skew?"
            )
        expect = self._seq_in.get(source, 0)
        got = frame.get("seq")
        # The frame itself is intact: later frames must keep validating,
        # so the expected sequence resumes AFTER this one.
        self._seq_in[source] = int(got) + 1
        if got != expect:
            self._m_failed.inc()
            # The gap condemns the EARLIER frame(s); this one is still
            # installable if its own checksum holds — hand it back on
            # the error so the caller can salvage its slots.
            intact = (
                frame["kind"] != "slots"
                or _crc(frame["body"]) == frame["crc"]
            )
            raise MigrationError(
                f"migration frame from rank {source}: sequence {got}, "
                f"expected {expect} — {got - expect} frame(s) dropped in "
                "flight (their slots are lost; re-prefill from the "
                "request log upstream)",
                frame=frame if intact else None,
            )
        if frame["kind"] == "slots" and _crc(frame["body"]) != frame["crc"]:
            self._m_failed.inc()
            raise MigrationError(
                f"migration frame from rank {source} seq {got}: KV "
                "checksum mismatch — torn frame, refusing to install"
            )
        return frame

    def poll(self, source: int,
             timeout_ms: Optional[int] = 0) -> Optional[dict]:
        """Non/short-blocking :meth:`recv`: ``None`` when no frame
        arrived within ``timeout_ms``.  Validation errors still raise."""
        try:
            return self.recv(source, timeout_ms=timeout_ms)
        except MigrationError:
            raise
        except TimeoutError as e:
            # PeerFailedError subclasses TimeoutError; only a genuine
            # deadline expiry is a quiet "nothing yet" — a transport
            # failure or detector verdict must surface.
            if getattr(e, "kind", "timeout") != "timeout":
                raise
            return None


# ------------------------------------------------------ migration verbs
def handoff_slots(src: Scheduler, dst: Scheduler,
                  slots: Optional[Sequence[_Slot]] = None
                  ) -> Tuple[int, int]:
    """In-process scale-down / rolling-deploy handoff (ISSUE 17): pack
    ``slots`` (default: every decode-ready slot) from ``src`` and
    install them straight into ``dst`` — the same cmn-kvmig-1 body the
    framed transport ships, minus the wire, so the destination's
    one-variant ``kv_put``/``kv_gather`` programs do the move and the
    survivor never recompiles.  Slots detach from ``src`` only AFTER
    the install returns: an exception mid-install leaves the source
    intact (over-held beats lost; the caller's fault boundary decides
    what to do with the husk).  Returns ``(slots_installed,
    entries_queued)`` — ``entries_queued`` counts slots the destination
    could not place live (no free slot / pool blocks) that fell back to
    recompute entries on its queue, carried tokens preserved."""
    slots = src.ready_slots() if slots is None else list(slots)
    if not slots:
        return 0, 0
    body = pack_slots(src, slots)
    installed, queued, _ = install_payload(dst, body)
    detach_slots(src, slots)
    return installed, queued


def migrate_slots(sched: Scheduler, transport: MigrationTransport,
                  dest: int, slots: Sequence[_Slot]) -> int:
    """Move live decode-ready ``slots`` to peer ``dest``: pack → framed
    send → detach from the source.  Returns the slot count."""
    if not slots:
        return 0
    t0 = time.perf_counter()
    body = pack_slots(sched, slots)
    transport.send(body, dest)
    detach_slots(sched, slots)
    transport.observe_ms((time.perf_counter() - t0) * 1e3)
    return len(body["slots"])


def drain_all(sched: Scheduler, transport: MigrationTransport,
              dest: int, eof: bool = True,
              deferred: Sequence[dict] = (),
              eof_ranks: Sequence[int] = ()) -> dict:
    """Preemption drain: migrate EVERYTHING this scheduler holds to
    ``dest`` — decode-ready slots ship their live KV, still-prefilling
    slots and every queued entry ship as recompute entries (carried
    tokens ride along) — then optionally signal ``eof``.  Zero in-flight
    requests are lost; the peer's completions are greedy-identical to
    what an unpreempted run would have produced (byte-identical KV +
    stateless per-request RNG).  ``deferred`` forwards migration bodies
    a decode role had parked waiting for capacity (they hold requests
    no other rank knows about — a drain that dropped them would break
    the zero-loss contract; :meth:`DecodeRole.drain` passes its
    backlog).  ``eof_ranks`` closes the stream toward EVERY peer this
    rank was feeding, not just the drain destination — a decode rank
    still waiting on this source's eof would otherwise never terminate
    (:meth:`PrefillRole.drain` passes its full ``decode_ranks``).
    Returns a summary dict (the guard's stderr line / flight
    record)."""
    t0 = time.perf_counter()
    fwd_slots = 0
    for b in deferred:
        transport.send(b, dest)
        fwd_slots += len(b["slots"]) + len(b["entries"])
    ready = sched.ready_slots()
    body = pack_slots(sched, ready)
    for slot in sched._slots:
        if slot is None or not slot.prefilling:
            continue
        entry = slot.entry
        entry.carried = list(entry.carried) + list(slot.generated)
        body["entries"].append(_pack_entry(entry))
    while sched._queue:
        body["entries"].append(_pack_entry(sched._queue.pop(0)))
    transport.send(body, dest)
    detach_slots(sched, ready)
    for i, slot in enumerate(sched._slots):
        if slot is not None:
            sched.engine.release_blocks(slot.blocks)
            sched._slots[i] = None
            if sched.ledger is not None:
                # Still-prefilling slots drained as recompute entries:
                # settle their occupancy at release like any eviction.
                sched.ledger.set_blocks(
                    slot.entry.req.id, 0, sched.clock.now()
                )
    if eof:
        for d in dict.fromkeys([dest, *eof_ranks]):
            transport.send_eof(d)
    transport.observe_ms((time.perf_counter() - t0) * 1e3)
    out = {
        "dest": dest,
        "slots": len(body["slots"]),
        "entries": len(body["entries"]),
        "blocks": len(body["blocks"]),
        "bytes": payload_bytes(body),
    }
    if fwd_slots:
        out["deferred_forwarded"] = fwd_slots
    return out


# ---------------------------------------------------------------- roles
class PrefillRole:
    """Drives a :class:`~chainermn_tpu.serving.Scheduler` in
    prefill-only mode: admission + the chunked-prefill ladder, then
    every slot whose prefill finished (first token sampled) ships to a
    decode rank — this rank never takes a decode step, so its
    ``serve.mixed_ms`` is the only place prefill/decode interference
    can land, and the decode ranks' histograms stay clean.

    Requests that complete AT prefill (``max_new_tokens == 1``, or EOS
    on the first token) retire locally — their completions merge with
    the decode ranks' downstream.
    """

    def __init__(self, sched: Scheduler, transport: MigrationTransport,
                 decode_ranks: Sequence[int], guard=None):
        if not decode_ranks:
            raise ValueError("prefill role needs >= 1 decode rank")
        self.sched = sched
        self.transport = transport
        self.decode_ranks = list(decode_ranks)
        self.guard = guard
        self._rr = 0
        self._ticks = 0

    def submit(self, req: Request) -> None:
        self.sched.submit(req)

    def tick(self) -> bool:
        """One prefill-role iteration: admit, one chunk per refilling
        slot, ship every finished slot (round-robin over the decode
        ranks).  Returns whether anything progressed."""
        self._ticks += 1
        if self.guard is not None:
            self.guard.poll_serving(self._ticks)
        progressed = False
        while self.sched._try_admit():
            progressed = True
        if self.sched._prefill_round():
            progressed = True
        ready = [
            s for s in self.sched._slots
            if s is not None and not s.prefilling
        ]
        if ready:
            # Round-robin PER SLOT (near-simultaneous completions are
            # the common case — similar-length prompts admitted
            # together), grouped per destination so blocks shared
            # within a batch still ship once.
            groups: Dict[int, List[_Slot]] = {}
            for s in ready:
                dest = self.decode_ranks[
                    self._rr % len(self.decode_ranks)
                ]
                self._rr += 1
                groups.setdefault(dest, []).append(s)
            for dest, batch in groups.items():
                migrate_slots(self.sched, self.transport, dest, batch)
            progressed = True
        self.sched._m_queue.set(len(self.sched._queue))
        self.sched._m_occ.set(self.sched.slot_occupancy)
        return progressed

    @property
    def pending(self) -> bool:
        return self.sched.pending

    def finish(self) -> None:
        """Signal every decode rank this source is done, close books."""
        for d in self.decode_ranks:
            self.transport.send_eof(d)
        self.sched.finish()

    def drain(self, dest: int) -> dict:
        """This role's preemption drain (bind via
        ``guard.attach_drain``): everything the scheduler holds goes to
        ``dest``, and EVERY decode rank this role feeds gets the eof —
        a decode peer still waiting on this source would otherwise
        never terminate its loop."""
        return drain_all(
            self.sched, self.transport, dest,
            eof_ranks=self.decode_ranks,
        )


class DecodeRole:
    """Drives a :class:`~chainermn_tpu.serving.Scheduler` as a decode
    rank: installs migration frames from the prefill ranks, then runs
    the scheduler's normal tick — with no local admissions and no
    prefilling slots that is CLEAN decode steps only (every iteration
    books to ``serve.decode_ms``; the one-compile contract holds under
    churn).  Drained recompute ENTRIES (preemption) do re-enter through
    prefill here — resilience beats purity when a peer is dying.

    ``peer_ranks`` names the decode/mixed peers whose PREEMPTION DRAIN
    may target this rank (i.e. every rank for which
    :func:`drain_peer_from_env` can pick us): they are polled for
    frames exactly like prefill sources, but a healthy peer never
    sends anything — so unlike prefill sources they do NOT gate
    :attr:`done` (waiting on an eof a healthy peer never emits would
    deadlock every unpreempted run).  Wiring a drain source into
    ``prefill_ranks`` instead is exactly that deadlock — use
    ``peer_ranks``."""

    def __init__(self, sched: Scheduler, transport: MigrationTransport,
                 prefill_ranks: Sequence[int], guard=None,
                 peer_ranks: Sequence[int] = ()):
        self.sched = sched
        self.transport = transport
        self.prefill_ranks = list(prefill_ranks)
        self.peer_ranks = [
            r for r in peer_ranks if r not in self.prefill_ranks
        ]
        self.guard = guard
        self._eof = set()
        self._ticks = 0
        #: migration bodies waiting for a slot/blocks to free up (the
        #: KV is already paid for — deferring beats re-prefilling).
        self._deferred: List[dict] = []

    def _install(self, body: dict) -> bool:
        installed, queued, rest = install_payload(
            self.sched, body, defer=True
        )
        if rest is not None:
            self._deferred.append(rest)
        return bool(installed or queued)

    def tick(self, poll_ms: int = 0) -> bool:
        """One decode-role iteration: retry deferred installs, drain
        arrived migration frames from every still-open source, then one
        scheduler tick."""
        self._ticks += 1
        if self.guard is not None:
            self.guard.poll_serving(self._ticks)
        progressed = False
        if self._deferred:
            backlog, self._deferred = self._deferred, []
            for body in backlog:
                if self._install(body):
                    progressed = True
        for src in (*self.prefill_ranks, *self.peer_ranks):
            if src in self._eof:
                continue
            while True:
                try:
                    frame = self.transport.poll(src, timeout_ms=poll_ms)
                except MigrationError as e:
                    # One lost/torn frame must not take the rank (and
                    # every resident slot) with it: the failure is
                    # counted (``serve.migration.failed`` — the
                    # ``migration_failed`` rule fires at the next
                    # incident evaluation), sequence validation already
                    # resumed, and an intact frame that merely REPORTED
                    # the gap still gets its slots installed.
                    import sys as _sys

                    _sys.stderr.write(
                        f"[chainermn_tpu.serving.disagg] from rank "
                        f"{src}: {e}\n"
                    )
                    progressed = True
                    frame = e.frame
                    if frame is None:
                        continue
                if frame is None:
                    break
                if frame["kind"] == "eof":
                    self._eof.add(src)
                    break
                if self._install(frame["body"]):
                    progressed = True
        if self.sched.tick():
            progressed = True
        return progressed

    @property
    def done(self) -> bool:
        """Every PREFILL source signalled eof and nothing is left to
        serve.  ``peer_ranks`` (potential drain sources) don't gate
        this: a healthy peer never sends an eof."""
        return (
            all(src in self._eof for src in self.prefill_ranks)
            and not self.sched.pending
            and not self._deferred
        )

    def drain(self, dest: int) -> dict:
        """This role's preemption drain (what ``guard.attach_drain``
        should bind for a decode rank): everything the scheduler holds
        PLUS the deferred migration backlog — bodies parked here hold
        requests no other rank knows about, so a drain that skipped
        them would silently break the zero-loss contract."""
        deferred, self._deferred = self._deferred, []
        return drain_all(
            self.sched, self.transport, dest, deferred=deferred
        )

    def run_loop(self, poll_ms: int = 50) -> List[Completion]:
        """Multi-rank service loop: tick until every prefill source is
        done and the last slot retires (the decode rank's ``main``).
        Ticks BEFORE checking :attr:`done`, so a pure drain receiver
        (no prefill sources, only ``peer_ranks``) installs the frames
        already queued for it instead of terminating vacuously."""
        while True:
            progressed = self.tick(poll_ms=poll_ms)
            if self.done:
                break
            if not progressed:
                nxt = self.sched.next_arrival()
                if nxt is not None:
                    self.sched.clock.skip_to(nxt)
        self.sched.finish()
        return list(self.sched.completions)


def serve_disaggregated(prefill: PrefillRole, decode: DecodeRole,
                        requests: Optional[Sequence[Request]] = None
                        ) -> List[Completion]:
    """Single-process driver for one prefill/decode role pair on a
    SHARED scheduler clock (tier-1 tests, benchmarks): interleave the
    two roles' ticks until the stream drains, then merge completions
    (sorted by finish time).  Multi-rank deployments run each role's
    own loop instead (:meth:`DecodeRole.run_loop`)."""
    for r in requests or ():
        prefill.submit(r)
    clock = prefill.sched.clock
    while prefill.pending:
        # Decode first: both roles share one process (and, on the CPU
        # rig, one device), so ticking prefill first would queue its
        # chunk dispatches ahead of the decode step inside every loop
        # iteration — exactly the contamination the role split exists
        # to remove.  Real deployments separate the devices; the order
        # here keeps the in-process approximation honest.
        d = decode.tick()
        p = prefill.tick()
        if not (p or d):
            nxt = prefill.sched.next_arrival()
            if nxt is None:  # pragma: no cover - defensive
                raise RuntimeError(
                    "disagg pair made no progress with work pending"
                )
            clock.skip_to(nxt)
    prefill.finish()
    while not decode.done:
        if not decode.tick():
            nxt = decode.sched.next_arrival()
            if nxt is None:  # pragma: no cover - defensive
                raise RuntimeError(
                    "decode role made no progress with work pending"
                )
            clock.skip_to(nxt)
    decode.sched.finish()
    out = list(prefill.sched.completions) + list(decode.sched.completions)
    return sorted(out, key=lambda c: (c.finished_at, c.id))


# ------------------------------------------------------- in-process comm
class _LocalEndpoint:
    """One rank's view of a :class:`LocalComm` — the ``send_obj`` /
    ``recv_obj`` surface :class:`MigrationTransport` needs."""

    def __init__(self, mesh: "LocalComm", rank: int):
        self._mesh = mesh
        self.rank = rank
        self.size = mesh.size

    def send_obj(self, obj, dest: int, timeout_ms=None,
                 op: str = "send_obj") -> None:
        import pickle

        # Pickle round-trip: wire-faithful framing (the payload must
        # survive real serialization, exactly as hostcomm's frames do).
        self._mesh.queues[(self.rank, dest)].append(pickle.dumps(obj))

    def recv_obj(self, source: int, timeout_ms=None,
                 op: str = "recv_obj"):
        import pickle

        q = self._mesh.queues[(source, self.rank)]
        if not q:
            raise TimeoutError(
                f"recv_obj from {source}: no frame queued (LocalComm is "
                "single-threaded — timeouts cannot be waited out)"
            )
        return pickle.loads(q.popleft())


class LocalComm:
    """In-process N-rank object plane over queue pairs — the PR-8
    fleet-test rig's comm shape, packaged for single-process role-split
    serving (tier-1 tests, the ``--disagg`` bench arm).  Frames pickle
    through, so payloads are exercised against real serialization;
    ``recv_obj`` on an empty queue raises ``TimeoutError`` immediately
    (single-threaded — there is nobody else to wait for)."""

    def __init__(self, size: int):
        from collections import deque

        self.size = int(size)
        self.queues = {
            (s, d): deque()
            for s in range(size) for d in range(size) if s != d
        }

    def endpoint(self, rank: int) -> _LocalEndpoint:
        return _LocalEndpoint(self, rank)
