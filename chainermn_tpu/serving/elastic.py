"""Elastic serving fleet: closed-loop autoscaling + rolling deploys.

The paper's MPMD fleet is statically provisioned — ChainerMN's world
size is fixed at ``mpiexec`` time — but production serving load is
diurnal and bursty.  This module (ISSUE 17) closes the loop with the
mechanical pieces the repo already owns: the router's live gauges
(PR 13), the zero-loss ``cmn-kvmig-1`` drain/migration path (PR 14),
the probation circuit breaker (PR 15), and the declarative watch-rule
grammar (PR 12).

* :class:`Autoscaler` — watches ``serve.router.queue_depth``,
  ``serve.slot_occupancy`` and ``serve.slo.p95_drift`` through
  incident-plane :class:`~chainermn_tpu.observability.incident.Watch`
  rules and scales the :class:`~chainermn_tpu.serving.router.Router`'s
  replica set.  Scale-up constructs a replica via the injected
  ``engine_factory`` and registers it BEHIND PROBATION
  (``Router.add_replica``); scale-down picks the coldest live replica,
  fences it (DRAINING), drains every live slot and queued entry to
  survivors (``Router.drain_replica`` — live KV over
  ``pack_slots``/``install_payload``, nothing lost, survivors never
  recompile), then deregisters it.  Hysteresis (consecutive breaching
  ticks, the Watch latch discipline) plus a post-action cooldown keep
  bursty gauges from flapping the fleet; a would-be action in the
  OPPOSITE direction during cooldown counts ``serve.autoscale.flap``
  (the critical ``scale_flap`` default incident rule) and is
  suppressed.

* :class:`RollingDeploy` — zero-downtime version replacement: the same
  fence → drain → revive sequence, one replica at a time, with
  checkpointer-loaded params standing in for "new model version".
  Health gate: each replaced replica must GRADUATE PROBATION before
  the next is touched.  A replica that dies mid-rollout pauses the
  rollout and files a critical incident (``rollout_interrupted``)
  instead of marching on; a step stuck past
  ``CMN_SERVE_ROLLOUT_TIMEOUT_TICKS`` counts ``serve.rollout.stalled``
  (the critical ``rollout_stalled`` default rule).

Both controllers are host-side supervisors over PUBLIC router seams
(``add_replica`` / ``drain_replica`` / ``retire_replica`` /
``revive_replica`` / ``deregister_replica``) — everything they do, an
external operator could do by hand; the chaos harness drives the same
seams under fault schedules (``tests/serving_tests/test_elastic.py``).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
    _env_float,
)


# ----------------------------------------------------------- env knobs
def scale_up_depth_from_env() -> float:
    """``CMN_SERVE_SCALE_UP_DEPTH`` — arrived requests held back in the
    router queue above which the autoscaler wants a replica (default
    4)."""
    return _env_float("CMN_SERVE_SCALE_UP_DEPTH", 4.0)


def scale_up_drift_from_env() -> float:
    """``CMN_SERVE_SCALE_UP_DRIFT`` — worst-replica ``serve.slo.
    p95_drift`` above which the autoscaler wants a replica (default
    0.25)."""
    return _env_float("CMN_SERVE_SCALE_UP_DRIFT", 0.25)


def scale_down_occ_from_env() -> float:
    """``CMN_SERVE_SCALE_DOWN_OCC`` — mean fleet slot occupancy below
    which (with an empty router queue) the autoscaler retires the
    coldest replica (default 0.3)."""
    return _env_float("CMN_SERVE_SCALE_DOWN_OCC", 0.3)


def scale_hysteresis_from_env() -> int:
    """``CMN_SERVE_SCALE_HYSTERESIS`` — consecutive breaching ticks a
    scaling signal must hold before the autoscaler acts (default 2)."""
    return max(1, int(_env_float("CMN_SERVE_SCALE_HYSTERESIS", 2)))


def scale_cooldown_from_env() -> int:
    """``CMN_SERVE_SCALE_COOLDOWN_TICKS`` — ticks after a scale action
    during which no further action fires (a reversed direction in this
    window counts ``serve.autoscale.flap``; default 16)."""
    return max(0, int(_env_float("CMN_SERVE_SCALE_COOLDOWN_TICKS", 16)))


def scale_bounds_from_env() -> tuple:
    """``CMN_SERVE_SCALE_MIN`` / ``CMN_SERVE_SCALE_MAX`` — fleet-size
    bounds the autoscaler never crosses (defaults 1 / 8)."""
    lo = max(1, int(_env_float("CMN_SERVE_SCALE_MIN", 1)))
    hi = max(lo, int(_env_float("CMN_SERVE_SCALE_MAX", 8)))
    return lo, hi


def rollout_timeout_from_env() -> int:
    """``CMN_SERVE_ROLLOUT_TIMEOUT_TICKS`` — ticks one rollout step may
    take (drain + probation graduation) before ``serve.rollout.
    stalled`` counts and the ``rollout_stalled`` rule fires (default
    256)."""
    return max(1, int(_env_float("CMN_SERVE_ROLLOUT_TIMEOUT_TICKS", 256)))


# ------------------------------------------------------------ Autoscaler
class Autoscaler:
    """Closed-loop fleet sizing over the router's live signals.

    Args:
      router: the :class:`~chainermn_tpu.serving.router.Router` whose
        replica set this controller owns.
      engine_factory: builds one fresh engine per scale-up (same
        contract as the chaos harness's: a new replica's device state
        is always fresh).
      registry: where ``serve.autoscale.*`` publishes — same latch as
        the Scheduler/Router (explicit always publishes; ``None``
        rides the ``CMN_OBS`` master switch; off → noop instruments,
        zero overhead — the obs A/B contract is unchanged with an
        autoscaler constructed).
      min_replicas / max_replicas: fleet-size bounds (defaults
        ``CMN_SERVE_SCALE_MIN`` / ``CMN_SERVE_SCALE_MAX``).
      up_depth / up_drift / down_occ: signal thresholds (defaults
        ``CMN_SERVE_SCALE_UP_DEPTH`` / ``CMN_SERVE_SCALE_UP_DRIFT`` /
        ``CMN_SERVE_SCALE_DOWN_OCC``).
      hysteresis / cooldown_ticks: flap damping (defaults
        ``CMN_SERVE_SCALE_HYSTERESIS`` /
        ``CMN_SERVE_SCALE_COOLDOWN_TICKS``).
      down_hysteresis: streak the DOWN watch needs (default:
        ``hysteresis``).  Scale-down is the reversible-but-expensive
        direction, and the tick after a scale-up always samples a
        transient occupancy dip (the newcomer is empty) — an
        aggressive-up policy sets ``hysteresis=1,
        down_hysteresis>=3`` so that dip never even registers as an
        urge, let alone a flap.

    Call :meth:`tick` once per router tick.  Decisions are recorded in
    :attr:`decisions` and published as ``serve.autoscale.*``;
    :attr:`replica_ticks` integrates fleet size over ticks (the
    bench's replica-seconds numerator).
    """

    def __init__(self, router, engine_factory: Callable[[], object],
                 registry=None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 up_depth: Optional[float] = None,
                 up_drift: Optional[float] = None,
                 down_occ: Optional[float] = None,
                 hysteresis: Optional[int] = None,
                 cooldown_ticks: Optional[int] = None,
                 down_hysteresis: Optional[int] = None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.incident import Watch
        from chainermn_tpu.observability.metrics import (
            registry as global_registry,
        )

        self.router = router
        self.engine_factory = engine_factory
        lo, hi = scale_bounds_from_env()
        self.min_replicas = lo if min_replicas is None else max(
            1, int(min_replicas)
        )
        self.max_replicas = hi if max_replicas is None else max(
            self.min_replicas, int(max_replicas)
        )
        self.up_depth = (
            scale_up_depth_from_env() if up_depth is None else up_depth
        )
        self.up_drift = (
            scale_up_drift_from_env() if up_drift is None else up_drift
        )
        self.down_occ = (
            scale_down_occ_from_env() if down_occ is None else down_occ
        )
        h = (
            scale_hysteresis_from_env() if hysteresis is None
            else max(1, int(hysteresis))
        )
        dh = h if down_hysteresis is None else max(1, int(down_hysteresis))
        self.cooldown_ticks = (
            scale_cooldown_from_env() if cooldown_ticks is None
            else max(0, int(cooldown_ticks))
        )
        #: The scaling policy AS watch rules — the PR-12 grammar judges
        #: the signals (compiled predicate + hysteresis streak), this
        #: controller only acts on the verdicts.  +1 = wants a replica,
        #: −1 = can spare one.
        self.watches = [
            (Watch(
                "autoscale_up_backlog", "serve.router.queue_depth",
                f"> {self.up_depth:g}", hysteresis=h,
                description="arrived requests held back fleet-wide — "
                            "the scale-out signal",
            ), +1),
            (Watch(
                "autoscale_up_slo", "serve.slo.p95_drift",
                f"> {self.up_drift:g}", hysteresis=h,
                description="worst replica's rolling p95 left the SLO "
                            "envelope",
            ), +1),
            (Watch(
                "autoscale_down_idle", "serve.slot_occupancy",
                f"< {self.down_occ:g}", hysteresis=dh,
                description="mean fleet occupancy low with an empty "
                            "router queue — capacity to spare",
            ), -1),
        ]
        self._streak = {w.name: 0 for w, _ in self.watches}
        self._cooldown_left = 0
        self._last_direction = 0
        self._ticks = 0
        #: Σ up-replica count per tick — replica-seconds on the shared
        #: scheduler clock's tick grid (a draining replica still costs
        #: a machine, so it counts until deregistration).
        self.replica_ticks = 0
        self.flaps = 0
        #: [{"tick", "action", "replica", "reason"}] audit trail.
        self.decisions: List[dict] = []
        if registry is None and not _obs.enabled():
            noop = _NoopInstrument()
            self._m_replicas = self._m_up = self._m_down = noop
            self._m_flap = noop
        else:
            reg = registry if registry is not None else global_registry()
            self._m_replicas = reg.gauge("serve.autoscale.replicas")
            self._m_up = reg.counter("serve.autoscale.scale_up")
            self._m_down = reg.counter("serve.autoscale.scale_down")
            self._m_flap = reg.counter("serve.autoscale.flap")
        self._m_replicas.set(len(self._up_replicas()))

    # ----------------------------------------------------------- signals
    def _up_replicas(self) -> List[int]:
        r = self.router
        return [
            i for i in range(len(r.schedulers))
            if r.schedulers[i] is not None and r.health.is_up(i)
        ]

    def _signals(self) -> dict:
        """The three live signals, fleet-aggregated: arrived router
        backlog, mean up-replica occupancy, worst-replica SLO drift
        (``None`` when no replica has published one — an absent signal
        never fires, the Watch contract)."""
        r = self.router
        now = r.clock.now()
        depth = float(sum(
            1 for q in r.queued_requests() if q.arrival <= now
        ))
        ups = self._up_replicas()
        occ = (
            sum(r._occupancy(i) for i in ups) / len(ups) if ups else None
        )
        drifts = []
        for i in ups:
            inst = r.replica_registries[i].peek("serve.slo.p95_drift")
            if inst is not None and inst.value is not None:
                drifts.append(float(inst.value))
        return {
            "serve.router.queue_depth": depth,
            "serve.slot_occupancy": occ,
            "serve.slo.p95_drift": max(drifts) if drifts else None,
        }

    # ------------------------------------------------------------ control
    def tick(self) -> Optional[dict]:
        """One control-loop evaluation.  Returns the action record when
        the fleet changed size, else ``None``."""
        self._ticks += 1
        self.replica_ticks += len(self._up_replicas())
        sig = self._signals()
        direction = 0
        reason = None
        for w, d in self.watches:
            v = sig.get(w.metric)
            if v is not None and w._fn(v):
                self._streak[w.name] += 1
            else:
                self._streak[w.name] = 0
            if self._streak[w.name] >= w.hysteresis:
                # Scale-up outranks scale-down (latency beats savings);
                # watch order encodes the priority.
                if direction == 0 or (direction < 0 and d > 0):
                    direction, reason = d, w.name
        if direction < 0 and sig["serve.router.queue_depth"] > 0:
            # Never retire capacity while anything waits fleet-wide.
            direction, reason = 0, None
        in_cooldown = self._cooldown_left > 0
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
        if direction == 0:
            self._m_replicas.set(len(self._up_replicas()))
            return None
        if in_cooldown:
            if self._last_direction and direction != self._last_direction:
                # Direction reversed within cooldown: the flap the
                # damping exists to absorb.  Counted (the critical
                # ``scale_flap`` default rule watches this), suppressed.
                self.flaps += 1
                self._m_flap.inc()
            return None
        action = self._act(direction, reason)
        self._m_replicas.set(len(self._up_replicas()))
        return action

    def _act(self, direction: int, reason: str) -> Optional[dict]:
        n = len(self._up_replicas())
        if direction > 0:
            if n >= self.max_replicas:
                return None
            i = self.router.add_replica(self.engine_factory())
            self._m_up.inc()
            action = "scale_up"
        else:
            if n <= self.min_replicas:
                return None
            i = self._coldest()
            if i is None:
                return None
            self.router.drain_replica(i)
            self.router.deregister_replica(i)
            self._m_down.inc()
            action = "scale_down"
        self._last_direction = direction
        self._cooldown_left = self.cooldown_ticks
        rec = {
            "tick": self._ticks, "action": action, "replica": i,
            "reason": reason,
        }
        self.decisions.append(rec)
        for name in self._streak:
            self._streak[name] = 0
        return rec

    def _coldest(self) -> Optional[int]:
        """The scale-down victim: the least-loaded FULL-TRUST live
        admitting replica (a probation newcomer is never the victim —
        retiring what was just added is the flap this controller
        damps), keeping at least one admitting replica."""
        r = self.router
        admitting = [i for i in r._admitting if r.health.can_admit(i)]
        cands = [i for i in admitting if r.health.state(i) == "live"]
        if not cands or len(admitting) <= 1:
            return None
        return min(cands, key=r._load)


# --------------------------------------------------------- RollingDeploy
class RollingDeploy:
    """Zero-downtime rolling deploy over the router's elastic seams.

    Replaces every replica that is LIVE at construction, one at a
    time: fence → drain (live slots hand off over cmn-kvmig-1, queue
    re-dispatches — zero loss) → retire (orderly, not a counted
    failure) → revive with a new-version engine behind probation.
    Health gate: the replaced replica must graduate probation (state
    ``live`` again) before the next is touched.

    ``engine_factory`` builds the replacement engine; when ``params``
    is given (checkpointer-loaded "new model version" weights) it is
    called as ``engine_factory(params=params)``, else ``()``.

    A replica that dies mid-rollout — the one in flight, or one still
    waiting its turn — PAUSES the rollout (:attr:`paused`) and files a
    critical ``rollout_interrupted`` incident; :meth:`resume` continues
    once an operator revived it.  A step stuck longer than
    ``timeout_ticks`` (``CMN_SERVE_ROLLOUT_TIMEOUT_TICKS``) counts
    ``serve.rollout.stalled`` once, which the critical
    ``rollout_stalled`` default rule turns into an incident.

    Drive :meth:`tick` once per router tick; :attr:`done` reports
    completion, :attr:`replaced` the replica order.
    """

    def __init__(self, router, engine_factory: Callable[..., object],
                 params=None, registry=None,
                 timeout_ticks: Optional[int] = None,
                 incidents=None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.metrics import (
            registry as global_registry,
        )

        self.router = router
        self.engine_factory = engine_factory
        self.params = params
        self.timeout_ticks = (
            rollout_timeout_from_env() if timeout_ticks is None
            else max(1, int(timeout_ticks))
        )
        self.incidents = (
            incidents if incidents is not None else router.incidents
        )
        #: replicas still awaiting replacement, in index order.
        self.pending: List[int] = [
            i for i in range(len(router.schedulers))
            if router.schedulers[i] is not None
            and router.health.state(i) == "live"
        ]
        #: the replica currently in probation, awaiting graduation.
        self.current: Optional[int] = None
        self.replaced: List[int] = []
        self.paused = False
        self._step_ticks = 0
        self._stalled = False
        if registry is None and not _obs.enabled():
            noop = _NoopInstrument()
            self._m_replaced = self._m_inprog = self._m_stalled = noop
        else:
            reg = registry if registry is not None else global_registry()
            self._m_replaced = reg.counter("serve.rollout.replaced")
            self._m_inprog = reg.gauge("serve.rollout.in_progress")
            self._m_stalled = reg.counter("serve.rollout.stalled")
        self._m_inprog.set(1.0 if self.pending else 0.0)

    @property
    def done(self) -> bool:
        return (
            not self.paused and self.current is None and not self.pending
        )

    def resume(self) -> None:
        """Operator acknowledgment after a mid-rollout death: continue
        with the remaining replicas (the dead one is the revival
        machinery's problem; if it was still pending it will be
        re-checked at its turn)."""
        self.paused = False
        self._m_inprog.set(0.0 if self.done else 1.0)

    def _pause(self, replica: int, why: str) -> None:
        self.paused = True
        self._m_inprog.set(0.0)
        if self.incidents is not None:
            try:
                self.incidents.file_incident(
                    "rollout_interrupted", severity="critical",
                    plane="serving",
                    detail={
                        "replica": replica, "why": why,
                        "replaced": list(self.replaced),
                        "pending": list(self.pending),
                    },
                )
            except Exception:  # pragma: no cover - incident I/O best-effort
                pass

    def tick(self) -> None:
        """One rollout step evaluation (call once per router tick)."""
        if self.paused or self.done:
            return
        health = self.router.health
        if self.current is not None:
            i = self.current
            st = health.state(i)
            if st == "dead":
                # The replacement died before graduating — stop the
                # rollout rather than march the fleet down.
                self._pause(i, "replacement died in probation")
                return
            if st != "live":
                self._step_ticks += 1
                if self._step_ticks > self.timeout_ticks \
                        and not self._stalled:
                    self._stalled = True
                    self._m_stalled.inc()
                return
            # Graduated — the health gate opens for the next replica.
            self.replaced.append(i)
            self._m_replaced.inc()
            self.current = None
            self._step_ticks = 0
            self._stalled = False
        while self.pending:
            i = self.pending.pop(0)
            st = health.state(i)
            if st == "dead":
                self._pause(i, "replica died awaiting its rollout turn")
                return
            if st not in ("live", "probation"):
                # Scaled away (draining/removed) while waiting — no
                # longer ours to replace.
                continue
            self.router.drain_replica(i)
            if health.state(i) == "dead":
                # Crashed during its own drain (the fault boundary
                # already harvested it) — pause, same discipline.
                self._pause(i, "replica crashed during rollout drain")
                return
            self.router.retire_replica(i)
            self.router.revive_replica(i, self._new_engine())
            self.current = i
            self._step_ticks = 0
            return
        self._m_inprog.set(0.0 if self.done else 1.0)

    def _new_engine(self):
        if self.params is not None:
            return self.engine_factory(params=self.params)
        return self.engine_factory()
