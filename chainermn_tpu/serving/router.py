"""Multi-replica serving router: N engines × M chips behind one queue.

The second layer of the pod-scale story (ROADMAP item 1): one sharded
engine spans chips, and the :class:`Router` puts N such engines behind
**least-loaded dispatch** so the fleet serves one request stream.  Each
replica is a full :class:`~chainermn_tpu.serving.Scheduler` over its own
:class:`~chainermn_tpu.serving.DecodeEngine` (its own device group, pool,
prefix trie) plus its OWN metrics registry and span ring — the router is
deliberately thin host-side glue:

* **Dispatch** reads each replica's LIVE gauges — ``serve.slot_occupancy``
  and ``serve.queue_depth`` for load, ``mem.kv.occupancy`` as the
  tie-break — exactly the signals every replica already publishes (PR 6/8);
  the router adds only a count of its own dispatches since the gauges
  last refreshed, so a burst between ticks still spreads.
* **Backpressure** is per-replica admission: a replica whose queue is at
  ``max_queue`` (``CMN_ROUTER_MAX_QUEUE``, default ``2 × capacity``)
  takes no new work; when EVERY replica is saturated the request waits in
  the router's own holdback queue (``serve.router.queue_depth`` — the
  autoscaling signal, watched by the incident plane's ``router_backlog``
  rule).  Nothing is ever dropped: holdback drains the moment any replica
  dips below its cap.
* **Rebalance** (``CMN_ROUTER_REBALANCE``, default on): when one replica
  has arrived work queued behind full slots while another sits idle, the
  router *steals* the youngest queued entry and resubmits it to the idle
  replica — carried tokens and accounting ride along
  (:meth:`Scheduler.steal_queued` / :meth:`Scheduler.submit_entry`).
  A migrated request's lifecycle spans therefore land on BOTH replicas'
  span rings, and :meth:`Router.export_fleet_trace` merges the per-replica
  rings through the PR-8 fleet pipeline (one replica = one "rank"/pid in
  the Perfetto trace), so one request's life is visible across replicas.

Clock: all replicas share ONE scheduler clock, so cross-replica
timestamps (and the merged trace) are coherent and idle gaps skip once
for the whole fleet.

Everything here is host-side: the router never touches a device buffer —
its cost per tick is a few gauge reads and list operations, measured by
``serve.router.dispatch_ms``.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

from chainermn_tpu.observability.metrics import (
    MetricsRegistry,
    NoopInstrument as _NoopInstrument,
)
from chainermn_tpu.serving.kv_pool import PoolExhausted
from chainermn_tpu.serving.scheduler import (
    Completion,
    Request,
    Scheduler,
    _Clock,
    _QueueEntry,
    terminal_completion,
)


class Router:
    """Least-loaded dispatch over N scheduler replicas.

    Args:
      engines: one :class:`~chainermn_tpu.serving.DecodeEngine` per
        replica (each already placed — its own mesh or pinned device).
        Replicas are assumed geometry-homogeneous: any replica's
        :meth:`Scheduler.check_fit` gate speaks for all.
      registry: where the ``serve.router.*`` family publishes.  Same
        contract as the Scheduler: an explicit registry always
        publishes; ``None`` rides the ``CMN_OBS`` master switch on the
        ambient global registry.  (Each REPLICA always gets its own
        private :class:`MetricsRegistry` regardless — the router's
        dispatch signals must exist even with observability off, and
        per-replica instruments must not collide in one registry.)
      clock: injectable shared clock (tests/benchmarks).
      max_queue: per-replica admission cap (requests queued at one
        replica).  Default ``CMN_ROUTER_MAX_QUEUE``, else
        ``2 × capacity``.
      rebalance: steal queued work from a blocked replica for an idle
        one.  Default ``CMN_ROUTER_REBALANCE`` (on).
      roles: optional per-replica role (``"mixed"`` | ``"prefill"`` |
        ``"decode"``, default all mixed) — the disaggregated fleet's
        dispatch rule (ISSUE 14): fresh requests go only to admitting
        replicas (mixed/prefill), and rebalance steals only between
        them; ``"decode"`` replicas take migrated slots through the
        :mod:`~chainermn_tpu.serving.disagg` plane, never the router
        queue.  Resolve a launch-wide spec with
        :func:`~chainermn_tpu.serving.disagg.roles_from_env`
        (``CMN_DISAGG_ROLES``).
    """

    def __init__(self, engines: Sequence, registry=None,
                 clock: Optional[_Clock] = None,
                 max_queue: Optional[int] = None,
                 rebalance: Optional[bool] = None,
                 roles: Optional[Sequence[str]] = None,
                 faults: Optional[Sequence] = None,
                 fault=None,
                 retry_budget: Optional[int] = None,
                 probation_ticks: Optional[int] = None,
                 shed_depth: Optional[int] = None,
                 ledger=None, policy=None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.metrics import (
            DEFAULT_MS_EDGES,
            registry as global_registry,
        )
        from chainermn_tpu.observability.tracing import (
            RequestTimeline,
            SpanRing,
        )
        from chainermn_tpu.resilience import faults as _faults
        from chainermn_tpu.serving.recovery import (
            FleetHealth,
            shed_depth_from_env,
        )

        engines = list(engines)
        if not engines:
            raise ValueError("router needs at least one engine")
        if roles is None:
            roles = ["mixed"] * len(engines)
        roles = [str(r) for r in roles]
        if len(roles) != len(engines):
            raise ValueError(
                f"roles ({len(roles)}) must match engines ({len(engines)})"
            )
        from chainermn_tpu.serving.disagg import ROLES as _ROLES

        for r in roles:
            if r not in _ROLES:
                raise ValueError(f"unknown role {r!r} (one of {_ROLES})")
        if all(r == "decode" for r in roles):
            raise ValueError(
                "every replica is decode-role — nobody can admit; a "
                "disaggregated fleet needs >= 1 mixed/prefill replica"
            )
        self.roles = roles
        #: replica indices fresh requests may be dispatched to.
        self._admitting = [
            i for i, r in enumerate(roles) if r != "decode"
        ]
        self.clock = clock or _Clock()
        #: per-replica span rings: each replica is one "rank" in the
        #: merged fleet trace (the timeline mirrors every lifecycle
        #: event as a ``serve.<kind>`` span carrying ``req=<id>``).
        self.rings = [SpanRing(4096) for _ in engines]
        self.replica_registries = [MetricsRegistry() for _ in engines]
        if faults is None:
            faults = [None] * len(engines)
        faults = list(faults)
        if len(faults) != len(engines):
            raise ValueError(
                f"faults ({len(faults)}) must match engines "
                f"({len(engines)})"
            )
        #: Usage ledger (ISSUE 16): ONE fleet ledger shared by every
        #: replica (revivals included), so a request migrated or
        #: harvested across replicas keeps one record and per-tenant
        #: sums stay fleet-coherent.  Explicit wins; otherwise
        #: construction follows the router's own publishing latch
        #: (explicit registry always, ``None`` rides the ``CMN_OBS``
        #: master switch) gated by ``CMN_OBS_LEDGER``.  The resolved
        #: decision is FORCED onto every replica (``False`` = off) —
        #: a replica must never self-build a private ledger the fleet
        #: books would then miss.
        from chainermn_tpu.observability import ledger as _oledger

        if ledger is not None:
            self.ledger = ledger
        elif (registry is not None or _obs.enabled()) \
                and _oledger.ledger_enabled():
            self.ledger = _oledger.CostLedger(registry=registry)
        else:
            self.ledger = None
        #: Multi-tenant policy plane (ISSUE 19): ONE fleet plane shared
        #: by the router's own dispatch pick and every replica (revivals
        #: and scale-ups included), so the fair-share clocks, rate
        #: limits and prefix quotas are fleet-coherent — exactly the
        #: shared-ledger discipline.  ``fleet`` flips so replicas defer
        #: the per-tenant queue-depth census to the router's fleet-wide
        #: one.  None keeps FIFO dispatch bit-for-bit.
        self.policy = policy
        if policy is not None:
            policy.fleet = True
        self.schedulers: List[Scheduler] = [
            Scheduler(
                eng, registry=reg, clock=self.clock,
                timeline=RequestTimeline(ring=ring), fault=fi,
                ledger=(
                    self.ledger if self.ledger is not None else False
                ),
                policy=policy,
            )
            for eng, reg, ring, fi in zip(
                engines, self.replica_registries, self.rings, faults
            )
        ]
        if max_queue is None:
            env = os.environ.get("CMN_ROUTER_MAX_QUEUE", "")
            max_queue = (
                int(env) if env.isdigit() and int(env) > 0
                else 2 * max(e.capacity for e in engines)
            )
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.rebalance = (
            rebalance if rebalance is not None
            else os.environ.get("CMN_ROUTER_REBALANCE", "1") != "0"
        )
        #: router holdback queue (FIFO by submission; the traffic
        #: generators submit in arrival order, same as the Scheduler).
        self._queue: List[Request] = []
        #: request id -> replica indices it was dispatched to, in order
        #: (len > 1 = migrated) — the dispatch audit trail tests and
        #: benchmarks read.
        self.assignments: Dict[int, List[int]] = {}
        #: dispatches since each replica's gauges last refreshed — the
        #: burst corrector added onto the gauge-read load score.
        self._since_gauge = [0] * len(engines)
        #: per-replica occupancy accumulation (benchmark's spread
        #: headline: mean occupancy per replica over the run).
        self._occ_sum = [0.0] * len(engines)
        self._occ_n = 0
        #: host-side dispatch latencies, ms (the histogram's raw feed;
        #: kept for the benchmark's percentile report).
        self.dispatch_ms: List[float] = []
        self._ticks = 0
        enabled = _obs.enabled()
        if registry is None and not enabled:
            noop = _NoopInstrument()
            self._m_disp = self._m_migr = self._m_bp = noop
            self._m_rq = self._m_spread = self._m_disp_ms = noop
            health_reg = None
        else:
            reg = registry if registry is not None else global_registry()
            self._m_disp = reg.counter("serve.router.dispatched")
            self._m_migr = reg.counter("serve.router.migrated")
            self._m_bp = reg.counter("serve.router.backpressure")
            self._m_rq = reg.gauge("serve.router.queue_depth")
            self._m_spread = reg.gauge("serve.router.occupancy_spread")
            self._m_disp_ms = reg.histogram(
                "serve.router.dispatch_ms", edges=DEFAULT_MS_EDGES
            )
            health_reg = reg
        #: The failure plane (ISSUE 15): per-replica live/probation/dead
        #: state + the serve.health.* instruments; the fault boundary in
        #: :meth:`tick` drives it.
        self.health = FleetHealth(
            len(engines), registry=health_reg,
            retry_budget=retry_budget, probation_ticks=probation_ticks,
        )
        #: router-level fault hook — the recovery re-dispatch path is a
        #: ``migrate`` fault site (``drop@migrate`` loses one re-dispatch
        #: "frame"; the entry stays router-held, is detected immediately
        #: and retried — the chaos harness's wire-loss arm).
        self._fault = (
            fault if fault is not None else _faults.process_injector()
        )
        self.shed_depth = (
            shed_depth if shed_depth is not None else shed_depth_from_env()
        )
        #: terminal completions the ROUTER produced (poisoned requests
        #: quarantined at the fault boundary, shed overflow) — replicas
        #: never saw these finish, so they live here and merge in
        #: :attr:`completions`.
        self._router_completions: List[Completion] = []
        #: harvested entries waiting for a survivor (only while NO live
        #: full-trust replica can take them; drained first each tick).
        self._recovered: List = []
        #: Incident plane: same resolution as the Scheduler — the
        #: process manager rides the ambient-registry publishing
        #: decision (an explicit registry's gauges live where the
        #: process rules cannot see them); evaluated on a tick cadence
        #: + once at finish, so a sustained ``serve.router.queue_depth``
        #: backlog trips the ``router_backlog`` default rule.
        if registry is None and enabled:
            from chainermn_tpu.observability import incident as _oincident

            self.incidents = _oincident.manager()
        else:
            self.incidents = None
        self._inc_every = 16

    # ---------------------------------------------------------- dispatch
    @property
    def replicas(self) -> int:
        return len(self.schedulers)

    def submit(self, req: Request) -> None:
        """Accept a request into the router queue.  Geometry-validated
        per replica: a replica whose pool cannot EVER hold the request
        (heterogeneous fleets — ``PoolExhausted`` from its
        ``check_fit``) is that replica's problem, not grounds for
        refusing a request another replica can serve; the submit
        raises only when NO admitting replica fits it."""
        err = None
        for i in self._admitting:
            try:
                self.schedulers[i].check_fit(req)
                self._queue.append(req)
                if self.ledger is not None:
                    # The record opens when the fleet ACCEPTS the
                    # request — a later shed/poison terminal still
                    # finalizes it (conservation counts holdback too).
                    self.ledger.begin(req, self.clock.now())
                return
            except PoolExhausted as e:
                err = e
        raise err if err is not None else RuntimeError(
            "router has no admitting replica"
        )

    def _fits(self, i: int, req: Request) -> bool:
        try:
            self.schedulers[i].check_fit(req)
            return True
        except PoolExhausted:
            return False

    def _gauge(self, i: int, name: str):
        inst = self.replica_registries[i].peek(name)
        v = inst.value if inst is not None else None
        return None if v is None else float(v)

    def _load(self, i: int) -> float:
        """Replica load score off the LIVE gauges: occupied slots plus
        queued requests, per slot of capacity, with the KV-pool
        occupancy gauge as the fractional tie-break (two equally busy
        replicas — prefer the one with more free pool).  Gauges refresh
        once per tick, so the router adds its own dispatches since the
        last refresh on top; before a replica's FIRST tick (cold start
        — gauges never published) the scheduler's host-side truth
        stands in, and already includes every dispatch."""
        s = self.schedulers[i]
        cap = s.engine.capacity
        occ = self._gauge(i, "serve.slot_occupancy")
        qd = self._gauge(i, "serve.queue_depth")
        if occ is None or qd is None:
            occ, qd = s.slot_occupancy, float(s.queue_depth)
        else:
            qd += self._since_gauge[i]
        kv = self._gauge(i, "mem.kv.occupancy") or 0.0
        return (occ * cap + qd) / cap + 0.1 * kv

    def _admit_candidates(self) -> List[int]:
        """Admitting-role replicas that may take FRESH work: live or
        probation only — dead replicas take nothing, and a DRAINING
        replica (mid-scale-down / mid-rollout) is fenced even though
        its tick loop still runs (ISSUE 17)."""
        return [i for i in self._admitting if self.health.can_admit(i)]

    def _ranked_replicas(self, probation_ok: bool = True) -> List[int]:
        """Dispatch candidates (admitting, up, with admission headroom)
        ranked least-loaded first.  Probation replicas carry a flat
        load penalty — the reduced-weight half of the circuit breaker:
        they receive fresh work only when every full-trust replica is
        busier — and are excluded entirely for recovered work
        (``probation_ok=False``)."""
        ranked = []
        for i in self._admit_candidates():
            s = self.schedulers[i]
            probation = self.health.in_probation(i)
            if probation and not probation_ok:
                continue
            # queue_depth is LIVE (submit appends immediately), so it
            # already counts this tick's dispatches — _since_gauge is
            # only for correcting the stale gauges in _load.
            if s.queue_depth >= self.max_queue:
                continue
            ranked.append((self._load(i) + (1.0 if probation else 0.0), i))
        ranked.sort()
        return [i for _, i in ranked]

    def _dispatch(self) -> bool:
        """Move every ARRIVED router-queue request to the least-loaded
        replica, FIFO; stop at the first backpressure refusal (order
        preservation) or future arrival.  A replica-side
        ``PoolExhausted`` is that replica's problem: it is excluded for
        this pick and the next candidate tried."""
        progressed = self._drain_recovered()
        now = self.clock.now()
        while self._queue:
            if self.policy is None:
                if self._queue[0].arrival > now:
                    break
                qi = 0
            else:
                # Weighted-fair dispatch (ISSUE 19): the holdback pick
                # runs on the same fleet plane the replicas consult, so
                # the order work LEAVES the router already honors the
                # fair-share clocks (per-tenant FIFO within a tenant).
                # None = nothing arrived, or every arrived tenant is
                # rate-throttled this instant — both wait here.
                qi = self.policy.pick_index(self._queue, now)
                if qi is None:
                    break
            t0 = time.perf_counter()
            ranked = self._ranked_replicas()
            if not ranked:
                # Fleet-wide backpressure: the request WAITS here (and
                # is never lost) — count the deferral, surface depth.
                self._m_bp.inc()
                break
            req = self._queue[qi]
            placed = None
            misfit = None
            for i in ranked:
                try:
                    self.schedulers[i].submit(req)
                except PoolExhausted as e:
                    misfit = e
                    continue
                placed = i
                break
            if placed is None:
                # Every candidate's POOL GEOMETRY refuses this request
                # (check_fit is occupancy-blind).  If a currently-
                # saturated replica could fit it, wait for headroom
                # (backpressure); if nobody up can EVER fit it, the
                # request is terminal — quarantine, never a router
                # abort and never an infinite holdback.
                if any(
                    self._fits(i, req)
                    for i in self._admit_candidates() if i not in ranked
                ):
                    self._m_bp.inc()
                    break
                self._queue.pop(qi)
                self._terminal_request(
                    req, "poisoned",
                    error=f"PoolExhausted: {misfit}",
                )
                self.health.m_poisoned.inc()
                if self.incidents is not None:
                    self.incidents.evaluate()
                progressed = True
                continue
            self._queue.pop(qi)
            self.assignments.setdefault(req.id, []).append(placed)
            self._since_gauge[placed] += 1
            ms = (time.perf_counter() - t0) * 1e3
            self.dispatch_ms.append(ms)
            self._m_disp.inc()
            self._m_disp_ms.observe(ms)
            progressed = True
        if self._shed_overflow(now):
            progressed = True
        self._m_rq.set(len(self._queue))
        return progressed

    def _shed_overflow(self, now: float) -> bool:
        """Load shedding (``CMN_ROUTER_SHED_DEPTH``): when surviving
        capacity leaves more than ``shed_depth`` ARRIVED requests in
        the holdback queue, refuse the newest-arrived
        (``status="shed"``) — bounded queues instead of unbounded
        latency collapse.  0 (the default) disables shedding; future
        arrivals never count (they are not waiting yet).

        Per-tenant depths (ISSUE 19): a policy tenant with its own
        ``shed_depth`` gets the same newest-first discipline applied to
        ITS arrived backlog alone — a bursty tenant's overflow sheds at
        its cap without the fleet cap ever engaging, and without
        another tenant's requests counting against it."""
        progressed = False
        if self.policy is not None:
            for tenant in sorted({r.tenant for r in self._queue}):
                depth = self.policy.shed_depth(tenant)
                if not depth:
                    continue
                t_arrived = [
                    r for r in self._queue
                    if r.tenant == tenant and r.arrival <= now
                ]
                if len(t_arrived) <= depth:
                    continue
                victims = sorted(
                    t_arrived, key=lambda r: r.arrival
                )[depth:]
                shed_ids = {id(v) for v in victims}
                self._queue = [
                    r for r in self._queue if id(r) not in shed_ids
                ]
                for req in sorted(victims, key=lambda r: -r.arrival):
                    self._terminal_request(
                        req, "shed",
                        error=f"tenant {tenant!r} holdback depth > "
                              f"{depth}",
                    )
                    self.health.m_shed.inc()
                progressed = True
        if not self.shed_depth:
            return progressed
        arrived = [r for r in self._queue if r.arrival <= now]
        if len(arrived) <= self.shed_depth:
            return progressed
        victims = sorted(arrived, key=lambda r: r.arrival)[
            self.shed_depth:
        ]
        shed_ids = {id(v) for v in victims}
        self._queue = [r for r in self._queue if id(r) not in shed_ids]
        for req in sorted(victims, key=lambda r: -r.arrival):
            self._terminal_request(
                req, "shed",
                error=f"holdback depth > {self.shed_depth} with "
                      "surviving capacity saturated",
            )
            self.health.m_shed.inc()
        return True

    def _terminal_request(self, req: Request, status: str,
                          error: Optional[str] = None) -> None:
        """A never-admitted router-queue request terminates here (shed,
        or unservable-anywhere): one definite Completion."""
        now = self.clock.now()
        comp = terminal_completion(
            _QueueEntry(req=req), status, now, error=error,
        )
        if self.ledger is not None:
            comp.usage = self.ledger.finalize(req.id, status, now)
        self._router_completions.append(comp)

    def _rebalance(self) -> bool:
        """Steal arrived queued work from a replica whose slots are all
        busy for a replica with a free slot and an empty queue."""
        if not self.rebalance:
            return False
        # Role discipline holds under rebalance too: a decode replica's
        # free slots belong to the migration plane, and its queue (if a
        # drain ever filled one) is recompute work another decode
        # replica could not prefill faster anyway.  Health discipline:
        # only full-trust LIVE replicas steal (a probation replica gets
        # fresh admissions only — the circuit breaker), dead replicas
        # neither donate (harvested already) nor receive, and a
        # DRAINING replica sits out both sides — the drain is the one
        # mover of its work (ISSUE 17).
        idle = [
            i for i in self._admitting
            if self.health.state(i) == "live"
            and self.schedulers[i].has_free_slot
            and self.schedulers[i].queue_depth == 0
        ]
        if not idle:
            return False
        donors = sorted(
            (
                i for i in self._admitting
                if self.health.can_admit(i)
                and self.schedulers[i].queue_depth > 0
                and not self.schedulers[i].has_free_slot
            ),
            key=lambda i: -self.schedulers[i].queue_depth,
        )
        moved = False
        for dst in idle:
            for src in donors:
                if src == dst:
                    continue
                entry = self.schedulers[src].steal_queued()
                if entry is None:
                    continue
                try:
                    self.schedulers[dst].check_fit(entry.req)
                except PoolExhausted:
                    # The idle replica's pool cannot hold this entry
                    # (heterogeneous fleet) — hand it straight back
                    # (same queue end it was stolen from).
                    self.schedulers[src].submit_entry(entry)
                    continue
                self.schedulers[dst].submit_entry(entry)
                self.assignments.setdefault(
                    entry.req.id, []
                ).append(dst)
                self._m_migr.inc()
                moved = True
                break
        return moved

    # ---------------------------------------------------------- recovery
    def _on_replica_death(self, i: int, exc: BaseException) -> None:
        """The fault boundary (ISSUE 15): replica ``i``'s tick escaped.
        Mark it dead, harvest its queued entries AND live slots into
        recompute entries (carried + generated tokens preserved — the
        eviction-requeue discipline, so survivor continuations are
        greedy-identical), and re-dispatch each to a survivor — unless
        the entry has now killed ``retry_budget`` replicas, in which
        case it is the likely cause and is quarantined as a poisoned
        Completion with the attributed error."""
        err = f"{type(exc).__name__}: {exc}"
        self.health.mark_dead(i, err)
        try:
            entries = self.schedulers[i].harvest_entries()
        except Exception:  # pragma: no cover - defensive harvest
            entries = []
        # Requests this replica FINISHED before dying are history, not
        # casualties — move them to the router's books so a revival
        # (which replaces the scheduler) cannot lose them.
        self._router_completions.extend(self.schedulers[i].completions)
        self.schedulers[i].completions = []
        for entry in entries:
            entry.retries += 1
            entry.last_error = err
            self.health.m_retries.inc()
            if self.ledger is not None:
                # The harvest already settled block occupancy and booked
                # the eviction; the DEATH itself books here.
                self.ledger.book(entry.req.id, "retries", 1)
            if entry.retries >= self.health.retry_budget:
                self._quarantine(entry, err)
            else:
                self._redispatch(entry)
        # Evaluate the incident rules NOW, while the breach is fresh:
        # the critical `replica_dead` (and `poison_request`, when a
        # quarantine happened) default rules capture their bundles at
        # the moment the fleet lost the replica.
        if self.incidents is not None:
            self.incidents.evaluate()

    def _quarantine(self, entry, err: str) -> None:
        now = self.clock.now()
        comp = terminal_completion(entry, "poisoned", now, error=err)
        if self.ledger is not None:
            comp.usage = self.ledger.finalize(
                entry.req.id, "poisoned", now
            )
        self._router_completions.append(comp)
        self.health.m_poisoned.inc()

    def _redispatch(self, entry) -> bool:
        """Re-dispatch one harvested entry to a surviving FULL-TRUST
        replica (probation replicas take only fresh admissions).  This
        is a ``migrate`` fault site: ``drop@migrate`` loses one
        re-dispatch frame on the wire — detected immediately (the
        entry never left the router) and retried on the next path.
        With no survivor able to take it, the entry parks in
        ``_recovered`` and re-tries every dispatch round — recovered
        work is never dropped."""
        candidates = [
            i for i in self._ranked_replicas(probation_ok=False)
            if self._fits(i, entry.req)
        ] or [
            # Every full-trust survivor is at its admission cap:
            # recovered work outranks the cap (it already waited once),
            # so fall back to the least-loaded fitting survivor.
            i for i in sorted(
                (j for j in self._admit_candidates()
                 if not self.health.in_probation(j)),
                key=self._load,
            )
            if self._fits(i, entry.req)
        ]
        for i in candidates:
            if self._fault is not None and \
                    self._fault.hook("migrate") == "drop":
                self.health.m_retries.inc()
                continue
            self.schedulers[i].submit_entry(entry)
            self.assignments.setdefault(entry.req.id, []).append(i)
            self._since_gauge[i] += 1
            self.health.m_recovered.inc()
            return True
        up = self._admit_candidates()
        if up and not any(self._fits(i, entry.req) for i in up):
            # Replicas are UP but none's POOL GEOMETRY can ever hold
            # this entry (a heterogeneous fleet lost the only replica
            # that could) — terminal, the same verdict the fresh-
            # dispatch path reaches: never an infinite park.  With NO
            # up replica at all the entry parks instead: a pending
            # revival is the recovery path, and ``run()`` raises loudly
            # if nobody ever drives one.
            self._quarantine(
                entry,
                "PoolExhausted on every surviving replica"
                + (f" (after {entry.last_error})"
                   if entry.last_error else ""),
            )
            if self.incidents is not None:
                self.incidents.evaluate()
            return True
        self._recovered.append(entry)
        return False

    def _drain_recovered(self) -> bool:
        """Retry parked recovered entries (their survivor may have
        appeared — a revival graduated, or capacity freed)."""
        if not self._recovered:
            return False
        parked, self._recovered = self._recovered, []
        progressed = False
        for entry in parked:
            if self._redispatch(entry):
                progressed = True
        return progressed

    def revive_replica(self, i: int, engine, fault=None) -> None:
        """Re-register a replacement engine for dead replica ``i``
        behind the probation circuit breaker: fresh Scheduler, fresh
        metrics registry, fresh span ring (the old incarnation's books
        are closed — its harvest already moved every request it held).
        The revived replica receives only fresh admissions at reduced
        dispatch weight until ``CMN_SERVE_PROBATION_TICKS`` clean ticks
        pass, so a flapping replica cannot thrash the fleet."""
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.observability.tracing import (
            RequestTimeline,
            SpanRing,
        )

        if self.health.state(i) != "dead":
            raise ValueError(
                f"replica {i} is {self.health.state(i)!r} — only a dead "
                "replica can be revived"
            )
        ring = SpanRing(4096)
        reg = MetricsRegistry()
        self.rings[i] = ring
        self.replica_registries[i] = reg
        self.schedulers[i] = Scheduler(
            engine, registry=reg, clock=self.clock,
            timeline=RequestTimeline(ring=ring), fault=fault,
            ledger=self.ledger if self.ledger is not None else False,
            policy=self.policy,
        )
        self._since_gauge[i] = 0
        self.health.start_probation(i)

    # ------------------------------------------------- elastic (ISSUE 17)
    def add_replica(self, engine, role: str = "mixed",
                    fault=None) -> int:
        """Scale-up: register a NEW replica behind the probation
        circuit breaker — fresh Scheduler, registry and span ring,
        sharing the fleet clock and ledger, earning full trust through
        ``CMN_SERVE_PROBATION_TICKS`` clean ticks exactly like a
        revival replacement (a cold newcomer must not immediately soak
        up recovered work or rebalance steals).  Returns the new
        replica index."""
        from chainermn_tpu.observability.metrics import MetricsRegistry
        from chainermn_tpu.observability.tracing import (
            RequestTimeline,
            SpanRing,
        )
        from chainermn_tpu.serving.disagg import ROLES as _ROLES

        if role not in _ROLES:
            raise ValueError(f"unknown role {role!r} (one of {_ROLES})")
        i = len(self.schedulers)
        ring = SpanRing(4096)
        reg = MetricsRegistry()
        self.rings.append(ring)
        self.replica_registries.append(reg)
        self.roles.append(role)
        self.schedulers.append(Scheduler(
            engine, registry=reg, clock=self.clock,
            timeline=RequestTimeline(ring=ring), fault=fault,
            ledger=self.ledger if self.ledger is not None else False,
            policy=self.policy,
        ))
        self._since_gauge.append(0)
        self._occ_sum.append(0.0)
        self.health.add_replica()
        self.health.start_probation(i)
        if role != "decode":
            self._admitting.append(i)
        return i

    def drain_replica(self, i: int) -> dict:
        """Scale-down / rolling-deploy drain: fence replica ``i``
        (DRAINING — no fresh admissions, no rebalance steals), hand its
        decode-ready slots to the least-loaded full-trust survivor over
        the cmn-kvmig-1 path (``pack_slots``/``install_payload`` — live
        KV moves through the one-variant programs, the survivor never
        recompiles), and re-dispatch its still-prefilling slots and
        queued entries as recompute entries (carried tokens ride
        along, the eviction-requeue discipline).  Nothing is lost; the
        replica ends empty and fenced, ready for
        :meth:`deregister_replica` (scale-down) or
        :meth:`retire_replica` + :meth:`revive_replica` (rollout).

        The slot handoff is a ``migrate`` fault site: ``drop@migrate``
        loses the frame BEFORE any detach, so the slots stay
        source-held and fall back to the recompute path — detected
        immediately, zero loss.  A replica that crashes mid-drain
        downgrades to the fault boundary (:meth:`_on_replica_death`):
        marked dead, work harvested — the terminal invariant holds
        either way."""
        from chainermn_tpu.serving import disagg as _disagg

        if not self.health.is_draining(i):
            self.health.start_draining(i)
        s = self.schedulers[i]
        summary = {
            "replica": i, "slots_migrated": 0, "entries_requeued": 0,
            "dropped_frames": 0,
        }
        try:
            ready = s.ready_slots()
            survivors = [
                j for j in self._admitting
                if j != i and self.health.state(j) == "live"
            ]
            if ready and survivors:
                if self._fault is not None and \
                        self._fault.hook("migrate") == "drop":
                    # Handoff frame lost on the wire — detected here
                    # (nothing detached yet); the slots fall back to
                    # the recompute path below.
                    summary["dropped_frames"] += 1
                    self.health.m_retries.inc()
                else:
                    dest = min(survivors, key=self._load)
                    installed, queued = _disagg.handoff_slots(
                        s, self.schedulers[dest], ready
                    )
                    for slot in ready:
                        self.assignments.setdefault(
                            slot.entry.req.id, []
                        ).append(dest)
                        self._m_migr.inc()
                    self._since_gauge[dest] += installed
                    summary["slots_migrated"] = installed
                    summary["entries_requeued"] += queued
                    summary["dest"] = dest
        except Exception as exc:
            self._on_replica_death(i, exc)
            summary["crashed"] = f"{type(exc).__name__}: {exc}"
            return summary
        for entry in s.harvest_entries():
            summary["entries_requeued"] += 1
            self._redispatch(entry)
        s.finish()
        return summary

    def retire_replica(self, i: int) -> None:
        """Rolling-deploy seam: a DRAINED replica steps aside (state
        ``dead``, orderly — not a counted failure) so
        :meth:`revive_replica` can register the new-version engine
        behind probation.  Its finished completions move to the
        router's books first — ``revive_replica`` replaces the
        Scheduler wholesale, and the old incarnation's terminals must
        survive that."""
        s = self.schedulers[i]
        if s is not None and s.pending:
            raise ValueError(
                f"replica {i} still holds work — drain it first"
            )
        if s is not None:
            self._router_completions.extend(s.completions)
            s.completions = []
        self.health.mark_retired(i)

    def deregister_replica(self, i: int) -> None:
        """Scale-down final step: remove a DRAINED (or crashed
        mid-drain, hence dead-and-harvested) replica and fully release
        its state — scheduler (whose weakref'd flight/incident
        providers die with it), span ring, metrics registry, and the
        FleetHealth row (tombstoned ``removed`` so historical indices
        stay stable).  Its finished completions move to the router's
        books first, so :attr:`completions` and the fleet ledger's
        conservation hold across the removal (ISSUE 17 satellite: a
        long-lived fleet that scales down must not leak)."""
        st = self.health.state(i)
        if st not in ("draining", "dead"):
            raise ValueError(
                f"replica {i} is {st!r} — only a draining or dead "
                "replica can be deregistered (drain it first)"
            )
        s = self.schedulers[i]
        if s is not None and s.pending:
            raise ValueError(
                f"replica {i} still holds work — drain it first"
            )
        if s is not None:
            self._router_completions.extend(s.completions)
            s.completions = []
        self.health.remove_replica(i)
        self.schedulers[i] = None
        self.rings[i] = None
        self.replica_registries[i] = None
        self._admitting = [j for j in self._admitting if j != i]

    def queued_requests(self) -> List[Request]:
        """The router holdback queue (oldest first) — chaos-harness /
        dashboard introspection."""
        return list(self._queue)

    # --------------------------------------------------------------- run
    def tick(self) -> bool:
        """One fleet iteration: dispatch arrived requests, tick every
        UP replica inside the fault boundary, rebalance, refresh router
        gauges.  Returns whether anything progressed anywhere.

        The fault boundary (ISSUE 15): an exception escaping a
        replica's tick — a real defect or an injected
        ``crash@serve_step`` — marks THAT replica dead and recovers its
        work onto survivors (:meth:`_on_replica_death`) instead of
        aborting the fleet.  A clean tick feeds the probation counter
        of a revived replica."""
        progressed = self._dispatch()
        for i, s in enumerate(self.schedulers):
            if s is None or not self.health.is_up(i):
                continue
            try:
                if s.tick():
                    progressed = True
            except Exception as exc:
                self._on_replica_death(i, exc)
                progressed = True
            else:
                was_probation = self.health.in_probation(i)
                self.health.clean_tick(i)
                if was_probation and self._recovered:
                    # The countdown toward graduating this replica IS
                    # progress toward serving the parked recovered work
                    # (which only full-trust replicas may take) — an
                    # otherwise-idle fleet must keep ticking it down
                    # rather than declare deadlock.
                    progressed = True
        if self._rebalance():
            progressed = True
        self._since_gauge = [0] * len(self.schedulers)
        occs = [
            self._occupancy(i) for i in range(len(self.schedulers))
        ]
        self._m_spread.set(max(occs) - min(occs))
        for i, o in enumerate(occs):
            self._occ_sum[i] += o
        self._occ_n += 1
        self._ticks += 1
        if self.policy is not None:
            # Fleet-wide per-tenant queue census: holdback + parked
            # recovered work + every UP replica's queue — the
            # ``serve.tenant.<t>.queue_depth`` gauges the starvation
            # rule and dashboards read.
            census = [r.tenant for r in self._queue]
            census += [e.req.tenant for e in self._recovered]
            for j, s in enumerate(self.schedulers):
                if s is not None and self.health.is_up(j):
                    census += [e.req.tenant for e in s._queue]
            self.policy.publish_queue(census)
        if self.incidents is not None and \
                self._ticks % self._inc_every == 0:
            self.incidents.evaluate()
        return progressed

    def _occupancy(self, i: int) -> float:
        """Replica occupancy off the live gauge, falling back to the
        scheduler's host-side truth (a freshly revived replica's
        registry has not published yet; a dead one's gauges are stale
        — its harvested slots are empty, which is what the host truth
        reads)."""
        if self.schedulers[i] is None or not self.health.is_up(i):
            return 0.0
        o = self._gauge(i, "serve.slot_occupancy")
        return o if o is not None else self.schedulers[i].slot_occupancy

    @property
    def pending(self) -> bool:
        return bool(
            self._queue or self._recovered or any(
                s.pending for i, s in enumerate(self.schedulers)
                if s is not None and self.health.is_up(i)
            )
        )

    def run(self, requests: Optional[Sequence[Request]] = None
            ) -> List[Completion]:
        """Submit ``requests`` (optional) and drain the whole fleet.
        Returns every replica's completions, merged (sorted by finish
        time)."""
        for r in requests or ():
            self.submit(r)
        while self.pending:
            if not self.tick():
                if self.policy is None:
                    nxt = [r.arrival for r in self._queue[:1]]
                else:
                    # Policy dispatch can pick ANY queued entry, and a
                    # fully-throttled holdback unblocks at the earliest
                    # rate release, not an arrival — cover both, with
                    # the min arrival as the no-candidate fallback
                    # (parity with the FIFO head).
                    now = self.clock.now()
                    nxt = [
                        r.arrival for r in self._queue
                        if r.arrival > now
                    ]
                    rel = self.policy.next_release(self._queue, now)
                    if rel is not None:
                        nxt.append(rel)
                    if not nxt and self._queue:
                        nxt = [min(r.arrival for r in self._queue)]
                nxt += [
                    t for t in (
                        s.next_arrival()
                        for i, s in enumerate(self.schedulers)
                        if s is not None and self.health.is_up(i)
                    ) if t is not None
                ]
                if not nxt:  # pragma: no cover - defensive
                    raise RuntimeError(
                        "router made no progress with no future "
                        "arrivals (dead replicas un-revived? drive the "
                        "loop yourself — or via recovery.ChaosHarness "
                        "— to revive mid-run)"
                    )
                self.clock.skip_to(min(nxt))
        self.finish()
        return self.completions

    def finish(self) -> None:
        """Close every UP replica's books + the router's own gauges
        (a dead replica's books closed at harvest — its process would
        be gone in a real fleet)."""
        for i, s in enumerate(self.schedulers):
            if s is not None and self.health.is_up(i):
                s.finish()
        self._m_rq.set(len(self._queue))
        self._m_spread.set(0.0)
        if self.incidents is not None:
            self.incidents.evaluate()

    # ------------------------------------------------------ introspection
    @property
    def completions(self) -> List[Completion]:
        """Every replica's completions plus the router's own terminal
        verdicts (poisoned / shed), merged."""
        out: List[Completion] = list(self._router_completions)
        for s in self.schedulers:
            if s is not None:
                out.extend(s.completions)
        return sorted(out, key=lambda c: (c.finished_at, c.id))

    def replica_stats(self) -> List[dict]:
        """Per-replica host-side summary (benchmarks/dashboards).  A
        deregistered replica keeps its row (historical dispatch counts
        stay attributable) but its live state is gone."""
        out = []
        for i, s in enumerate(self.schedulers):
            out.append({
                "replica": i,
                "role": self.roles[i],
                "state": self.health.state(i),
                "dispatched": sum(
                    1 for reps in self.assignments.values()
                    if reps and reps[0] == i
                ),
                "served": sum(
                    1 for reps in self.assignments.values()
                    if reps and reps[-1] == i
                ),
                "completions": len(s.completions) if s is not None else 0,
                "occupancy_mean": (
                    self._occ_sum[i] / self._occ_n if self._occ_n else 0.0
                ),
                "engine": s.engine.stats() if s is not None else None,
            })
        return out

    def export_fleet_trace(self, path: str) -> dict:
        """Merge the per-replica span rings through the PR-8 fleet
        pipeline — one replica = one "rank" (pid) — and write ONE
        Perfetto-loadable trace.  A migrated request's ``serve.*``
        spans (each carrying ``req=<id>`` detail) appear under every
        replica that touched it.  Replicas share one process and one
        monotonic clock, so no offset correction is needed (offsets
        default to zero).  Returns the merge summary (with ``path``)."""
        from chainermn_tpu.observability import fleet as _fleet
        from chainermn_tpu.observability import tracing as _tracing

        dumps = [
            {
                "rank": i,
                "spans": ring.snapshot(),
                "spans_total": ring.total,
                "epoch_wall": _tracing.EPOCH_WALL,
                "epoch_perf": _tracing.EPOCH_PERF,
            }
            for i, ring in enumerate(self.rings)
            if ring is not None  # deregistered replicas released theirs
        ]
        merged = _fleet.merge_fleet_trace(dumps)
        merged["summary"]["path"] = _fleet.write_fleet_trace(
            path, merged["payload"]
        )
        return merged["summary"]
