"""Multi-replica serving router: N engines × M chips behind one queue.

The second layer of the pod-scale story (ROADMAP item 1): one sharded
engine spans chips, and the :class:`Router` puts N such engines behind
**least-loaded dispatch** so the fleet serves one request stream.  Each
replica is a full :class:`~chainermn_tpu.serving.Scheduler` over its own
:class:`~chainermn_tpu.serving.DecodeEngine` (its own device group, pool,
prefix trie) plus its OWN metrics registry and span ring — the router is
deliberately thin host-side glue:

* **Dispatch** reads each replica's LIVE gauges — ``serve.slot_occupancy``
  and ``serve.queue_depth`` for load, ``mem.kv.occupancy`` as the
  tie-break — exactly the signals every replica already publishes (PR 6/8);
  the router adds only a count of its own dispatches since the gauges
  last refreshed, so a burst between ticks still spreads.
* **Backpressure** is per-replica admission: a replica whose queue is at
  ``max_queue`` (``CMN_ROUTER_MAX_QUEUE``, default ``2 × capacity``)
  takes no new work; when EVERY replica is saturated the request waits in
  the router's own holdback queue (``serve.router.queue_depth`` — the
  autoscaling signal, watched by the incident plane's ``router_backlog``
  rule).  Nothing is ever dropped: holdback drains the moment any replica
  dips below its cap.
* **Rebalance** (``CMN_ROUTER_REBALANCE``, default on): when one replica
  has arrived work queued behind full slots while another sits idle, the
  router *steals* the youngest queued entry and resubmits it to the idle
  replica — carried tokens and accounting ride along
  (:meth:`Scheduler.steal_queued` / :meth:`Scheduler.submit_entry`).
  A migrated request's lifecycle spans therefore land on BOTH replicas'
  span rings, and :meth:`Router.export_fleet_trace` merges the per-replica
  rings through the PR-8 fleet pipeline (one replica = one "rank"/pid in
  the Perfetto trace), so one request's life is visible across replicas.

Clock: all replicas share ONE scheduler clock, so cross-replica
timestamps (and the merged trace) are coherent and idle gaps skip once
for the whole fleet.

Everything here is host-side: the router never touches a device buffer —
its cost per tick is a few gauge reads and list operations, measured by
``serve.router.dispatch_ms``.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

from chainermn_tpu.observability.metrics import (
    MetricsRegistry,
    NoopInstrument as _NoopInstrument,
)
from chainermn_tpu.serving.scheduler import (
    Completion,
    Request,
    Scheduler,
    _Clock,
)


class Router:
    """Least-loaded dispatch over N scheduler replicas.

    Args:
      engines: one :class:`~chainermn_tpu.serving.DecodeEngine` per
        replica (each already placed — its own mesh or pinned device).
        Replicas are assumed geometry-homogeneous: any replica's
        :meth:`Scheduler.check_fit` gate speaks for all.
      registry: where the ``serve.router.*`` family publishes.  Same
        contract as the Scheduler: an explicit registry always
        publishes; ``None`` rides the ``CMN_OBS`` master switch on the
        ambient global registry.  (Each REPLICA always gets its own
        private :class:`MetricsRegistry` regardless — the router's
        dispatch signals must exist even with observability off, and
        per-replica instruments must not collide in one registry.)
      clock: injectable shared clock (tests/benchmarks).
      max_queue: per-replica admission cap (requests queued at one
        replica).  Default ``CMN_ROUTER_MAX_QUEUE``, else
        ``2 × capacity``.
      rebalance: steal queued work from a blocked replica for an idle
        one.  Default ``CMN_ROUTER_REBALANCE`` (on).
      roles: optional per-replica role (``"mixed"`` | ``"prefill"`` |
        ``"decode"``, default all mixed) — the disaggregated fleet's
        dispatch rule (ISSUE 14): fresh requests go only to admitting
        replicas (mixed/prefill), and rebalance steals only between
        them; ``"decode"`` replicas take migrated slots through the
        :mod:`~chainermn_tpu.serving.disagg` plane, never the router
        queue.  Resolve a launch-wide spec with
        :func:`~chainermn_tpu.serving.disagg.roles_from_env`
        (``CMN_DISAGG_ROLES``).
    """

    def __init__(self, engines: Sequence, registry=None,
                 clock: Optional[_Clock] = None,
                 max_queue: Optional[int] = None,
                 rebalance: Optional[bool] = None,
                 roles: Optional[Sequence[str]] = None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.metrics import (
            DEFAULT_MS_EDGES,
            registry as global_registry,
        )
        from chainermn_tpu.observability.tracing import (
            RequestTimeline,
            SpanRing,
        )

        engines = list(engines)
        if not engines:
            raise ValueError("router needs at least one engine")
        if roles is None:
            roles = ["mixed"] * len(engines)
        roles = [str(r) for r in roles]
        if len(roles) != len(engines):
            raise ValueError(
                f"roles ({len(roles)}) must match engines ({len(engines)})"
            )
        from chainermn_tpu.serving.disagg import ROLES as _ROLES

        for r in roles:
            if r not in _ROLES:
                raise ValueError(f"unknown role {r!r} (one of {_ROLES})")
        if all(r == "decode" for r in roles):
            raise ValueError(
                "every replica is decode-role — nobody can admit; a "
                "disaggregated fleet needs >= 1 mixed/prefill replica"
            )
        self.roles = roles
        #: replica indices fresh requests may be dispatched to.
        self._admitting = [
            i for i, r in enumerate(roles) if r != "decode"
        ]
        self.clock = clock or _Clock()
        #: per-replica span rings: each replica is one "rank" in the
        #: merged fleet trace (the timeline mirrors every lifecycle
        #: event as a ``serve.<kind>`` span carrying ``req=<id>``).
        self.rings = [SpanRing(4096) for _ in engines]
        self.replica_registries = [MetricsRegistry() for _ in engines]
        self.schedulers: List[Scheduler] = [
            Scheduler(
                eng, registry=reg, clock=self.clock,
                timeline=RequestTimeline(ring=ring),
            )
            for eng, reg, ring in zip(
                engines, self.replica_registries, self.rings
            )
        ]
        if max_queue is None:
            env = os.environ.get("CMN_ROUTER_MAX_QUEUE", "")
            max_queue = (
                int(env) if env.isdigit() and int(env) > 0
                else 2 * max(e.capacity for e in engines)
            )
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.rebalance = (
            rebalance if rebalance is not None
            else os.environ.get("CMN_ROUTER_REBALANCE", "1") != "0"
        )
        #: router holdback queue (FIFO by submission; the traffic
        #: generators submit in arrival order, same as the Scheduler).
        self._queue: List[Request] = []
        #: request id -> replica indices it was dispatched to, in order
        #: (len > 1 = migrated) — the dispatch audit trail tests and
        #: benchmarks read.
        self.assignments: Dict[int, List[int]] = {}
        #: dispatches since each replica's gauges last refreshed — the
        #: burst corrector added onto the gauge-read load score.
        self._since_gauge = [0] * len(engines)
        #: per-replica occupancy accumulation (benchmark's spread
        #: headline: mean occupancy per replica over the run).
        self._occ_sum = [0.0] * len(engines)
        self._occ_n = 0
        #: host-side dispatch latencies, ms (the histogram's raw feed;
        #: kept for the benchmark's percentile report).
        self.dispatch_ms: List[float] = []
        self._ticks = 0
        enabled = _obs.enabled()
        if registry is None and not enabled:
            noop = _NoopInstrument()
            self._m_disp = self._m_migr = self._m_bp = noop
            self._m_rq = self._m_spread = self._m_disp_ms = noop
        else:
            reg = registry if registry is not None else global_registry()
            self._m_disp = reg.counter("serve.router.dispatched")
            self._m_migr = reg.counter("serve.router.migrated")
            self._m_bp = reg.counter("serve.router.backpressure")
            self._m_rq = reg.gauge("serve.router.queue_depth")
            self._m_spread = reg.gauge("serve.router.occupancy_spread")
            self._m_disp_ms = reg.histogram(
                "serve.router.dispatch_ms", edges=DEFAULT_MS_EDGES
            )
        #: Incident plane: same resolution as the Scheduler — the
        #: process manager rides the ambient-registry publishing
        #: decision (an explicit registry's gauges live where the
        #: process rules cannot see them); evaluated on a tick cadence
        #: + once at finish, so a sustained ``serve.router.queue_depth``
        #: backlog trips the ``router_backlog`` default rule.
        if registry is None and enabled:
            from chainermn_tpu.observability import incident as _oincident

            self.incidents = _oincident.manager()
        else:
            self.incidents = None
        self._inc_every = 16

    # ---------------------------------------------------------- dispatch
    @property
    def replicas(self) -> int:
        return len(self.schedulers)

    def submit(self, req: Request) -> None:
        """Accept a request into the router queue (validated against
        one admitting replica's geometry — homogeneous replicas)."""
        self.schedulers[self._admitting[0]].check_fit(req)
        self._queue.append(req)

    def _gauge(self, i: int, name: str):
        inst = self.replica_registries[i].peek(name)
        v = inst.value if inst is not None else None
        return None if v is None else float(v)

    def _load(self, i: int) -> float:
        """Replica load score off the LIVE gauges: occupied slots plus
        queued requests, per slot of capacity, with the KV-pool
        occupancy gauge as the fractional tie-break (two equally busy
        replicas — prefer the one with more free pool).  Gauges refresh
        once per tick, so the router adds its own dispatches since the
        last refresh on top; before a replica's FIRST tick (cold start
        — gauges never published) the scheduler's host-side truth
        stands in, and already includes every dispatch."""
        s = self.schedulers[i]
        cap = s.engine.capacity
        occ = self._gauge(i, "serve.slot_occupancy")
        qd = self._gauge(i, "serve.queue_depth")
        if occ is None or qd is None:
            occ, qd = s.slot_occupancy, float(s.queue_depth)
        else:
            qd += self._since_gauge[i]
        kv = self._gauge(i, "mem.kv.occupancy") or 0.0
        return (occ * cap + qd) / cap + 0.1 * kv

    def _pick_replica(self) -> Optional[int]:
        """Least-loaded ADMITTING replica (decode-role replicas take
        migrated slots, never fresh requests) with admission headroom,
        or ``None`` when every one is at ``max_queue`` (backpressure)."""
        best, best_load = None, None
        for i in self._admitting:
            s = self.schedulers[i]
            # queue_depth is LIVE (submit appends immediately), so it
            # already counts this tick's dispatches — _since_gauge is
            # only for correcting the stale gauges in _load.
            if s.queue_depth >= self.max_queue:
                continue
            load = self._load(i)
            if best_load is None or load < best_load:
                best, best_load = i, load
        return best

    def _dispatch(self) -> bool:
        """Move every ARRIVED router-queue request to the least-loaded
        replica, FIFO; stop at the first backpressure refusal (order
        preservation) or future arrival."""
        progressed = False
        now = self.clock.now()
        while self._queue and self._queue[0].arrival <= now:
            t0 = time.perf_counter()
            best = self._pick_replica()
            if best is None:
                # Fleet-wide backpressure: the request WAITS here (and
                # is never lost) — count the deferral, surface depth.
                self._m_bp.inc()
                break
            req = self._queue.pop(0)
            self.schedulers[best].submit(req)
            self.assignments.setdefault(req.id, []).append(best)
            self._since_gauge[best] += 1
            ms = (time.perf_counter() - t0) * 1e3
            self.dispatch_ms.append(ms)
            self._m_disp.inc()
            self._m_disp_ms.observe(ms)
            progressed = True
        self._m_rq.set(len(self._queue))
        return progressed

    def _rebalance(self) -> bool:
        """Steal arrived queued work from a replica whose slots are all
        busy for a replica with a free slot and an empty queue."""
        if not self.rebalance:
            return False
        # Role discipline holds under rebalance too: a decode replica's
        # free slots belong to the migration plane, and its queue (if a
        # drain ever filled one) is recompute work another decode
        # replica could not prefill faster anyway.
        idle = [
            i for i in self._admitting
            if self.schedulers[i].has_free_slot
            and self.schedulers[i].queue_depth == 0
        ]
        if not idle:
            return False
        donors = sorted(
            (
                i for i in self._admitting
                if self.schedulers[i].queue_depth > 0
                and not self.schedulers[i].has_free_slot
            ),
            key=lambda i: -self.schedulers[i].queue_depth,
        )
        moved = False
        for dst in idle:
            for src in donors:
                if src == dst:
                    continue
                entry = self.schedulers[src].steal_queued()
                if entry is None:
                    continue
                self.schedulers[dst].submit_entry(entry)
                self.assignments.setdefault(
                    entry.req.id, []
                ).append(dst)
                self._m_migr.inc()
                moved = True
                break
        return moved

    # --------------------------------------------------------------- run
    def tick(self) -> bool:
        """One fleet iteration: dispatch arrived requests, tick every
        replica, rebalance, refresh router gauges.  Returns whether
        anything progressed anywhere."""
        progressed = self._dispatch()
        for s in self.schedulers:
            if s.tick():
                progressed = True
        if self._rebalance():
            progressed = True
        self._since_gauge = [0] * len(self.schedulers)
        occs = [
            self._gauge(i, "serve.slot_occupancy")
            for i in range(len(self.schedulers))
        ]
        self._m_spread.set(max(occs) - min(occs))
        for i, o in enumerate(occs):
            self._occ_sum[i] += o
        self._occ_n += 1
        self._ticks += 1
        if self.incidents is not None and \
                self._ticks % self._inc_every == 0:
            self.incidents.evaluate()
        return progressed

    @property
    def pending(self) -> bool:
        return bool(
            self._queue or any(s.pending for s in self.schedulers)
        )

    def run(self, requests: Optional[Sequence[Request]] = None
            ) -> List[Completion]:
        """Submit ``requests`` (optional) and drain the whole fleet.
        Returns every replica's completions, merged (sorted by finish
        time)."""
        for r in requests or ():
            self.submit(r)
        while self.pending:
            if not self.tick():
                nxt = [r.arrival for r in self._queue[:1]]
                nxt += [
                    t for t in (
                        s.next_arrival() for s in self.schedulers
                    ) if t is not None
                ]
                if not nxt:  # pragma: no cover - defensive
                    raise RuntimeError(
                        "router made no progress with no future arrivals"
                    )
                self.clock.skip_to(min(nxt))
        self.finish()
        return self.completions

    def finish(self) -> None:
        """Close every replica's books + the router's own gauges."""
        for s in self.schedulers:
            s.finish()
        self._m_rq.set(len(self._queue))
        self._m_spread.set(0.0)
        if self.incidents is not None:
            self.incidents.evaluate()

    # ------------------------------------------------------ introspection
    @property
    def completions(self) -> List[Completion]:
        out: List[Completion] = []
        for s in self.schedulers:
            out.extend(s.completions)
        return sorted(out, key=lambda c: (c.finished_at, c.id))

    def replica_stats(self) -> List[dict]:
        """Per-replica host-side summary (benchmarks/dashboards)."""
        out = []
        for i, s in enumerate(self.schedulers):
            out.append({
                "replica": i,
                "role": self.roles[i],
                "dispatched": sum(
                    1 for reps in self.assignments.values()
                    if reps and reps[0] == i
                ),
                "served": sum(
                    1 for reps in self.assignments.values()
                    if reps and reps[-1] == i
                ),
                "completions": len(s.completions),
                "occupancy_mean": (
                    self._occ_sum[i] / self._occ_n if self._occ_n else 0.0
                ),
                "engine": s.engine.stats(),
            })
        return out

    def export_fleet_trace(self, path: str) -> dict:
        """Merge the per-replica span rings through the PR-8 fleet
        pipeline — one replica = one "rank" (pid) — and write ONE
        Perfetto-loadable trace.  A migrated request's ``serve.*``
        spans (each carrying ``req=<id>`` detail) appear under every
        replica that touched it.  Replicas share one process and one
        monotonic clock, so no offset correction is needed (offsets
        default to zero).  Returns the merge summary (with ``path``)."""
        from chainermn_tpu.observability import fleet as _fleet
        from chainermn_tpu.observability import tracing as _tracing

        dumps = [
            {
                "rank": i,
                "spans": ring.snapshot(),
                "spans_total": ring.total,
                "epoch_wall": _tracing.EPOCH_WALL,
                "epoch_perf": _tracing.EPOCH_PERF,
            }
            for i, ring in enumerate(self.rings)
        ]
        merged = _fleet.merge_fleet_trace(dumps)
        merged["summary"]["path"] = _fleet.write_fleet_trace(
            path, merged["payload"]
        )
        return merged["summary"]
