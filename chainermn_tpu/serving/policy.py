"""Multi-tenant SLO policy plane: weighted fair queuing, priority
preemption, drift-driven chunked-prefill budgeting (ISSUE 19).

The fleet has every sensor and actuator a production scheduler needs —
live SLO drift (PR 6), near-free preemption via the prefix cache (PR 7),
exactly-once terminals (PR 15), per-tenant cost attribution (PR 16),
closed-loop autoscaling (PR 17) — but admission was one FIFO queue, so a
single bursty tenant could starve a latency-sensitive one.  This module
is the missing *policy* layer: one :class:`PolicyPlane` the
:class:`~chainermn_tpu.serving.scheduler.Scheduler` and
:class:`~chainermn_tpu.serving.router.Router` consult at every
admission / eviction / steal decision.  Four mechanisms, all host-side
(``decode_compiles == 1`` stays pinned with policy ON):

* **Weighted fair admission (VTC).**  Every tenant carries a virtual
  service clock — the *virtual token counter* of Sheng et al. 2023 —
  charged from the SAME integer cost seams the PR-16 ledger books:
  prefill tokens net of prefix hits (``_prefill_chunk`` computes from
  the first unmatched token, so a cached prefix is free here exactly as
  it is on the bill), decode iterations, and KV block-microseconds
  (piecewise-constant integration mirroring
  :meth:`~chainermn_tpu.observability.ledger.CostLedger.set_blocks`).
  Admission picks the queued tenant with the smallest
  ``charged / weight`` clock (per-tenant FIFO within), so fairness is
  over real cost, not request count.  A tenant going active after idling
  is LIFTED to the busiest floor (min clock over currently-queued
  tenants) — idle time banks no credit.

* **Priority classes with preemption.**  A queued entry whose effective
  class (``Request.priority``, else its tenant's default) strictly
  outranks a running slot's may evict the lowest-class youngest slot
  through the existing recompute-requeue path: generated tokens fold
  into ``carried``, the entry re-queues at its tenant's head, and the
  re-admission re-matches its own just-cached prefix — preemption is
  nearly free, the continuation greedy-identical.  ``entry.retries`` is
  never touched (that counter means replica deaths).

* **Drift-driven chunked-prefill budgeting.**  When the live SLO check
  reports a breach (rolling p95 left the envelope — the
  ``serve.slo.p95_drift`` signal) for ``drift_hysteresis`` consecutive
  checks, the plane latches a Sarathi-style cap: at most
  ``prefill_cap`` prefill tokens admitted per scheduler iteration
  (chunk-granular; the first chunk of a round always runs so prefill
  can never wedge).  The latch releases after the same number of clean
  checks — the PR-17 autoscaler's hysteresis discipline.

* **Per-tenant isolation knobs.**  Token rate limits over the policy
  clock (a tenant past ``rate_limit`` cost-units/s is simply not
  eligible for admission until the clock catches up — terminals stay
  exactly-once: a throttled request still completes, or terminates
  through the existing ``deadline``/``shed`` paths), prefix-cache block
  quotas (enforced inside
  :meth:`~chainermn_tpu.serving.prefix_cache.PrefixCache.insert` /
  eviction — a tenant over quota evicts its OWN least-recently-used
  leaves, never another tenant's), and per-tenant deadline / shed
  defaults that terminate as the existing ``status="deadline"`` /
  ``"shed"`` outcomes.

Starvation watch: the plane publishes ``serve.policy.starved_tenant``
(the index of a tenant whose rolling queue-wait p95 exceeds
``CMN_POLICY_STARVATION_MS``; −1 = nobody — the ``fleet_straggler``
idiom), which the ``tenant_starvation`` default incident rule turns
into a keyed incident per starved tenant.

Share ONE plane fleet-wide: the Router passes its ``policy=`` into
every replica (revivals and scale-ups included) so the service clocks
and rate limits are fleet-coherent, exactly like the PR-16 ledger.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from chainermn_tpu.observability.metrics import (
    NoopInstrument as _NoopInstrument,
    _env_float,
)

#: cost-dimension weights: one unit per prefill token; decode iterations
#: and block-microseconds are scaled by the env-tunable weights below.
COST_DIMS = ("prefill_tokens", "decode_iterations", "block_us")


# ----------------------------------------------------------- env knobs
def prefill_cap_from_env() -> int:
    """``CMN_POLICY_PREFILL_CAP`` — prefill tokens admitted per
    scheduler iteration while the drift latch is engaged (default
    32)."""
    return max(1, int(_env_float("CMN_POLICY_PREFILL_CAP", 32)))


def drift_hysteresis_from_env() -> int:
    """``CMN_POLICY_DRIFT_HYSTERESIS`` — consecutive breaching SLO
    checks before the prefill cap engages (and clean checks before it
    releases; default 2)."""
    return max(1, int(_env_float("CMN_POLICY_DRIFT_HYSTERESIS", 2)))


def decode_cost_from_env() -> int:
    """``CMN_POLICY_COST_DECODE`` — policy-clock cost units per decode
    iteration (default 1; prefill tokens are always 1 each)."""
    return max(0, int(_env_float("CMN_POLICY_COST_DECODE", 1)))


def block_cost_from_env() -> float:
    """``CMN_POLICY_COST_BLOCK_US`` — policy-clock cost units per KV
    block-microsecond held (default 0 = pool occupancy not metered
    into the fairness clock; enable to charge hoarders)."""
    return max(0.0, _env_float("CMN_POLICY_COST_BLOCK_US", 0.0))


def starvation_ms_from_env() -> float:
    """``CMN_POLICY_STARVATION_MS`` — per-tenant rolling queue-wait p95
    above which the plane names the tenant on the
    ``serve.policy.starved_tenant`` gauge (default 1000 ms)."""
    return _env_float("CMN_POLICY_STARVATION_MS", 1000.0)


def default_weight_from_env() -> float:
    """``CMN_SERVE_TENANT_WEIGHT`` — fair-share weight for tenants not
    named in the spec (default 1)."""
    return max(1e-9, _env_float("CMN_SERVE_TENANT_WEIGHT", 1.0))


def tenant_spec_from_env() -> Dict[str, "TenantPolicy"]:
    """Parse ``CMN_SERVE_TENANT_SPEC`` — semicolon-separated per-tenant
    specs ``name:key=value,key=value`` with keys ``weight``,
    ``priority``, ``rate`` (cost units/s), ``quota`` (prefix-cache
    blocks), ``deadline_ms``, ``shed`` (router holdback depth), e.g.
    ``slo:weight=4,priority=1,deadline_ms=500;batch:weight=1,rate=200``.
    Unparseable fragments are skipped (tolerant, like every obs
    knob)."""
    import os

    spec = os.environ.get("CMN_SERVE_TENANT_SPEC", "").strip()
    out: Dict[str, TenantPolicy] = {}
    if not spec:
        return out
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, body = part.partition(":")
        name = name.strip()
        if not name:
            continue
        kw: dict = {}
        for item in body.split(","):
            k, _, v = item.partition("=")
            k, v = k.strip(), v.strip()
            try:
                if k == "weight":
                    kw["weight"] = max(1e-9, float(v))
                elif k == "priority":
                    kw["priority"] = int(float(v))
                elif k == "rate":
                    kw["rate_limit"] = float(v)
                elif k == "quota":
                    kw["prefix_quota"] = int(float(v))
                elif k == "deadline_ms":
                    kw["deadline_ms"] = float(v)
                elif k == "shed":
                    kw["shed_depth"] = int(float(v))
            except ValueError:
                continue
        out[name] = TenantPolicy(name=name, **kw)
    return out


# --------------------------------------------------------- TenantPolicy
@dataclass
class TenantPolicy:
    """One tenant's knobs.  Everything optional: an unconfigured tenant
    gets the default weight and no limits — the plane never refuses a
    tenant it has not seen."""

    name: str
    #: fair-share weight: the VTC clock advances by ``cost / weight``,
    #: so a weight-3 tenant earns 3× the service of a weight-1 one.
    weight: float = 1.0
    #: default priority class for requests that carry none of their own
    #: (``Request.priority == 0``); higher preempts lower.
    priority: int = 0
    #: cost units per second this tenant may consume (policy clock);
    #: None = unlimited.
    rate_limit: Optional[float] = None
    #: prefix-cache trie blocks this tenant may pin; None = unlimited.
    prefix_quota: Optional[int] = None
    #: default deadline (ms past arrival) for its requests that carry
    #: none — terminates as the existing ``status="deadline"``.
    deadline_ms: Optional[float] = None
    #: router holdback cap for this tenant's ARRIVED requests; overflow
    #: sheds newest-first as the existing ``status="shed"``.
    shed_depth: Optional[int] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}"
            )


# ---------------------------------------------------------- PolicyPlane
class PolicyPlane:
    """The fleet's admission/eviction/steal policy.

    Args:
      tenants: per-tenant knobs — a dict ``name -> TenantPolicy``, an
        iterable of :class:`TenantPolicy`, or None (resolve from
        ``CMN_SERVE_TENANT_SPEC``).  Tenants not named get
        ``TenantPolicy(name, weight=CMN_SERVE_TENANT_WEIGHT)`` on first
        sight.
      registry: where ``serve.policy.*`` and the per-tenant
        ``serve.tenant.<t>.*`` family publish — the Scheduler/Router
        latch (explicit always publishes; ``None`` rides ``CMN_OBS``;
        off → noop instruments).
      prefill_cap / drift_hysteresis: the Sarathi latch (env-backed
        defaults ``CMN_POLICY_PREFILL_CAP`` /
        ``CMN_POLICY_DRIFT_HYSTERESIS``).
      decode_cost / block_cost_us: policy-clock weights for the decode
        and block-occupancy seams (``CMN_POLICY_COST_DECODE`` /
        ``CMN_POLICY_COST_BLOCK_US``).
      starvation_ms: queue-wait p95 envelope behind the
        ``tenant_starvation`` rule (``CMN_POLICY_STARVATION_MS``).
    """

    def __init__(self, tenants=None, registry=None,
                 prefill_cap: Optional[int] = None,
                 drift_hysteresis: Optional[int] = None,
                 decode_cost: Optional[int] = None,
                 block_cost_us: Optional[float] = None,
                 starvation_ms: Optional[float] = None):
        import chainermn_tpu.observability as _obs
        from chainermn_tpu.observability.metrics import (
            registry as global_registry,
        )

        if tenants is None:
            tenants = tenant_spec_from_env()
        if not isinstance(tenants, dict):
            tenants = {t.name: t for t in tenants}
        self.tenants: Dict[str, TenantPolicy] = dict(tenants)
        self._default_weight = default_weight_from_env()
        self.prefill_cap = (
            prefill_cap_from_env() if prefill_cap is None
            else max(1, int(prefill_cap))
        )
        self.drift_hysteresis = (
            drift_hysteresis_from_env() if drift_hysteresis is None
            else max(1, int(drift_hysteresis))
        )
        self.decode_cost = (
            decode_cost_from_env() if decode_cost is None
            else max(0, int(decode_cost))
        )
        self.block_cost_us = (
            block_cost_from_env() if block_cost_us is None
            else max(0.0, float(block_cost_us))
        )
        self.starvation_ms = (
            starvation_ms_from_env() if starvation_ms is None
            else float(starvation_ms)
        )
        #: live view the PrefixCache reads at insert time — one dict,
        #: shared by reference into every replica's trie.
        self.prefix_quotas: Dict[str, int] = {
            n: t.prefix_quota for n, t in self.tenants.items()
            if t.prefix_quota is not None
        }
        #: raw policy-clock charge per tenant (integer cost units except
        #: for the optional fractional block weight) — the rate-limit
        #: basis and the VTC oracle's input.
        self.charged: Dict[str, float] = {}
        #: the virtual token counter: ``charged / weight``, lifted on
        #: (re)activation.  Admission picks the smallest.
        self.virtual: Dict[str, float] = {}
        #: first-sighting time per tenant — the rate-limit clock origin.
        self._t0: Dict[str, float] = {}
        #: request id -> (tenant, block level, since-us) — the
        #: piecewise block-second integral, mirroring the ledger's.
        self._blocks: Dict[int, tuple] = {}
        #: tenants queued at the last pick (activation-lift tracking).
        self._was_queued: set = set()
        #: stable tenant index for the starvation gauge / incident key.
        self._tenant_index: Dict[str, int] = {}
        #: rolling queue-wait windows (ms), per tenant.
        self._wait_win: Dict[str, List[float]] = {}
        self._wait_window = 64
        # Drift latch state.
        self._breach_streak = 0
        self._clean_streak = 0
        self.prefill_cap_active = False
        #: audit trail: (req_id, tenant, virtual-clock-at-pick) per
        #: admission pick — the VTC convergence test's exact record.
        self.admission_log: List[tuple] = []
        self.preemptions = 0
        self.throttle_deferrals = 0
        #: True once a Router owns this plane: replicas then skip their
        #: own per-tenant queue-depth publish (the router's fleet-wide
        #: count is the truth; per-replica publishes would thrash it).
        self.fleet = False
        if registry is None and not _obs.enabled():
            self._reg = None
            noop = _NoopInstrument()
            self._m_preempt = self._m_throttled = noop
            self._m_cap_active = self._m_capped = noop
            self._m_starved = noop
        else:
            reg = registry if registry is not None else global_registry()
            self._reg = reg
            self._m_preempt = reg.counter("serve.policy.preemptions")
            self._m_throttled = reg.counter("serve.policy.throttled")
            self._m_cap_active = reg.gauge(
                "serve.policy.prefill_cap_active"
            )
            self._m_capped = reg.counter("serve.policy.prefill_capped")
            self._m_starved = reg.gauge("serve.policy.starved_tenant")
        self._m_cap_active.set(0.0)
        self._m_starved.set(-1.0)
        #: per-tenant instruments, created on first sight.
        self._t_depth: Dict[str, object] = {}
        self._t_preempted: Dict[str, object] = {}
        self._t_throttled: Dict[str, object] = {}

    # ------------------------------------------------------------ tenants
    def policy_for(self, tenant: str) -> TenantPolicy:
        t = self.tenants.get(tenant)
        if t is None:
            t = TenantPolicy(tenant, weight=self._default_weight)
            self.tenants[tenant] = t
        if tenant not in self._tenant_index:
            self._tenant_index[tenant] = len(self._tenant_index)
        return t

    def tenant_index(self, tenant: str) -> int:
        """Stable integer id (first-sighting order) — the starvation
        gauge's value and incident dedupe key."""
        self.policy_for(tenant)
        return self._tenant_index[tenant]

    def effective_priority(self, req) -> int:
        """The request's class: its own ``priority`` when set (non-zero),
        else its tenant's default."""
        p = getattr(req, "priority", 0)
        return p if p else self.policy_for(req.tenant).priority

    def _t_inst(self, cache: Dict[str, object], tenant: str,
                suffix: str, kind: str):
        inst = cache.get(tenant)
        if inst is None:
            if self._reg is None:
                inst = _NoopInstrument()
            elif kind == "gauge":
                inst = self._reg.gauge(f"serve.tenant.{tenant}.{suffix}")
            else:
                inst = self._reg.counter(
                    f"serve.tenant.{tenant}.{suffix}"
                )
            cache[tenant] = inst
        return inst

    # ------------------------------------------------------------ charging
    def charge(self, tenant: str, dim: str, amount) -> None:
        """Advance ``tenant``'s policy clock by one booked cost — the
        same seams the PR-16 ledger books (prefill tokens net of prefix
        hits, decode iterations, block-microseconds)."""
        if amount <= 0:
            return
        if dim == "prefill_tokens":
            cost = float(amount)
        elif dim == "decode_iterations":
            cost = float(amount) * self.decode_cost
        elif dim == "block_us":
            cost = float(amount) * self.block_cost_us
        else:
            raise ValueError(f"unknown policy cost dim {dim!r}")
        if cost <= 0:
            return
        t = self.policy_for(tenant)
        self.charged[tenant] = self.charged.get(tenant, 0.0) + cost
        self.virtual[tenant] = (
            self.virtual.get(tenant, 0.0) + cost / t.weight
        )

    def set_blocks(self, rid: int, tenant: str, blocks: int,
                   now: float) -> None:
        """Piecewise-constant block-second integration on the policy
        clock — the ledger's ``set_blocks`` discipline, charged into
        the fairness clock at ``CMN_POLICY_COST_BLOCK_US`` units per
        block-microsecond (0 = seam present, charge off)."""
        now_us = int(now * 1e6)
        prev = self._blocks.get(rid)
        if prev is not None:
            _, level, since = prev
            if level > 0 and now_us > since:
                self.charge(tenant, "block_us", (now_us - since) * level)
        if blocks > 0:
            self._blocks[rid] = (tenant, int(blocks), now_us)
        else:
            self._blocks.pop(rid, None)

    # ---------------------------------------------------------- rate limit
    def _ensure_clock(self, tenant: str, now: float) -> None:
        if tenant not in self._t0:
            self._t0[tenant] = now

    def throttled(self, tenant: str, now: float) -> bool:
        """True while ``tenant`` has consumed past its ``rate_limit``
        allowance (``rate × seconds-since-first-sight``)."""
        t = self.policy_for(tenant)
        if t.rate_limit is None:
            return False
        self._ensure_clock(tenant, now)
        allowance = t.rate_limit * max(0.0, now - self._t0[tenant])
        return self.charged.get(tenant, 0.0) > allowance

    def next_release(self, reqs: Sequence, now: float
                     ) -> Optional[float]:
        """Earliest time a currently-throttled queued tenant becomes
        eligible again — the idle-skip bound for ``run()`` loops (a
        fully-throttled queue must advance the clock, not spin)."""
        out = None
        for tenant in {r.tenant for r in reqs if r.arrival <= now}:
            t = self.policy_for(tenant)
            if t.rate_limit is None or not self.throttled(tenant, now):
                continue
            rel = (
                self._t0[tenant]
                + self.charged.get(tenant, 0.0) / t.rate_limit
            )
            out = rel if out is None else min(out, rel)
        return out

    # ------------------------------------------------------------- picking
    def pick_index(self, reqs: Sequence, now: float,
                   record: bool = False) -> Optional[int]:
        """The weighted-fair admission pick over ``reqs`` (Request-like:
        ``.arrival`` / ``.tenant`` / ``.id``): the first-queued item of
        the arrived, un-throttled tenant with the smallest virtual
        clock.  Returns the index into ``reqs``, or None (nothing
        arrived, or every arrived tenant is rate-throttled — counted as
        a throttle deferral)."""
        heads: Dict[str, int] = {}
        order: List[str] = []
        for i, r in enumerate(reqs):
            if r.arrival > now:
                continue
            if r.tenant not in heads:
                heads[r.tenant] = i
                order.append(r.tenant)
        if not heads:
            return None
        # Activation lift: a tenant newly (re)joining the queue starts
        # at the busiest floor — idle time banks no credit (VTC).
        floor = min(
            (self.virtual.get(t, 0.0) for t in order
             if t in self._was_queued),
            default=None,
        )
        for t in order:
            self.policy_for(t)
            self._ensure_clock(t, now)
            if t not in self._was_queued and floor is not None:
                self.virtual[t] = max(
                    self.virtual.get(t, 0.0), floor
                )
        self._was_queued = set(order)
        eligible = [t for t in order if not self.throttled(t, now)]
        if not eligible:
            self.throttle_deferrals += 1
            self._m_throttled.inc()
            for t in order:
                self._t_inst(
                    self._t_throttled, t, "throttled", "counter"
                ).inc()
            return None
        best = min(
            eligible,
            key=lambda t: (self.virtual.get(t, 0.0),
                           self._tenant_index[t]),
        )
        idx = heads[best]
        if record:
            self.admission_log.append(
                (reqs[idx].id, best, self.virtual.get(best, 0.0))
            )
        return idx

    def note_admission(self, req) -> None:
        """Record one COMMITTED admission (the scheduler calls this
        after the allocator gate passed, never on a failed pick) —
        ``(req id, tenant, virtual clock at admission)``, the VTC
        convergence test's exact trace."""
        self.admission_log.append(
            (req.id, req.tenant,
             self.virtual.get(req.tenant, 0.0))
        )

    def steal_index(self, reqs: Sequence, now: float) -> Optional[int]:
        """The rebalance-steal pick: the same weighted-fair head the
        donor's own admission would serve next — the stolen entry runs
        immediately on an idle replica, so picking the fair head can
        only ACCELERATE the schedule, never let a backlogged tenant
        jump an SLO tenant's entry."""
        return self.pick_index(reqs, now)

    # ----------------------------------------------------------- preemption
    def preempt_pick(self, slots: Sequence, incoming_class: int):
        """The victim for a class-``incoming_class`` admission with no
        free slot: the LOWEST-class slot, youngest admission among
        equals (the eviction discipline), and only when strictly
        outranked.  Returns the slot or None."""
        victims = [
            s for s in slots
            if self.effective_priority(s.entry.req) < incoming_class
        ]
        if not victims:
            return None
        return min(
            victims,
            key=lambda s: (self.effective_priority(s.entry.req),
                           -s.admit_seq),
        )

    def note_preemption(self, victim_tenant: str) -> None:
        self.preemptions += 1
        self._m_preempt.inc()
        self._t_inst(
            self._t_preempted, victim_tenant, "preempted", "counter"
        ).inc()

    # ------------------------------------------------------- prefill budget
    def on_slo_check(self, report: Optional[dict]) -> None:
        """Feed one SLO check verdict into the drift latch (call on the
        scheduler's check cadence).  Engages the prefill cap after
        ``drift_hysteresis`` consecutive breaching checks; releases
        after the same number of clean ones."""
        breached = bool(report) and any(
            isinstance(v, dict) and v.get("breached")
            for v in report.values()
        )
        if breached:
            self._breach_streak += 1
            self._clean_streak = 0
            if not self.prefill_cap_active and \
                    self._breach_streak >= self.drift_hysteresis:
                self.prefill_cap_active = True
                self._m_cap_active.set(1.0)
        else:
            self._clean_streak += 1
            self._breach_streak = 0
            if self.prefill_cap_active and \
                    self._clean_streak >= self.drift_hysteresis:
                self.prefill_cap_active = False
                self._m_cap_active.set(0.0)

    def prefill_budget(self) -> Optional[int]:
        """Prefill tokens admissible this iteration: ``prefill_cap``
        while the drift latch is engaged, None (unbounded) otherwise."""
        return self.prefill_cap if self.prefill_cap_active else None

    def note_prefill_capped(self) -> None:
        self._m_capped.inc()

    # ---------------------------------------------------------- starvation
    def note_queue_wait(self, tenant: str, wait_ms: float) -> None:
        """One first-admission queue-wait sample; refreshes the starved
        gauge (worst breaching tenant's index, −1 = nobody)."""
        win = self._wait_win.setdefault(tenant, [])
        win.append(float(wait_ms))
        if len(win) > self._wait_window:
            del win[: len(win) - self._wait_window]
        self._publish_starved()

    def _wait_p95(self, tenant: str) -> Optional[float]:
        win = self._wait_win.get(tenant)
        if not win:
            return None
        vals = sorted(win)
        return vals[min(len(vals) - 1, int(0.95 * (len(vals) - 1)))]

    def _publish_starved(self) -> None:
        worst, worst_p95 = None, None
        for tenant in self._wait_win:
            p95 = self._wait_p95(tenant)
            if p95 is not None and p95 > self.starvation_ms and (
                worst_p95 is None or p95 > worst_p95
            ):
                worst, worst_p95 = tenant, p95
        self._m_starved.set(
            float(self.tenant_index(worst)) if worst is not None
            else -1.0
        )

    # ------------------------------------------------------------ defaults
    def deadline_ms(self, tenant: str) -> Optional[float]:
        """The tenant's default deadline for requests carrying none."""
        return self.policy_for(tenant).deadline_ms

    def shed_depth(self, tenant: str) -> Optional[int]:
        """The tenant's router holdback cap (None = only the fleet
        ``CMN_ROUTER_SHED_DEPTH`` applies)."""
        return self.policy_for(tenant).shed_depth

    # -------------------------------------------------------------- publish
    def publish_queue(self, tenants: Sequence[str]) -> None:
        """Refresh ``serve.tenant.<t>.queue_depth`` from one queue
        census (every queued request's tenant, fleet-wide when the
        Router drives it)."""
        counts: Dict[str, int] = {}
        for t in tenants:
            counts[t] = counts.get(t, 0) + 1
        for t in self._t_depth:
            if t not in counts:
                self._t_depth[t].set(0.0)
        for t, n in counts.items():
            self._t_inst(
                self._t_depth, t, "queue_depth", "gauge"
            ).set(float(n))

    # ---------------------------------------------------------- inspection
    def state(self) -> dict:
        """Host-side snapshot (flight records / tests / benchmarks)."""
        return {
            "virtual": dict(self.virtual),
            "charged": dict(self.charged),
            "prefill_cap_active": self.prefill_cap_active,
            "preemptions": self.preemptions,
            "throttle_deferrals": self.throttle_deferrals,
            "tenants": sorted(self.tenants),
        }
