"""Distributed checkpointing — restart-based fault tolerance.

Reference anchor: ``chainermn/extensions/checkpoint.py`` —
``create_multi_node_checkpointer(name, comm)`` / ``class
_MultiNodeCheckpointer``: each rank snapshots its local state with rank-tagged
filenames, the ranks ``allgather_obj`` their saved iteration lists and agree
on the latest iteration *common to all ranks*, stale files are
garbage-collected, and ``maybe_load`` resumes from the consistent set on
restart.  World size is fixed (restart-based, not elastic).

TPU-native: orbax's ``CheckpointManager`` already provides exactly the hard
parts — sharded async saves, cross-host atomicity (every host commits or the
step is not visible, which IS the "latest common iteration" agreement),
retention-based gc, and ``latest_step``.  This module wraps it in the
reference's extension + ``maybe_load`` shape and adds iterator/trainer state
so resume is exact.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import shutil
from collections import deque
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from chainermn_tpu import observability as _obs
from chainermn_tpu.observability import metrics as _omet
from chainermn_tpu.observability import tracing as _otrace
from chainermn_tpu.resilience.policy import RetryPolicy
from chainermn_tpu.training import Extension


def capture_loop_state(trainer) -> dict:
    """Snapshot the loop-resume state (trainer iteration/epoch, iterator
    cursor + RNG) as a flat dict of numpy leaves.  Module-level because two
    planes snapshot it: the orbax checkpointer (durable tier) and the
    peer-replication plane (``resilience/replicate.py``, fast tier) — both
    must carry identical loop state for a restore to be bit-exact."""
    out = {
        "iteration": np.zeros((), np.int64),
        "epoch": np.zeros((), np.int64),
        "it_pos": np.zeros((), np.int64),
    }
    if trainer is None:
        return out
    it = trainer.train_iter
    out["iteration"] = np.asarray(trainer.iteration, np.int64)
    out["epoch"] = np.asarray(getattr(it, "epoch", 0), np.int64)
    # Iterators with lookahead (PrefetchIterator's native ring) expose an
    # explicit consumption-granular cursor — their raw attributes must
    # not be snapshotted (the submission cursor runs depth batches ahead).
    st = (
        it.checkpoint_loop_state()
        if hasattr(it, "checkpoint_loop_state")
        else None
    )
    if st is not None:
        out["it_pos"] = np.asarray(st["pos"], np.int64)
        out["it_order"] = np.asarray(st["order"], np.int64)
        out["rng_keys"] = np.asarray(st["rng_keys"], np.uint32)
        out["rng_pos"] = np.asarray(st["rng_pos"], np.int64)
        out["rng_has_gauss"] = np.asarray(st["rng_has_gauss"], np.int64)
        out["rng_cached"] = np.asarray(st["rng_cached"], np.float64)
        # Degraded-cursor flag (see DevicePrefetchIterator): > 0 means
        # the snapshot may replay/skip up to this many samples on
        # restore.  ALWAYS present so the orbax tree structure is
        # deterministic (StandardRestore templates must match).
        out["it_inexact"] = np.asarray(st.get("inexact", 0), np.int64)
        return out
    out["it_pos"] = np.asarray(getattr(it, "_pos", 0), np.int64)
    # Exact mid-epoch resume needs the iterator's in-flight permutation
    # and RNG state (restoring _pos into a FRESH permutation would skip
    # and duplicate samples).  SerialIterator-shaped iterators only.
    if hasattr(it, "_order") and hasattr(it, "_rng"):
        mt, keys, pos, has_gauss, cached = it._rng.get_state()
        out["it_order"] = np.asarray(it._order, np.int64)
        out["rng_keys"] = np.asarray(keys, np.uint32)
        out["rng_pos"] = np.asarray(pos, np.int64)
        out["rng_has_gauss"] = np.asarray(has_gauss, np.int64)
        out["rng_cached"] = np.asarray(cached, np.float64)
    return out


def apply_loop_state(trainer, new_state, loop) -> None:
    """Push restored trainer/iterator/extension state — shared by the
    checkpointer's template and elastic restore paths and by the
    peer-replication fast restore (``resilience/replicate.py``)."""
    if trainer is None:
        return
    trainer.state = new_state
    trainer.iteration = int(loop["iteration"])
    it = trainer.train_iter
    if hasattr(it, "restore_loop_state") and "it_order" in loop:
        it.restore_loop_state(
            int(loop["epoch"]),
            {
                "pos": int(loop["it_pos"]),
                "order": loop["it_order"],
                "rng_keys": loop["rng_keys"],
                "rng_pos": int(loop["rng_pos"]),
                "rng_has_gauss": int(loop["rng_has_gauss"]),
                "rng_cached": float(loop["rng_cached"]),
            },
        )
    else:
        if hasattr(it, "epoch"):
            it.epoch = int(loop["epoch"])
        if hasattr(it, "_pos"):
            it._pos = int(loop["it_pos"])
        if "it_order" in loop and hasattr(it, "_order"):
            it._order = np.asarray(loop["it_order"]).astype(np.int64)
            it._rng.set_state((
                "MT19937",
                np.asarray(loop["rng_keys"]).astype(np.uint32),
                int(loop["rng_pos"]),
                int(loop["rng_has_gauss"]),
                float(loop["rng_cached"]),
            ))
    # Sync trigger state so interval extensions don't all re-fire on
    # the first post-resume iteration (which would burn a retention
    # slot on a duplicate checkpoint and log a one-iteration window).
    for ext in trainer.extensions:
        ext._last_fired = (
            int(loop["epoch"])
            if ext.unit == "epoch"
            else int(loop["iteration"])
        )


class MultiNodeCheckpointer(Extension):
    """Trainer extension that snapshots (TrainState, iterator state, trainer
    iteration) every trigger, keeps ``max_to_keep`` checkpoints, and restores
    the newest complete one via :meth:`maybe_load`."""

    def __init__(
        self,
        name: str,
        comm,
        path: str = "checkpoints",
        max_to_keep: int = 5,
        trigger=(1, "epoch"),
        async_save: bool = True,
        known_good_keep: int = 3,
    ):
        super().__init__(self._fire, trigger=trigger, name=f"checkpointer/{name}")
        import orbax.checkpoint as ocp

        self.comm = comm
        self._dir = os.path.abspath(os.path.join(path, name))
        # Deterministic bounded retries around snapshot I/O: a transient
        # filesystem hiccup (GCS 5xx, NFS stall) must not cost a whole-job
        # restart.  Saves retry broadly (the partial commit is clobbered
        # with force=True); restores retry only OS-level I/O errors —
        # template/structure mismatches are NOT transients and must reach
        # maybe_load's fallback logic untouched.
        self._save_retry = RetryPolicy(
            max_attempts=3, base_delay_s=0.2, multiplier=2.0, max_delay_s=2.0
        )
        self._restore_retry = RetryPolicy(
            max_attempts=3, base_delay_s=0.2, multiplier=2.0,
            max_delay_s=2.0, retry_on=(OSError,),
        )
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        # Known-good ring (training-health guard, resilience/guard.py): the
        # last K snapshot steps that survived a clean cross-rank
        # consistency vote.  A snapshot's mere existence only proves the
        # job was ALIVE at the trigger; membership here proves the
        # replicas still agreed — the only steps rollback recovery may
        # target.  Persisted next to the snapshots so a supervised
        # relaunch after a health escalation resumes from verified state.
        self._known_good: deque = deque(maxlen=int(known_good_keep))
        for s in self._load_known_good():
            self._known_good.append(int(s))
        # Newest step save() committed in THIS life — rank-invariant (the
        # trigger fires at the same iterations everywhere), so blessing
        # can skip its async flush deterministically when nothing new
        # could possibly be on disk.
        self._last_saved_step: Optional[int] = None

    # ----------------------------------------------------------------- save
    def _fire(self, trainer):
        self.save(trainer.state, trainer)

    def save(self, state, trainer=None):
        import orbax.checkpoint as ocp

        step = int(trainer.iteration if trainer is not None else state.step)
        loop = self._loop_state(trainer)
        inexact = int(loop.get("it_inexact", 0))
        if inexact > 0:
            # Warn at SAVE time only — _loop_state also runs during restore
            # to build the orbax template, where this condition is noise.
            import warnings

            warnings.warn(
                "checkpoint saved with prefetch lookahead skew: the "
                f"iterator cursor is inexact by up to {inexact} samples "
                "(epoch boundary or shallow cursor in the prefetch "
                "queue); a restore from this snapshot may replay or skip "
                "that many samples.",
                stacklevel=2,
            )
        payload = {"train_state": state, "loop": loop}
        attempts = [0]

        def _commit():
            # Retry attempts force-overwrite: the failed attempt may have
            # left a partial step directory that a plain save would
            # reject.  Counted at ENTRY — a failed save must still mark
            # the attempt, or every retry would re-run force=False.
            attempt = attempts[0]
            attempts[0] += 1
            self._mngr.save(
                step,
                args=ocp.args.StandardSave(payload),
                force=attempt > 0,
            )

        # Span + counter: the save DISPATCH is what blocks the loop
        # (async commits flush later); the span records that cost and
        # the flight recorder can name a rank dying mid-save.
        obs_on = _obs.enabled()
        with (_otrace.tracer().span("ckpt_save", detail=f"step={step}")
              if obs_on else contextlib.nullcontext()):
            self._save_retry.call(_commit)
        if obs_on:
            _omet.registry().counter("ckpt.saves").inc()
        self._last_saved_step = step

    def emergency_save(self, trainer) -> int:
        """Preemption entry point (:class:`PreemptionGuard`): one
        *synchronous* snapshot at the trainer's current iteration —
        flushes any in-flight async commit first, skips the write when
        that step is already the newest snapshot (idempotent under
        repeated signals), and blocks until the new step is durable.
        Returns the step saved."""
        step = int(trainer.iteration)
        self._mngr.wait_until_finished()
        if self._mngr.latest_step() != step:
            self.save(trainer.state, trainer)
            self._mngr.wait_until_finished()
        return step

    @staticmethod
    def _loop_state(trainer) -> dict:
        return capture_loop_state(trainer)

    # -------------------------------------------------------------- restore
    def _restore(self, step, template):
        import orbax.checkpoint as ocp

        obs_on = _obs.enabled()
        with (_otrace.tracer().span("ckpt_restore", detail=f"step={step}")
              if obs_on else contextlib.nullcontext()):
            out = self._restore_retry.call(
                self._mngr.restore, step,
                args=ocp.args.StandardRestore(template),
            )
        if obs_on:
            _omet.registry().counter("ckpt.restores").inc()
        return out

    def maybe_load(self, state, trainer=None) -> Tuple[Any, int]:
        """Reference anchor: ``_MultiNodeCheckpointer.maybe_load`` — restore
        the latest complete snapshot if one exists; otherwise return the
        inputs unchanged.  Returns ``(state, iteration)``."""
        step = self._mngr.latest_step()
        if step is None:
            return state, 0
        return self.restore_step(step, state, trainer)

    def restore_step(self, step, state, trainer=None) -> Tuple[Any, int]:
        """Restore a SPECIFIC snapshot step into ``state``/``trainer`` —
        the rollback-recovery entry point (``maybe_load`` is this at
        ``latest_step``).  Collective: every rank restores together."""
        import orbax.checkpoint as ocp

        template = {
            "train_state": jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, state
            ),
            "loop": self._loop_state(trainer),
        }
        try:
            restored = self._restore(step, template)
        except Exception:
            # Backward-compatible retries: snapshots predating leaves the
            # CURRENT template carries (it_inexact; ema_params when the
            # user enables EMA on an existing run; the health carry when a
            # TrainingHealthGuard is newly attached) restore against a
            # template without those leaves, then the new leaves re-seed.
            # The snapshot may be missing ANY subset, so every drop
            # combination is tried independently (dropping a leaf the
            # snapshot HAS would hit the opposite structure mismatch).
            # Ordered LEAST-destructive first (ADVICE r3): {it} costs only
            # a counter re-seed, {health} resets the guard's anomaly
            # counters, {ema} discards a trained average — if a future
            # orbax version ever tolerates an extra checkpoint subtree,
            # trying {ema} first would silently throw away a saved EMA
            # from a snapshot that merely predates it_inexact.
            ts = template["train_state"]
            optional = []
            if "it_inexact" in template["loop"]:
                optional.append("it")
            if getattr(ts, "health", None) is not None:
                optional.append("health")
            if getattr(ts, "ema_params", None) is not None:
                optional.append("ema")
            drop_sets = [
                set(c)
                for k in range(1, len(optional) + 1)
                for c in itertools.combinations(optional, k)
            ]
            if not drop_sets:
                raise
            restored = None
            dropped = set()
            for drops in drop_sets:
                ts2 = ts
                if "ema" in drops:
                    ts2 = ts2.replace(ema_params=None)
                if "health" in drops:
                    ts2 = ts2.replace(health=None)
                t2 = {
                    "train_state": ts2,
                    "loop": (
                        {k: v for k, v in template["loop"].items()
                         if k != "it_inexact"}
                        if "it" in drops else template["loop"]
                    ),
                }
                try:
                    restored = self._restore(step, t2)
                    dropped = drops
                    break
                except Exception:
                    continue
            if restored is None:
                raise
            if "ema" in dropped:
                # Seed the average from the restored params (the same
                # no-debias init a fresh EMA run uses), in fp32.
                rs = restored["train_state"]
                restored["train_state"] = rs.replace(
                    ema_params=jax.tree_util.tree_map(
                        lambda p: np.asarray(p, np.float32), rs.params
                    )
                )
            if "health" in dropped:
                # Fresh guard counters, exactly as a first bind seeds them.
                restored["train_state"] = restored["train_state"].replace(
                    health=np.zeros(3, np.float32)
                )
        new_state = restored["train_state"]
        # Re-place on the communicator's mesh, honoring each INPUT leaf's
        # sharding (ZeRO states carry 1/N shards — blanket replication would
        # momentarily materialize N full copies).  Orbax may hand back leaves
        # with mixed placements (single-device scalars vs mesh arrays), which
        # jit rejects; leaves whose input sharding is unknown replicate.
        from jax.sharding import NamedSharding

        def _replace(restored_leaf, input_leaf):
            sh = getattr(input_leaf, "sharding", None)
            # Only mesh shardings count — single-device placements (fresh
            # uncommitted scalars like `step`) must re-replicate or jit sees
            # mixed device sets.
            if isinstance(sh, NamedSharding):
                return jax.device_put(restored_leaf, sh)
            if hasattr(self.comm, "replicate"):
                return self.comm.replicate(restored_leaf)
            return restored_leaf

        new_state = jax.tree_util.tree_map(_replace, new_state, state)
        loop = restored["loop"]
        self._apply_loop(trainer, new_state, loop)
        return new_state, int(loop["iteration"])

    def maybe_load_elastic(
        self, opt, params_template, trainer=None, model_state_template=None
    ) -> Tuple[Any, int]:
        """Elastic restore for the ZeRO tier: resume the latest snapshot even
        when it was saved under a DIFFERENT device count.

        The reference's checkpointer was restart-based with a fixed world
        size (SURVEY §2.8); ZeRO state is padded per device count, so the
        template path of :meth:`maybe_load` cannot reshard it.  This restores
        template-free and re-lays the state onto ``opt``'s mesh via
        :func:`chainermn_tpu.optimizers.zero.reshard_zero_state`.

        ``opt`` is the target :class:`ZeroMultiNodeOptimizer`;
        ``params_template`` a logical parameter pytree (e.g. a fresh
        ``model.init``).  Returns ``(state, iteration)`` — a fresh
        ``opt.init(params_template)`` state when no checkpoint exists.
        """
        import orbax.checkpoint as ocp

        from chainermn_tpu.optimizers.zero import reshard_zero_state

        step = self._mngr.latest_step()
        if step is None:
            return (
                opt.init(
                    params_template, model_state=model_state_template
                ),
                0,
            )
        # Restore to HOST numpy via a metadata-derived template: a
        # template-free restore (and the manager's own item_metadata, which
        # is None on a fresh manager) would rebuild the SAVED device
        # topology — orbax pins shardings to device ids, which by
        # definition no longer exist when the world size changed.  The
        # array metadata tree (shapes/dtypes only) lives under the step's
        # item directory; numpy leaves in the template force a host-RAM
        # restore with no device placement at all.
        item_dir = os.path.join(self._dir, str(step), "default")
        meta = ocp.StandardCheckpointer().metadata(item_dir)
        # Orbax moved the tree around across versions: current wraps it as
        # .item_metadata.tree, 0.7.x returns the metadata pytree directly.
        if hasattr(meta, "item_metadata"):
            meta = meta.item_metadata
        meta = getattr(meta, "tree", meta)
        template = jax.tree_util.tree_map(
            lambda m: np.zeros(m.shape, m.dtype), meta
        )
        raw = self._restore(step, template)
        new_state = reshard_zero_state(
            raw["train_state"], opt, params_template,
            model_state_template=model_state_template,
        )
        loop = raw["loop"]
        self._apply_loop(trainer, new_state, loop)
        return new_state, int(loop["iteration"])

    def _apply_loop(self, trainer, new_state, loop) -> None:
        apply_loop_state(trainer, new_state, loop)

    # ------------------------------------------------- known-good ring
    # (training-health guard rollback recovery — see resilience/guard.py)
    def mark_known_good_upto(self, iteration: int) -> List[int]:
        """Bless every saved snapshot step ≤ ``iteration`` not yet in the
        ring.  Called by the guard after a CLEAN consistency vote at that
        iteration: a vote only vouches for state it actually inspected, so
        snapshots from the future (or from before a rollback) never enter.
        Flushes in-flight async commits first so every rank blesses the
        same step set — skipped (deterministically: the gate depends only
        on rank-invariant state) when no save since the newest blessed
        step means there is nothing new to flush or bless.  Returns the
        newly blessed steps."""
        newest_blessed = max(self._known_good, default=None)
        if self._last_saved_step is None or (
            newest_blessed is not None
            and self._last_saved_step <= newest_blessed
        ):
            return []
        self._mngr.wait_until_finished()
        eligible = sorted(
            int(s) for s in self._mngr.all_steps() if s <= int(iteration)
        )
        # Only the newest ring-capacity's worth: blessing older steps just
        # to evict them immediately would make the return value (and the
        # persisted ring) churn.
        new = []
        for s in eligible[-self._known_good.maxlen:]:
            if s not in self._known_good:
                self._known_good.append(s)
                new.append(s)
        if new:
            self._persist_known_good()
        return new

    def latest_known_good(self) -> Optional[int]:
        """Newest step that survived a clean consistency vote AND still
        exists on disk (orbax's ``max_to_keep`` gc may have reaped an old
        blessed step), or None when no rollback target exists."""
        on_disk = {int(s) for s in self._mngr.all_steps()}
        good = [s for s in self._known_good if s in on_disk]
        return max(good) if good else None

    def known_good_steps(self) -> List[int]:
        return sorted(self._known_good)

    def discard_after(self, step: int) -> List[int]:
        """Delete every snapshot NEWER than ``step`` — they were taken on
        (potentially) poisoned state between the last blessing vote and an
        escalation.  Collective: call on every rank together — orbax's
        ``delete`` is itself a cross-process op (the primary host removes
        the directory, then ALL processes barrier-sync), so gating it to
        one rank would deadlock that rank in the sync.  The re-run of the
        rolled-back iterations re-saves those steps cleanly.  Returns the
        deleted steps."""
        self._mngr.wait_until_finished()
        doomed = sorted(int(s) for s in self._mngr.all_steps() if s > step)
        fell_back = False
        for s in doomed:
            try:
                self._mngr.delete(s)
            except Exception:
                # Last-resort path (orbax sync hiccup): the primary
                # removes the directory; the barrier below resynchronizes
                # and reload() refreshes every rank's step cache.
                fell_back = True
                if jax.process_index() == 0:
                    shutil.rmtree(
                        os.path.join(self._dir, str(s)), ignore_errors=True
                    )
        while self._known_good and max(self._known_good) > step:
            self._known_good.remove(max(self._known_good))
        if self._last_saved_step is not None:
            self._last_saved_step = min(self._last_saved_step, int(step))
        self._persist_known_good()
        if fell_back:
            if jax.process_count() > 1 and hasattr(self.comm, "barrier"):
                self.comm.barrier()
            try:
                self._mngr.reload()
            except AttributeError:  # pragma: no cover - pre-reload orbax
                pass
        return doomed

    def _known_good_path(self) -> str:
        return os.path.join(self._dir, "known_good.json")

    def _load_known_good(self) -> List[int]:
        try:
            with open(self._known_good_path()) as f:
                return [int(s) for s in json.load(f)["steps"]]
        except Exception:
            return []

    def _persist_known_good(self) -> None:
        if jax.process_index() != 0:
            return
        try:
            tmp = self._known_good_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"steps": sorted(self._known_good)}, f)
            os.replace(tmp, self._known_good_path())
        except OSError:  # best-effort: the ring also lives in memory
            pass

    # ------------------------------------------------------------------ misc
    def all_steps(self):
        return list(self._mngr.all_steps())

    def finalize(self, trainer=None):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.wait_until_finished()
        self._mngr.close()


def create_multi_node_checkpointer(
    name: str,
    comm,
    path: str = "checkpoints",
    max_to_keep: int = 5,
    trigger=(1, "epoch"),
    async_save: bool = True,
    known_good_keep: int = 3,
) -> MultiNodeCheckpointer:
    """Reference anchor: ``create_multi_node_checkpointer(name, comm)``.

    ``async_save=False`` commits synchronously at the trigger — use when a
    crash immediately after the trigger must still find that snapshot
    complete (fault-injection tests; final pre-shutdown saves).

    ``known_good_keep`` bounds the ring of vote-blessed snapshots kept for
    the training-health guard's rollback recovery (``docs/resilience.md``);
    it should not exceed ``max_to_keep`` or blessed steps may already be
    garbage-collected when a rollback wants them."""
    return MultiNodeCheckpointer(
        name, comm, path=path, max_to_keep=max_to_keep, trigger=trigger,
        async_save=async_save, known_good_keep=known_good_keep,
    )
