"""Corpus BLEU from exactly-summable per-sentence statistics.

Reference anchor: the ChainerMN seq2seq example's "BLEU eval via multi-node
evaluator" (``examples/seq2seq/seq2seq.py`` — SURVEY.md §2.9).  BLEU is a
*corpus-level* metric: clipped n-gram match counts, n-gram totals, and
candidate/reference lengths are summed over the whole corpus and only then
combined through the nonlinear BLEU formula — averaging per-sentence BLEU
(what a naive per-example evaluator would do) is a different, wrong number.

Split accordingly:

* :func:`bleu_stats` — traced, in-graph: per-sentence stat vectors, safe to
  mask-sum across devices (``lax.psum``) and batches.  Fully vectorized
  (window-comparison counting, no Python loops over tokens) so it runs inside
  the jitted eval step.
* :func:`bleu_from_stats` — host-side finalize on the summed stats.

Used through :class:`chainermn_tpu.extensions.Evaluator`'s ``finalize``
hook, which the multi-node wrapper sum-reduces across processes before
finalizing — bitwise the same result as a single-process pass over the whole
corpus.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from chainermn_tpu.datasets.seq import BOS, EOS, PAD

MAX_N = 4


def _clipped_ngram_counts(cand, cand_mask, ref, ref_mask, n):
    """Vectorized clipped n-gram matching.

    For every valid candidate window i with gram g_i: its contribution is
    ``min(c_i, r_i) / c_i`` where c_i / r_i count occurrences of g_i among
    valid candidate / reference windows — summing over the c_i instances of a
    gram yields the standard clipped count ``min(c, r)`` per distinct gram.
    """
    T = cand.shape[1]
    W = T - n + 1
    if W <= 0:
        B = cand.shape[0]
        return jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.float32)
    idx = jnp.arange(W)[:, None] + jnp.arange(n)[None, :]
    cg = cand[:, idx]  # (B, W, n)
    rg = ref[:, idx]
    cm = cand_mask[:, idx].min(-1)  # (B, W): window fully inside the sentence
    rm = ref_mask[:, idx].min(-1)
    eq_cr = (cg[:, :, None, :] == rg[:, None, :, :]).all(-1)  # (B, W, W)
    eq_cc = (cg[:, :, None, :] == cg[:, None, :, :]).all(-1)
    r_i = (eq_cr * rm[:, None, :]).sum(-1)
    c_i = (eq_cc * cm[:, None, :]).sum(-1)
    contrib = jnp.where(cm > 0, jnp.minimum(c_i, r_i) / jnp.maximum(c_i, 1.0), 0.0)
    return contrib.sum(-1), cm.sum(-1)


def bleu_stats(pred, ref) -> Dict[str, jnp.ndarray]:
    """Per-sentence BLEU statistics (each a float32 ``(B,)`` vector).

    ``pred``: decoded token ids (B, T) — the candidate runs until its first
    EOS/PAD/BOS.  ``ref``: PAD-padded reference ids (B, T).  Keys:
    ``bleu_match_n`` / ``bleu_total_n`` for n = 1..4, ``bleu_cand_len``,
    ``bleu_ref_len``.
    """
    if pred.shape != ref.shape:
        # The shared window index is built from pred's width; JAX clamps
        # out-of-bounds gathers silently, which would fabricate (or drop)
        # reference n-grams instead of erroring — pad both to one width.
        raise ValueError(
            f"pred {pred.shape} and ref {ref.shape} must be padded to the "
            "same shape"
        )
    pred = pred.astype(jnp.int32)
    ref = ref.astype(jnp.int32)
    stop = (pred == EOS) | (pred == PAD) | (pred == BOS)
    cand_mask = jnp.cumprod(1 - stop.astype(jnp.float32), axis=1)
    # References may carry a trained EOS terminator; BLEU compares content
    # tokens only (the candidate is likewise truncated BEFORE its EOS).
    ref_mask = ((ref != PAD) & (ref != EOS) & (ref != BOS)).astype(jnp.float32)
    out = {
        "bleu_cand_len": cand_mask.sum(-1),
        "bleu_ref_len": ref_mask.sum(-1),
    }
    for n in range(1, MAX_N + 1):
        m, t = _clipped_ngram_counts(pred, cand_mask, ref, ref_mask, n)
        out[f"bleu_match_{n}"] = m
        out[f"bleu_total_{n}"] = t
    return out


def bleu_from_stats(sums: Dict[str, float], smooth: float = 1e-9) -> float:
    """Corpus BLEU (0..100) from summed statistics: geometric mean of the
    clipped n-gram precisions with the brevity penalty."""
    logs = []
    for n in range(1, MAX_N + 1):
        match = float(sums[f"bleu_match_{n}"])
        total = float(sums[f"bleu_total_{n}"])
        if total <= 0:
            continue
        logs.append(np.log(max(match, smooth) / total))
    if not logs:
        return 0.0
    cand = max(float(sums["bleu_cand_len"]), smooth)
    ref = float(sums["bleu_ref_len"])
    bp = min(1.0, np.exp(1.0 - ref / cand))
    return float(100.0 * bp * np.exp(np.mean(logs)))


def bleu_finalize(sums: Dict[str, float], count: float) -> Dict[str, float]:
    """``Evaluator.finalize`` hook: corpus BLEU plus the raw corpus sizes."""
    return {
        "bleu": bleu_from_stats(sums),
        "bleu_cand_len": float(sums["bleu_cand_len"]),
        "bleu_ref_len": float(sums["bleu_ref_len"]),
        "n_sentences": float(count),
    }
