"""Multi-node evaluation.

Reference anchor: ``chainermn/evaluators.py — create_multi_node_evaluator``:
wraps an evaluator so the per-rank metric dict is allreduce-averaged across
ranks and rank 0 reports global validation metrics.

TPU-native: the per-device reduction happens *in-graph* (``lax.psum`` inside
the jitted eval step, riding ICI); the object-plane average across host
processes covers the multi-host case, mirroring the reference's
``allreduce_obj`` of the scalar dict.

Contract: ``metric_fn(params, batch) -> {name: per-example vector}``.  The
evaluator pads every batch to the iterator's fixed batch size (one compiled
shape, no retrace per tail batch) and aggregates with an in-graph validity
mask, so partial final batches are handled *exactly* — padded examples never
contribute.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.comm.xla import XlaCommunicator


class Evaluator:
    """Runs ``metric_fn(params, batch) -> {name: per-example values}`` over an
    iterator, exactly averaging across devices and batches (mask-weighted).

    Corpus-level metrics (BLEU-style, where statistics must be SUMMED over
    the whole corpus and only then combined nonlinearly) pass ``finalize``:
    ``finalize(sums, count) -> {name: value}`` receives the mask-exact summed
    stat dict instead of the default per-example mean.

    Multi-host contract: every process must iterate the SAME global batch
    stream (lockstep — same seed/order); the evaluator slices each padded
    global batch to this process's block itself, and the in-graph
    ``lax.psum`` over the communicator's mesh already makes every stat
    global, so the distributed result equals a single-process pass over the
    full corpus with no further host-side reduction.
    """

    def __init__(self, iterator_factory, metric_fn: Callable,
                 communicator: XlaCommunicator,
                 finalize: Optional[Callable] = None):
        # iterator_factory: callable returning a fresh non-repeating iterator
        self.iterator_factory = iterator_factory
        self.metric_fn = metric_fn
        self.comm = communicator
        self.finalize = finalize
        self._step = None

    def _eval_step(self):
        if self._step is None:
            comm = self.comm

            def body(params, batch, mask):
                m = self.metric_fn(params, batch)
                out = {}
                for k, v in m.items():
                    if v.ndim == 0:  # scalar metric: treat as batch-constant
                        v = jnp.broadcast_to(v, mask.shape)
                    out[k] = lax.psum(jnp.sum(v * mask), comm.axis_name)
                n = lax.psum(jnp.sum(mask), comm.axis_name)
                return out, n

            self._step = jax.jit(
                jax.shard_map(
                    body,
                    mesh=comm.mesh,
                    in_specs=(P(), P(comm.axes), P(comm.axes)),
                    out_specs=(P(), P()),
                    check_vma=True,
                )
            )
        return self._step

    def _pad(self, batch, size: int):
        """Pad leading dim to ``size`` by wrap-around; mask marks real rows."""
        leaves = jax.tree_util.tree_leaves(batch)
        n = leaves[0].shape[0]
        mask = np.zeros(size, np.float32)
        mask[:n] = 1.0
        if n == size:
            return batch, mask
        pad = lambda a: np.concatenate(
            [a, np.resize(a, (size - n,) + a.shape[1:])], axis=0
        )
        return jax.tree_util.tree_map(pad, batch), mask

    def evaluate_stats(self, params) -> Tuple[Dict[str, float], float]:
        """Mask-exact summed statistics + valid-example count over the
        iterator (the raw material both the mean and finalize paths share)."""
        step = self._eval_step()
        it = self.iterator_factory()
        size = getattr(it, "batch_size", None)
        sums: Dict[str, float] = {}
        count = 0.0
        nproc = jax.process_count()
        pidx = jax.process_index()
        for batch in it:
            n = jax.tree_util.tree_leaves(batch)[0].shape[0]
            target = size or n
            # Pad the GLOBAL batch to a multiple of lcm-friendly size, then
            # take this process's contiguous block — every process sees the
            # same global stream (lockstep) but contributes only its rows,
            # so no sentence is counted process_count times.
            target = -(-target // self.comm.size) * self.comm.size
            batch, mask = self._pad(batch, target)
            if nproc > 1:
                # The block split needs ranks spread evenly over processes;
                # a sub-communicator smaller than the process count would
                # silently drop rows — refuse instead.  (size % nproc == 0
                # also makes target, a multiple of size, divide by nproc.)
                if self.comm.size % nproc != 0:
                    raise ValueError(
                        f"evaluator communicator size {self.comm.size} must "
                        f"be a multiple of process_count {nproc}"
                    )
                per = target // nproc
                blk = lambda a: a[pidx * per : (pidx + 1) * per]
                batch = jax.tree_util.tree_map(blk, batch)
                mask = blk(mask)
            batch = self.comm.shard_batch(batch)
            mask = self.comm.shard_batch(mask)
            m, nvalid = step(params, batch, mask)
            for k, v in m.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            count += float(nvalid)
        return sums, count

    def evaluate(self, params) -> Dict[str, float]:
        sums, count = self.evaluate_stats(params)
        if self.finalize is not None:
            return self.finalize(sums, count)
        return {k: v / max(count, 1.0) for k, v in sums.items()}


class _MultiNodeEvaluator:
    def __init__(self, actual_evaluator, communicator):
        self.actual = actual_evaluator
        self.comm = communicator

    def evaluate(self, *args, **kw) -> Dict[str, float]:
        if getattr(self.actual, "finalize", None) is not None:
            # Corpus-level metric: the eval step's in-graph lax.psum spans
            # the communicator's whole mesh (all processes' devices), so the
            # summed stats are ALREADY global and identical on every process
            # — summing them again host-side would multiply every stat by
            # process_count.  Finalize directly.
            return self.actual.evaluate(*args, **kw)
        local = self.actual.evaluate(*args, **kw)
        # Cross-process average of per-example means: identical values on
        # every process for the same reason, so this is an identity that
        # doubles as a cheap lockstep barrier — reference shape:
        # ``allreduce_obj`` of the metric dict.
        return self.comm.allreduce_obj(local, op="mean")

    def __call__(self, *args, **kw):
        return self.evaluate(*args, **kw)

    def __getattr__(self, name):
        return getattr(self.actual, name)


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Reference anchor: ``create_multi_node_evaluator(ev, comm)``."""
    return _MultiNodeEvaluator(actual_evaluator, communicator)
