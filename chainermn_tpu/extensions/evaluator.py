"""Multi-node evaluation.

Reference anchor: ``chainermn/evaluators.py — create_multi_node_evaluator``:
wraps an evaluator so the per-rank metric dict is allreduce-averaged across
ranks and rank 0 reports global validation metrics.

TPU-native: the per-device reduction happens *in-graph* (``lax.psum`` inside
the jitted eval step, riding ICI); the object-plane average across host
processes covers the multi-host case, mirroring the reference's
``allreduce_obj`` of the scalar dict.

Contract: ``metric_fn(params, batch) -> {name: per-example vector}``.  The
evaluator pads every batch to the iterator's fixed batch size (one compiled
shape, no retrace per tail batch) and aggregates with an in-graph validity
mask, so partial final batches are handled *exactly* — padded examples never
contribute.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.comm.xla import XlaCommunicator


class Evaluator:
    """Runs ``metric_fn(params, batch) -> {name: per-example values}`` over an
    iterator, exactly averaging across devices and batches (mask-weighted)."""

    def __init__(self, iterator_factory, metric_fn: Callable,
                 communicator: XlaCommunicator):
        # iterator_factory: callable returning a fresh non-repeating iterator
        self.iterator_factory = iterator_factory
        self.metric_fn = metric_fn
        self.comm = communicator
        self._step = None

    def _eval_step(self):
        if self._step is None:
            comm = self.comm

            def body(params, batch, mask):
                m = self.metric_fn(params, batch)
                out = {}
                for k, v in m.items():
                    if v.ndim == 0:  # scalar metric: treat as batch-constant
                        v = jnp.broadcast_to(v, mask.shape)
                    out[k] = lax.psum(jnp.sum(v * mask), comm.axis_name)
                n = lax.psum(jnp.sum(mask), comm.axis_name)
                return out, n

            self._step = jax.jit(
                jax.shard_map(
                    body,
                    mesh=comm.mesh,
                    in_specs=(P(), P(comm.axes), P(comm.axes)),
                    out_specs=(P(), P()),
                    check_vma=False,
                )
            )
        return self._step

    def _pad(self, batch, size: int):
        """Pad leading dim to ``size`` by wrap-around; mask marks real rows."""
        leaves = jax.tree_util.tree_leaves(batch)
        n = leaves[0].shape[0]
        mask = np.zeros(size, np.float32)
        mask[:n] = 1.0
        if n == size:
            return batch, mask
        pad = lambda a: np.concatenate(
            [a, np.resize(a, (size - n,) + a.shape[1:])], axis=0
        )
        return jax.tree_util.tree_map(pad, batch), mask

    def evaluate(self, params) -> Dict[str, float]:
        step = self._eval_step()
        it = self.iterator_factory()
        size = getattr(it, "batch_size", None)
        sums: Dict[str, float] = {}
        count = 0.0
        for batch in it:
            n = jax.tree_util.tree_leaves(batch)[0].shape[0]
            target = size or n
            target = -(-target // self.comm.size) * self.comm.size
            batch, mask = self._pad(batch, target)
            batch = self.comm.shard_batch(batch)
            mask = self.comm.shard_batch(mask)
            m, nvalid = step(params, batch, mask)
            for k, v in m.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            count += float(nvalid)
        return {k: v / max(count, 1.0) for k, v in sums.items()}


class _MultiNodeEvaluator:
    def __init__(self, actual_evaluator, communicator):
        self.actual = actual_evaluator
        self.comm = communicator

    def evaluate(self, *args, **kw) -> Dict[str, float]:
        local = self.actual.evaluate(*args, **kw)
        # Cross-process average (identity single-process) — reference's
        # pickled allreduce_obj of the metric dict.
        return self.comm.allreduce_obj(local, op="mean")

    def __call__(self, *args, **kw):
        return self.evaluate(*args, **kw)

    def __getattr__(self, name):
        return getattr(self.actual, name)


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Reference anchor: ``create_multi_node_evaluator(ev, comm)``."""
    return _MultiNodeEvaluator(actual_evaluator, communicator)
