"""Trainer extensions: evaluation, checkpointing, fault tolerance.

Reference anchors: ``chainermn/evaluators.py``,
``chainermn/extensions/checkpoint.py``, ``chainermn/global_except_hook.py``.
"""

from chainermn_tpu.extensions.checkpoint import (
    MultiNodeCheckpointer,
    create_multi_node_checkpointer,
)
from chainermn_tpu.extensions.evaluator import (
    Evaluator,
    create_multi_node_evaluator,
)
from chainermn_tpu.extensions.bleu import (
    bleu_finalize,
    bleu_from_stats,
    bleu_stats,
)

__all__ = [
    "Evaluator",
    "create_multi_node_evaluator",
    "MultiNodeCheckpointer",
    "create_multi_node_checkpointer",
    "bleu_stats",
    "bleu_from_stats",
    "bleu_finalize",
]
