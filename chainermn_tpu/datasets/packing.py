"""Sequence packing for fixed-shape LM training.

XLA needs static shapes; variable-length documents either pad (wasting
compute on pad tokens) or PACK — several documents per row, attention kept
within each document by the flash kernel's ``segment_ids`` masking
(:func:`chainermn_tpu.ops.flash_attention`) and positions restarting per
document (:class:`~chainermn_tpu.models.TransformerLM` does this when given
``segment_ids``).  The bucketing data layer (``datasets/seq.py``) is the
padding half of that trade; this module is the packing half.

Layout per row: documents first-fit greedily into ``seq_len`` slots,
segment ids ``1, 2, …`` per document, ``0`` for the padding tail; targets
are next-token WITHIN each document (the last token of a document and all
padding get ``-1`` = ignore, matching ``lm_loss``'s contract).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def pack_sequences(
    docs: Sequence[np.ndarray],
    seq_len: int,
    drop_overlong: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack token documents into fixed ``(N, seq_len)`` rows.

    Args:
      docs: int token arrays (1-D, any lengths ≥ 1).
      seq_len: row width.
      drop_overlong: documents longer than ``seq_len`` are split into
        ``seq_len``-sized pieces (default) or dropped.  Split pieces get
        independent segment ids, so each piece attends only within itself
        — boundary predictions across a split are context-truncated.

    Returns ``(tokens, targets, segment_ids)``, each ``(N, seq_len)`` int32:
    padding tokens are 0 with segment id 0 and target −1.
    """
    import bisect

    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    # Per-doc targets computed BEFORE any splitting, so a split piece keeps
    # the true next-token target at its boundary (only the document's final
    # token is unsupervised).  Each piece gets its OWN segment id and is
    # placed independently, so a piece attends only within itself: the
    # boundary prediction (last token of piece i → first token of piece
    # i+1) is trained with zero context from the preceding piece — the
    # standard truncated-context approximation, not full-context training
    # of overlong documents.
    pieces: List[Tuple[np.ndarray, np.ndarray]] = []
    for d in docs:
        d = np.asarray(d, np.int32).reshape(-1)
        if len(d) == 0:
            continue
        tgt = np.concatenate([d[1:], np.array([-1], np.int32)])
        if len(d) > seq_len:
            if drop_overlong:
                continue
            pieces.extend(
                (d[i : i + seq_len], tgt[i : i + seq_len])
                for i in range(0, len(d), seq_len)
            )
        else:
            pieces.append((d, tgt))
    # Best-fit decreasing with a bisect-maintained free-space index:
    # near-optimal fill, deterministic layout, O(n log n).
    pieces.sort(key=lambda p: len(p[0]), reverse=True)
    rows: List[List[Tuple[np.ndarray, np.ndarray]]] = []
    free: List[Tuple[int, int]] = []  # sorted (free_space, row) pairs
    for p in pieces:
        L = len(p[0])
        j = bisect.bisect_left(free, (L, -1))
        if j < len(free):
            space, r = free.pop(j)
            rows[r].append(p)
            if space > L:
                bisect.insort(free, (space - L, r))
        else:
            rows.append([p])
            if seq_len > L:
                bisect.insort(free, (seq_len - L, len(rows) - 1))

    n = len(rows)
    tokens = np.zeros((n, seq_len), np.int32)
    targets = np.full((n, seq_len), -1, np.int32)
    seg = np.zeros((n, seq_len), np.int32)
    for r, row_docs in enumerate(rows):
        at = 0
        for s, (d, tg) in enumerate(row_docs, start=1):
            L = len(d)
            tokens[r, at : at + L] = d
            targets[r, at : at + L] = tg
            seg[r, at : at + L] = s
            at += L
    return tokens, targets, seg


def pack_pairs(
    pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
    src_len: int,
    tgt_len: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack (src, tgt) sentence pairs into fixed-shape rows for seq2seq.

    The NMT counterpart of :func:`pack_sequences` (the reference's ragged
    minibatches — ``examples/seq2seq/seq2seq.py`` — under XLA's static
    shapes): pair *j* of a row gets the SAME segment id on both sides, so
    encoder self-attention isolates source sentences, decoder
    self-attention isolates target sentences, and cross-attention matches
    each target to exactly its own source
    (``TransformerSeq2Seq(…, src_seg=…, tgt_seg=…)``).

    A pair is placed only where BOTH sides fit (best-fit decreasing on the
    combined length); pairs overlong on either side are dropped (sentence
    pairs cannot be split the way LM documents can).

    Returns ``(src, tgt, src_seg, tgt_seg)``, each ``(N, {src,tgt}_len)``
    int32; padding is token 0 with segment id 0.
    """
    usable = []
    for s, t in pairs:
        s = np.asarray(s, np.int32).reshape(-1)
        t = np.asarray(t, np.int32).reshape(-1)
        if 0 < len(s) <= src_len and 0 < len(t) <= tgt_len:
            usable.append((s, t))
    usable.sort(key=lambda p: len(p[0]) + len(p[1]), reverse=True)
    rows: List[List[Tuple[np.ndarray, np.ndarray]]] = []
    space: List[Tuple[int, int]] = []  # per-row (src_free, tgt_free)
    for s, t in usable:
        best, best_slack = None, None
        for r, (fs, ft) in enumerate(space):
            if len(s) <= fs and len(t) <= ft:
                slack = (fs - len(s)) + (ft - len(t))
                if best is None or slack < best_slack:
                    best, best_slack = r, slack
        if best is None:
            rows.append([(s, t)])
            space.append((src_len - len(s), tgt_len - len(t)))
        else:
            rows[best].append((s, t))
            fs, ft = space[best]
            space[best] = (fs - len(s), ft - len(t))

    n = len(rows)
    src = np.zeros((n, src_len), np.int32)
    tgt = np.zeros((n, tgt_len), np.int32)
    sseg = np.zeros((n, src_len), np.int32)
    tseg = np.zeros((n, tgt_len), np.int32)
    for r, row_pairs in enumerate(rows):
        at_s = at_t = 0
        for j, (s, t) in enumerate(row_pairs, start=1):
            src[r, at_s:at_s + len(s)] = s
            sseg[r, at_s:at_s + len(s)] = j
            at_s += len(s)
            tgt[r, at_t:at_t + len(t)] = t
            tseg[r, at_t:at_t + len(t)] = j
            at_t += len(t)
    return src, tgt, sseg, tseg


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of non-padding slots (segment id != 0)."""
    seg = np.asarray(segment_ids)
    return float((seg != 0).mean()) if seg.size else 0.0
