"""Sequence data utilities: bucketing + padding for static XLA shapes.

The reference's seq2seq example fed ragged per-sentence arrays through eager
MPI (``examples/seq2seq/seq2seq.py``); XLA requires static shapes, so this
module provides the TPU-native replacement (SURVEY.md §7): group sentence
pairs into length buckets, pad each bucket to its ceiling, and emit
fixed-shape batches whose padding overhead is bounded by the bucket width.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# Token sentinels — single source of truth (models/seq2seq.py imports these).
PAD = 0
BOS = 1
EOS = 2


def pad_to(arr: Sequence[int], length: int) -> np.ndarray:
    out = np.full(length, PAD, np.int32)
    out[: len(arr)] = arr
    return out


def bucket_batches(
    pairs: Sequence[Tuple[Sequence[int], Sequence[int]]],
    batch_size: int,
    bucket_width: int = 8,
    max_len: int = 64,
    seed: int = 0,
    drop_incomplete: bool = True,
    keep_tail: bool = False,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Group (src, tgt) token-id pairs into length buckets; return a list of
    ``(src_batch, tgt_batch)`` int32 arrays, each padded to its bucket
    ceiling.  Non-pad fraction stays ≥ (width-1)/width per bucket by
    construction (the BASELINE.md "no pathological padding" target).

    Tail policy for a bucket's final short chunk: drop it
    (``drop_incomplete=True``, training default — keeps one compiled shape),
    wrap-fill with duplicates (``drop_incomplete=False``), or emit it short
    (``keep_tail=True``, overrides both) for evaluation flows whose masking
    must see each sentence exactly once (corpus BLEU)."""
    rng = np.random.RandomState(seed)
    buckets: dict = {}
    for s, t in pairs:
        if len(s) > max_len or len(t) > max_len:
            trunc_t = list(t[:max_len])
            # Truncation must not strip a trained EOS terminator — losing it
            # reintroduces the untrained-termination/deflated-BLEU failure
            # (see make_synthetic_translation).
            if len(t) > max_len and t[-1] == EOS:
                trunc_t[-1] = EOS
            s, t = s[:max_len], trunc_t
        key = (
            -(-max(len(s), 1) // bucket_width) * bucket_width,
            -(-max(len(t), 1) // bucket_width) * bucket_width,
        )
        buckets.setdefault(key, []).append((s, t))
    batches = []
    for (ls, lt), items in sorted(buckets.items()):
        order = rng.permutation(len(items))
        for i in range(0, len(items), batch_size):
            chunk = [items[j] for j in order[i : i + batch_size]]
            if len(chunk) < batch_size and not keep_tail:
                if drop_incomplete:
                    continue
                # cyclic wrap-fill so even buckets smaller than batch_size
                # reach the full static shape
                pool = [items[j] for j in order]
                need = batch_size - len(chunk)
                chunk += [pool[j % len(pool)] for j in range(need)]
            src = np.stack([pad_to(s, ls) for s, _ in chunk])
            tgt = np.stack([pad_to(t, lt) for _, t in chunk])
            batches.append((src, tgt))
    rng.shuffle(batches)
    return batches


def save_translation_npz(path, pairs) -> None:
    """Persist ragged (src, tgt) token-id pairs as a flat offsets-format
    ``.npz`` (``{src,tgt}_tokens`` concatenated int32 + ``{src,tgt}_offsets``
    int64 prefix bounds) — the zero-copy on-disk corpus format for
    :func:`load_translation_npz` (the reference streamed WMT text files;
    token arrays are the XLA-era equivalent)."""
    src_tok = np.concatenate(
        [np.asarray(s, np.int32) for s, _ in pairs]
    ) if pairs else np.zeros(0, np.int32)
    tgt_tok = np.concatenate(
        [np.asarray(t, np.int32) for _, t in pairs]
    ) if pairs else np.zeros(0, np.int32)
    src_off = np.cumsum([0] + [len(s) for s, _ in pairs]).astype(np.int64)
    tgt_off = np.cumsum([0] + [len(t) for _, t in pairs]).astype(np.int64)
    np.savez(path, src_tokens=src_tok, src_offsets=src_off,
             tgt_tokens=tgt_tok, tgt_offsets=tgt_off)


def load_translation_npz(path) -> List[Tuple[List[int], List[int]]]:
    """Inverse of :func:`save_translation_npz`: returns the list of
    ``(src, tgt)`` token-id pairs ready for :func:`bucket_batches`."""
    with np.load(path) as d:
        st, so = d["src_tokens"], d["src_offsets"]
        tt, to = d["tgt_tokens"], d["tgt_offsets"]
    if len(so) != len(to):
        raise ValueError(
            f"src/tgt pair counts disagree: {len(so) - 1} vs {len(to) - 1}"
        )
    return [
        (st[so[i]:so[i + 1]].tolist(), tt[to[i]:to[i + 1]].tolist())
        for i in range(len(so) - 1)
    ]


def make_synthetic_translation(
    n: int = 2048,
    vocab: int = 50,
    min_len: int = 3,
    max_len: int = 24,
    seed: int = 0,
) -> List[Tuple[List[int], List[int]]]:
    """Deterministic learnable "translation": target = reversed source with a
    +3 vocab shift (PAD/BOS/EOS reserved), terminated with EOS so the decoder
    LEARNS to stop — without a trained EOS, greedy decoding runs to the
    bucket ceiling with unconstrained logits and BLEU is deflated by
    padding-length garbage.  Stand-in for the reference's WMT data in the
    zero-egress environment."""
    rng = np.random.RandomState(seed)
    pairs = []
    for _ in range(n):
        L = rng.randint(min_len, max_len + 1)
        src = rng.randint(3, vocab, size=L).tolist()
        tgt = [((w - 3 + 1) % (vocab - 3)) + 3 for w in reversed(src)] + [EOS]
        pairs.append((src, tgt))
    return pairs
