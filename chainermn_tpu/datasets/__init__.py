"""Data distribution.

Reference anchors: ``chainermn/datasets/scatter_dataset.py — scatter_dataset``
(root permutes indices, splits into near-equal slices, MPI-scatters shards)
and ``chainermn/datasets/empty_dataset.py — create_empty_dataset``.

TPU-native design: two-level sharding.  Level 1 (this module) shards the
dataset across *host processes* by ``jax.process_index()`` — the analog of the
MPI scatter.  Level 2 happens at batch time: the trainer forms a per-host
global batch whose leading dim is sharded over the device mesh
(``XlaCommunicator.shard_batch``).  Single-process jobs see the whole dataset
at level 1 and shard purely at level 2, which preserves the reference's
"each of the N workers consumes 1/N of the data" contract.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


class SubDataset:
    """A view of ``dataset`` through an index list (reference analog:
    ``chainer.datasets.SubDataset`` as produced by ``scatter_dataset``)."""

    def __init__(self, dataset, indices: np.ndarray):
        self._dataset = dataset
        self._indices = np.asarray(indices)

    def __len__(self) -> int:
        return len(self._indices)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self._indices[i]]
        return self._dataset[int(self._indices[i])]

    @property
    def base(self):
        """The underlying dataset this view selects from (public accessor —
        array-aware consumers like PrefetchIterator compose index maps
        through it instead of re-gathering rows one by one)."""
        return self._dataset

    @property
    def indices(self) -> np.ndarray:
        """This view's row indices into :attr:`base`."""
        return self._indices


def scatter_dataset(
    dataset,
    comm,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
):
    """Shard ``dataset`` across host processes.

    Mirrors the reference signature ``scatter_dataset(dataset, comm, root=0,
    shuffle=False, seed=None)``.  Every process computes the same permutation
    (seeded — no communication needed, the SPMD win over the reference's
    pickled MPI scatter) and takes its own slice.  ``force_equal_length`` pads
    the tail shards by wrap-around so all processes step in lockstep, as
    collectives require.
    """
    n = len(dataset)
    order = np.arange(n)
    if shuffle:
        if seed is None:
            # Fresh randomness per call, kept identical across processes by
            # broadcasting process 0's draw (reference: root draws, scatters).
            seed = comm.bcast_obj(int(np.random.randint(0, 2**31 - 1)), root=root)
        order = np.random.RandomState(seed).permutation(n)
    nproc = max(jax.process_count(), 1)
    pidx = jax.process_index()
    per = -(-n // nproc)  # ceil
    if force_equal_length:
        padded = np.resize(order, per * nproc)  # wrap-around pad
        mine = padded[pidx * per : (pidx + 1) * per]
    else:
        mine = order[pidx * per : (pidx + 1) * per]
    return SubDataset(dataset, mine)


class _EmptyDataset:
    def __init__(self, n: int):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return ()


def create_empty_dataset(dataset):
    """Reference anchor: ``create_empty_dataset`` — placeholder of the same
    length for ranks that only do model-parallel compute."""
    return _EmptyDataset(len(dataset))


class ArrayDataset:
    """Tuple-of-arrays dataset (the ``TupleDataset`` shape the examples use)."""

    def __init__(self, *arrays: np.ndarray):
        ns = {len(a) for a in arrays}
        assert len(ns) == 1, "all arrays must share their leading dim"
        self._arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self):
        return len(self._arrays[0])

    def __getitem__(self, i):
        return tuple(a[i] for a in self._arrays)

    @property
    def arrays(self):
        return self._arrays


class NpzDataset(ArrayDataset):
    """File-backed dataset (the reference's on-disk ImageNet role,
    ``examples/imagenet/train_imagenet.py`` ``PreprocessedDataset`` over image
    files — here numpy containers, the idiomatic zero-copy format for array
    data).

    Accepts either

    * a ``.npz`` archive — members are loaded via numpy's lazy ``NpzFile``
      (each member materializes once, on open; zipped members cannot be
      memory-mapped), or
    * a directory of ``.npy`` files — each memory-mapped (``mmap_mode='r'``),
      so rows are paged from disk on access and the resident set stays at
      the OS page cache's discretion.  This is the path that exercises real
      input-pipeline pressure: the prefetch workers fault pages in while the
      chip runs the current step.

    ``keys`` orders the member arrays into the example tuple (default: the
    container's sorted key order, with ``x``/``y``-style names first when
    present).  All members must share their leading dimension.
    """

    _PREFERRED = ("x", "images", "data", "y", "labels", "targets")

    def __init__(self, path, keys=None, mmap_mode: str = "r"):
        import os

        self.path = str(path)
        if os.path.isdir(self.path):
            found = {
                fn[:-4]: os.path.join(self.path, fn)
                for fn in sorted(os.listdir(self.path))
                if fn.endswith(".npy")
            }
            if not found:
                raise ValueError(f"no .npy files in directory {self.path}")
            keys = keys or self._order_keys(found)
            arrays = [np.load(found[k], mmap_mode=mmap_mode) for k in keys]
        else:
            with np.load(self.path) as npz:  # members materialize here;
                # close the zip handle rather than hold it for our lifetime
                keys = keys or self._order_keys(npz.files)
                arrays = [npz[k] for k in keys]
        self.keys = tuple(keys)
        ns = {len(a) for a in arrays}
        if len(ns) != 1:
            raise ValueError(
                f"members {self.keys} disagree on leading dim: "
                f"{[len(a) for a in arrays]}"
            )
        # Bypass ArrayDataset.__init__'s np.asarray (it would materialize a
        # memory-mapped member into RAM); np.memmap is already an ndarray.
        self._arrays = tuple(arrays)

    @classmethod
    def _order_keys(cls, names):
        names = sorted(names)
        pref = [k for k in cls._PREFERRED if k in names]
        return pref + [k for k in names if k not in pref]


def make_synthetic_classification(
    n: int = 4096,
    dim: int = 784,
    classes: int = 10,
    seed: int = 0,
    noise: float = 0.3,
    task_seed: int = 1234,
) -> ArrayDataset:
    """Deterministic learnable classification task (MNIST stand-in for the
    zero-egress environment: class = argmax of a fixed random projection plus
    noise).  ``task_seed`` fixes the projection (the "task"); ``seed`` draws
    the samples — so train/val splits share a task but not samples.
    Examples/tests use this where the reference used MNIST."""
    proj = (
        np.random.RandomState(task_seed)
        .normal(size=(dim, classes))
        .astype(np.float32)
    )
    rng = np.random.RandomState(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    logits = x @ proj + noise * rng.normal(size=(n, classes)).astype(np.float32)
    y = np.argmax(logits, axis=1).astype(np.int32)
    return ArrayDataset(x, y)

from chainermn_tpu.datasets.packing import (  # noqa: E402
    pack_pairs,
    pack_sequences,
    packing_efficiency,
)
from chainermn_tpu.datasets.seq import bucket_batches  # noqa: E402
