"""Minimal trainer loop.

The reference delegates its loop to Chainer's ``Trainer``/``StandardUpdater``
(see SURVEY.md §3.2); examples attach ``LogReport``/``PrintReport``/
``ProgressBar`` on rank 0 only.  This module provides just enough of that
shape for the stock example structure to run: a Trainer driving the jitted
SPMD update, interval-triggered extensions, and rank-0-gated reporting
(``jax.process_index() == 0`` — the SPMD analog of ``if comm.rank == 0:`` in
every reference example).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import sys
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from chainermn_tpu import observability as _obs
from chainermn_tpu.observability import aggregate as _oagg
from chainermn_tpu.observability import flight as _oflight
from chainermn_tpu.observability import metrics as _omet
from chainermn_tpu.observability import tracing as _otrace
from chainermn_tpu.resilience import faults as _faults


#: True while a ProgressBar \r-line is open on stderr; printers that emit
#: full lines (LogReport) break the line first so output never interleaves.
_progress_line_open = False


def _close_progress_line():
    global _progress_line_open
    if _progress_line_open:
        print(file=sys.stderr, flush=True)
        _progress_line_open = False


class Extension:
    """An interval-triggered trainer hook (Chainer extension analog)."""

    def __init__(self, fn: Callable, trigger: Tuple[int, str] = (1, "epoch"),
                 name: Optional[str] = None):
        self.fn = fn
        self.interval, self.unit = trigger
        assert self.unit in ("epoch", "iteration")
        self.name = name or getattr(fn, "__name__", "extension")
        self._last_fired = 0

    def should_fire(self, trainer: "Trainer") -> bool:
        tick = trainer.epoch if self.unit == "epoch" else trainer.iteration
        if tick // self.interval > self._last_fired // self.interval:
            self._last_fired = tick
            return True
        return False

    def __call__(self, trainer: "Trainer"):
        return self.fn(trainer)

    def finalize(self, trainer: "Trainer"):
        """Called once when training ends; default no-op (LogReport flushes
        its pending window here so a mid-epoch stop still reports)."""


def make_extension(trigger=(1, "epoch"), name=None):
    def deco(fn):
        return Extension(fn, trigger=trigger, name=name)
    return deco


class LogReport(Extension):
    """Collects metric means per interval; prints/records on rank 0 only."""

    def __init__(self, trigger=(1, "epoch"), out: Optional[str] = None,
                 print_report: bool = True):
        super().__init__(self._fire, trigger=trigger, name="LogReport")
        self.log: List[dict] = []
        self._out = out
        self._print = print_report
        self._t0 = time.time()

    def _fire(self, trainer: "Trainer"):
        window = trainer.drain_observations()
        if not window:
            return
        # Device arrays are converted to floats only here, at the trigger
        # interval — the hot loop never blocks on metric values.
        means = {k: float(np.mean([np.asarray(o[k]) for o in window if k in o]))
                 for k in window[-1]}
        entry = {
            "epoch": trainer.epoch,
            "iteration": trainer.iteration,
            "elapsed_time": time.time() - self._t0,
            **means,
        }
        self.log.append(entry)
        self._report(means, entry)

    def finalize(self, trainer: "Trainer"):
        self._fire(trainer)

    def _report(self, means, entry):
        if jax.process_index() == 0:
            if self._print:
                _close_progress_line()
                parts = [f"epoch {entry['epoch']}", f"iter {entry['iteration']}"]
                parts += [f"{k} {v:.4f}" for k, v in means.items()]
                print("  ".join(parts), flush=True)
            if self._out:
                os.makedirs(os.path.dirname(self._out) or ".", exist_ok=True)
                with open(self._out, "w") as f:
                    json.dump(self.log, f, indent=1)


class MetricsReport(Extension):
    """Observability counterpart of :class:`LogReport`: publishes the
    newest step metrics into the per-rank registry, writes a per-rank
    JSONL feed, and (collectively) ships the same entry to rank 0's
    merged feed over the host object plane.

    Where :class:`LogReport` prints rank-0 interval means and discards the
    rest, this extension keeps every rank's view: each tick it

    1. converts the trainer's newest metrics to floats (at the trigger
       interval only — the hot loop never syncs on metric values, same
       policy as LogReport) and sets them as ``train.<name>`` gauges;
    2. takes a stamped registry sample (the flight recorder's last-K ring);
    3. appends ``{"step", "rank", "metrics", "registry"}`` to
       ``<out_dir>/metrics.rank<R>.jsonl``;
    4. with a communicator, gathers every rank's entry to rank 0, which
       appends one merged line to ``<out_dir>/metrics.merged.jsonl``
       (``per_rank`` carries each entry verbatim — byte-comparable with
       the per-rank feeds) and optionally a Prometheus textfile
       (see :class:`~chainermn_tpu.observability.MetricsAggregator`).

    The gather is a collective: attach with the same ``trigger`` on every
    rank (interval triggers fire at identical iterations by construction).
    ``CMN_OBS=0`` turns the whole extension into a no-op — set it for the
    *job*, never for a subset of ranks, or the enabled ranks block in a
    gather the disabled ones skip.

    Fleet plane (``docs/observability.md`` "Fleet tracing"): with
    ``fleet_trace`` set, the first tick runs an NTP-style clock sync
    over the host object plane (re-run every ``fleet_resync`` ticks to
    track drift), and ``finalize`` gathers every rank's span ring to
    rank 0 and writes ONE offset-corrected, Perfetto-loadable merged
    trace at that path — collective spans aligned across ranks,
    ``fleet.collective_skew_ms`` / ``fleet.straggler_rank`` published.
    Both steps are collectives on the same cadence contract as the
    metrics gather.  ``memory=True`` (default) also publishes the
    ``mem.*`` device watermarks each tick, so the merged feed carries
    HBM alongside step time.  ``device=True`` (opt-in — the one-time
    cost capture re-lowers the step) publishes the train step's
    ``device.*`` MFU/roofline gauges each tick from the compile
    watcher's cost model (``docs/observability.md`` "Device roofline").

    Incident plane (``docs/observability.md`` "Incidents"): each tick
    also evaluates the process
    :class:`~chainermn_tpu.observability.incident.IncidentManager`'s
    watch rules against the live registry — a breaching headline signal
    (straggler named, compile budget blown, KV leak) captures ONE
    deduplicated debug bundle at that moment, per-rank and host-side
    only.
    """

    def __init__(self, comm=None, trigger=(10, "iteration"),
                 out_dir: str = "obs", prometheus: bool = False,
                 aggregate: bool = True, memory: bool = True,
                 device: bool = False,
                 fleet_trace: Optional[str] = None,
                 fleet_probes: int = 8, fleet_resync: int = 64):
        super().__init__(self._fire, trigger=trigger, name="MetricsReport")
        self.comm = comm
        self.out_dir = out_dir
        self._rank = int(getattr(comm, "rank", 0)) if comm is not None \
            else int(jax.process_index())
        self._agg = (
            _oagg.MetricsAggregator(comm, out_dir=out_dir,
                                    prometheus=prometheus)
            if aggregate else None
        )
        self._last_step: Optional[int] = None
        self._memory = bool(memory)
        self._mem_monitor = None
        #: Device/compile plane (PR 11): each tick, publish the train
        #: step's ``device.*`` MFU/roofline gauges from the compile
        #: watcher's captured cost model and the mean ``train.step_ms``
        #: since the last tick.  Opt-in: the one-time cost capture
        #: lowers the step program once more, which on a big model is a
        #: real compile.
        self._device = bool(device)
        self._dev_last = (0.0, 0)  # (sum_ms, count) of train.step_ms
        self.fleet_trace = fleet_trace
        self._fleet_probes = int(fleet_probes)
        self._fleet_resync = max(int(fleet_resync), 1)
        self._fleet_clock = None
        self._fires = 0

    @property
    def rank_path(self) -> str:
        return os.path.join(self.out_dir, f"metrics.rank{self._rank}.jsonl")

    def _fire(self, trainer: "Trainer"):
        if not _obs.enabled():
            return
        it = int(trainer.iteration)
        if it == self._last_step:  # finalize after an on-trigger last step
            return
        self._last_step = it
        self._fires += 1
        # Fleet clock: startup sync on the first tick, re-sync on a slow
        # cadence (drift tracking).  Collective — same-iteration firing
        # on every rank is the extension's existing contract.
        if self.fleet_trace is not None and (
                self._fleet_clock is None
                or self._fires % self._fleet_resync == 0):
            from chainermn_tpu.observability import fleet as _ofleet

            if self._fleet_clock is None:
                self._fleet_clock = _ofleet.FleetClock(
                    self.comm, probes=self._fleet_probes
                )
            self._fleet_clock.sync()
        # Device-memory watermarks land as gauges BEFORE the registry
        # sample below, so this tick's feed line carries them.
        if self._memory:
            if self._mem_monitor is None:
                from chainermn_tpu.observability import memory as _omem

                self._mem_monitor = _omem.MemoryMonitor()
            self._mem_monitor.sample()
        # Device-plane roofline gauges for the train step, from the
        # compile watcher's cost model + the step-time histogram's delta
        # since the last tick — landed BEFORE the registry sample so
        # this tick's feed line carries them (like the memory gauges).
        if self._device:
            self._publish_device_gauges()
        means = {}
        if trainer.last_metrics is not None:
            for k, v in trainer.last_metrics.items():
                try:
                    means[k] = float(np.asarray(v))
                except (TypeError, ValueError):
                    continue
        reg = _omet.registry()
        for k, v in means.items():
            reg.gauge(f"train.{k}").set(v)
        sample = reg.sample(it)
        entry = {
            "step": it,
            "rank": self._rank,
            "metrics": means,
            "registry": sample["metrics"],
        }
        os.makedirs(self.out_dir, exist_ok=True)
        # Same strict-JSON sanitization the merged-feed writer applies
        # (non-finite → null), keeping the two feeds verbatim-comparable
        # even on NaN-loss steps.
        with open(self.rank_path, "a") as f:
            f.write(json.dumps(_oagg.sanitize_json(entry)) + "\n")
        if self._agg is not None:
            self._agg.collect(it, entry)
        # Incident plane (ISSUE 12): evaluate the process watch rules on
        # this already-paid cadence — per rule, one registry lookup + a
        # predicate; a breach captures its debug bundle NOW, before the
        # gauge resets or the window rolls over.
        from chainermn_tpu.observability import incident as _oincident

        mgr = _oincident.manager()
        if self._fleet_clock is not None:
            mgr.note_fleet_clock(self._fleet_clock)
        mgr.evaluate()

    def _publish_device_gauges(self) -> None:
        """Best-effort ``device.*`` publish for the newest live
        ``train_step`` program: mean step wall ms since the last tick ×
        the watcher's captured cost model (one extra lowering the first
        time, memoized) → achieved TFLOP/s, MFU, arithmetic intensity,
        roofline gap.  MFU reads None (gauge absent) off the
        ``PEAK_BF16_FLOPS`` table — e.g. CPU CI."""
        from chainermn_tpu.observability import device as _odevice

        wf = _odevice.watch().find("train_step")
        if wf is None:
            return
        h = _omet.registry().histogram("train.step_ms").to_dict()
        d_sum = h["sum"] - self._dev_last[0]
        d_n = h["count"] - self._dev_last[1]
        self._dev_last = (h["sum"], h["count"])
        if d_n <= 0:
            return
        try:
            _odevice.watch().publish_roofline(
                wf, d_sum / d_n, n_devices=len(jax.devices())
            )
        except Exception:
            pass

    def finalize(self, trainer: "Trainer"):
        """Flush a final tick so a stop between triggers still lands the
        closing window (skipped when the last iteration already fired —
        a duplicate step would desync feed consumers); then, with
        ``fleet_trace`` configured, export the merged fleet trace
        (collective — every rank reaches finalize at the same loop
        exit)."""
        self._fire(trainer)
        if self.fleet_trace is not None and _obs.enabled():
            from chainermn_tpu.observability import fleet as _ofleet

            summary = _ofleet.export_fleet_trace(
                self.comm, path=self.fleet_trace,
                clock=self._fleet_clock, probes=self._fleet_probes,
            )
            if summary is not None and jax.process_index() == 0:
                _close_progress_line()
                who = summary.get("straggler_rank")
                print(
                    f"[chainermn_tpu.fleet] merged trace -> "
                    f"{summary['path']} ({summary['nranks']} ranks, "
                    f"max skew {summary['max_skew_ms']} ms, straggler "
                    f"{'none' if who is None else f'rank {who}'})",
                    flush=True,
                )


class PrintReport(Extension):
    """Prints a fixed-column table of selected LogReport entries (reference:
    Chainer's ``PrintReport``, attached ``if comm.rank == 0``).

    Reads the newest entries of the trainer's :class:`LogReport` (located
    automatically, or pass ``log_report=``); fires on the same cadence so a
    row appears per LogReport interval.  With a LogReport that also prints,
    set its ``print_report=False`` to avoid double output."""

    def __init__(self, entries: Sequence[str], log_report: "LogReport" = None,
                 trigger=(1, "epoch")):
        super().__init__(self._fire, trigger=trigger, name="PrintReport")
        self._keys = list(entries)
        if not self._keys:
            raise ValueError("PrintReport needs at least one entry key")
        self._log = log_report
        self._shown = 0
        self._header_done = False

    def _find_log(self, trainer: "Trainer") -> Optional["LogReport"]:
        if self._log is not None:
            return self._log
        for ext in trainer.extensions:
            if isinstance(ext, LogReport):
                return ext
        return None

    def should_fire(self, trainer: "Trainer") -> bool:
        # Fire AFTER the LogReport regardless of registration order: the
        # trainer walks extensions in list order, so an earlier-registered
        # PrintReport would read log.log before this tick's entry lands
        # (rows one interval late, final row dropped at finalize).  Instead
        # of an ordering contract, fire whenever there are unshown entries.
        log = self._find_log(trainer)
        if log is not None and len(log.log) > self._shown:
            return True
        return False

    def _fire(self, trainer: "Trainer"):
        if jax.process_index() != 0:
            return
        log = self._find_log(trainer)
        if log is None:
            return
        _close_progress_line()
        width = max(12, max(len(k) for k in self._keys) + 2)
        if not self._header_done:
            print("".join(k.ljust(width) for k in self._keys), flush=True)
            self._header_done = True
        for entry in log.log[self._shown:]:
            cells = []
            for k in self._keys:
                v = entry.get(k, "")
                cells.append(
                    (f"{v:.6g}" if isinstance(v, float) else str(v)).ljust(width)
                )
            print("".join(cells), flush=True)
        self._shown = len(log.log)

    def finalize(self, trainer: "Trainer"):
        self._fire(trainer)


class ProgressBar(Extension):
    """Rank-0 progress line with rate + ETA (reference: Chainer's
    ``ProgressBar``, attached ``if comm.rank == 0`` in every example).
    Writes a carriage-returned status line to stderr every
    ``update_interval`` iterations — never on the metric hot path."""

    def __init__(self, update_interval: int = 10):
        super().__init__(self._fire, trigger=(update_interval, "iteration"),
                         name="ProgressBar")
        self._t0 = time.time()

    def _fire(self, trainer: "Trainer"):
        if jax.process_index() != 0:
            return
        elapsed = time.time() - self._t0
        rate = trainer.iteration / elapsed if elapsed > 0 else 0.0
        total = self._total_iters(trainer)
        if total:
            frac = min(trainer.iteration / total, 1.0)
            bar = "#" * int(frac * 20)
            eta = (total - trainer.iteration) / rate if rate > 0 else 0.0
            msg = (f"[{bar:<20}] {frac:6.1%}  iter {trainer.iteration}"
                   f"  {rate:.2f} it/s  eta {eta:.0f}s")
        else:
            msg = (f"iter {trainer.iteration}  epoch {trainer.epoch}"
                   f"  {rate:.2f} it/s")
        # Pad to the widest line so a shrinking eta/rate never leaves stale
        # trailing characters, and \r only after the payload.
        self._width = max(getattr(self, "_width", 0), len(msg))
        print("\r" + msg.ljust(self._width), end="", file=sys.stderr,
              flush=True)
        global _progress_line_open
        _progress_line_open = True

    @staticmethod
    def _total_iters(trainer: "Trainer") -> Optional[int]:
        if trainer.stop_unit == "iteration":
            return trainer.stop_n
        it = trainer.train_iter
        n, bs = getattr(it, "_n", None), getattr(it, "batch_size", None)
        if n and bs:
            return trainer.stop_n * math.ceil(n / bs)
        return None

    def finalize(self, trainer: "Trainer"):
        if jax.process_index() == 0:
            _close_progress_line()


class Trainer:
    """Drives ``optimizer.update`` over a train iterator.

    Args:
      optimizer: a :class:`chainermn_tpu.optimizers.MultiNodeOptimizer`.
      state: initial TrainState (from ``optimizer.init``).
      loss_fn: ``loss_fn(params, batch) -> scalar`` (or ``(scalar, aux)``).
      train_iter: yields global batches (tuples of stacked arrays).
      stop: ``(n, 'epoch'|'iteration')`` stop trigger.
      preemption_guard: optional
        :class:`~chainermn_tpu.resilience.PreemptionGuard`, polled once per
        iteration — converts SIGTERM into a rank-synchronized emergency
        checkpoint + distinguished exit (see ``docs/resilience.md``).
      health_guard: optional
        :class:`~chainermn_tpu.resilience.TrainingHealthGuard` — adds
        in-graph step anomaly detection (the guard's kwargs merge into
        ``step_kwargs`` and its health carry is seeded on the state),
        cadenced cross-rank consistency votes, rollback recovery, and
        step-time/straggler stats (see ``docs/resilience.md``).

    The loop is also a ``CMN_FAULT`` hook point: ``crash@iter:N`` raises an
    :class:`~chainermn_tpu.resilience.InjectedFault` at iteration N through
    the exact path a user exception would take, and the fail-silent kinds
    corrupt this loop's values at the same per-iteration hook points —
    ``nan@grad:N``/``spike@loss:N`` poison the incoming batch,
    ``flip@param:N`` corrupts the local replica after the update,
    ``skew@step:N:ms`` stretches every step from N on (fail-slow).
    """

    def __init__(self, optimizer, state, loss_fn, train_iter,
                 stop: Tuple[int, str] = (1, "epoch"),
                 extensions: Optional[List[Extension]] = None,
                 has_aux: bool = False, stateful: bool = False,
                 step_kwargs: Optional[dict] = None,
                 preemption_guard=None, health_guard=None):
        self.optimizer = optimizer
        self.state = state
        self.loss_fn = loss_fn
        self.train_iter = train_iter
        self.stop_n, self.stop_unit = stop
        assert self.stop_unit in ("epoch", "iteration")
        self.extensions = list(extensions or [])
        self.has_aux = has_aux
        self.stateful = stateful
        # Extra make_train_step options threaded through optimizer.update
        # (accum_steps, augment, ...).
        self.step_kwargs = dict(step_kwargs or {})
        self.preemption_guard = preemption_guard
        # Process-wide injector, shared with HostComm's hook sites: a
        # hang@iter must also freeze the heartbeat threads whose freeze
        # callbacks live on the data plane's (same) injector.
        self._fault_injector = _faults.process_injector()
        self.iteration = 0
        self._observations: List[dict] = []
        #: Newest step's raw metrics dict (device arrays — no host sync);
        #: what MetricsReport converts at ITS cadence without consuming
        #: the LogReport observation window.
        self.last_metrics: Optional[dict] = None
        # Per-step observability publishers, resolved once (default-on,
        # CMN_OBS=0 removes even the instrument lookups): a host-side
        # counter + step-time histogram per iteration, nothing that could
        # sync the device stream.
        self._obs_on = _obs.enabled()
        if self._obs_on:
            _reg = _omet.registry()
            self._obs_iterations = _reg.counter("train.iterations")
            self._obs_step_ms = _reg.histogram("train.step_ms")
        # Arm the flight recorder (installs the SIGUSR1 live-snapshot
        # handler) UNGATED by CMN_OBS: the recorder is governed by its own
        # knobs (CMN_OBS_FLIGHT_DIR / CMN_OBS_FLIGHT), matching the
        # crash path, which builds it lazily regardless of CMN_OBS.
        _oflight.recorder()
        # Bind LAST: the guard merges its in-graph kwargs into step_kwargs
        # and seeds state.health on the state set above.
        self.health_guard = health_guard
        if health_guard is not None:
            health_guard.bind(self)

    @property
    def epoch(self) -> int:
        return getattr(self.train_iter, "epoch", 0)

    def extend(self, ext: Extension):
        self.extensions.append(ext)

    def drain_observations(self) -> List[dict]:
        obs, self._observations = self._observations, []
        return obs

    def _done(self) -> bool:
        tick = self.epoch if self.stop_unit == "epoch" else self.iteration
        return tick >= self.stop_n

    def run(self):
        inj = self._fault_injector
        while not self._done():
            t0 = time.perf_counter()
            batch = next(self.train_iter)
            if inj is not None:
                # Fail-silent injection, pre-step: nan@grad / spike@loss
                # poison THIS iteration's batch (counted 1-based like the
                # iter site).
                batch = _faults.poison_batch(inj, batch, self.iteration + 1)
            # Host-side profiler annotation around the step dispatch: an
            # xprof capture lines its device stream up with these step
            # numbers (and with the host spans in the ring).
            with (_otrace.step_annotation(self.iteration + 1)
                  if self._obs_on else contextlib.nullcontext()):
                self.state, metrics = self.optimizer.update(
                    self.state, batch, self.loss_fn, has_aux=self.has_aux,
                    stateful=self.stateful, **self.step_kwargs,
                )
            self.iteration += 1
            if inj is not None:
                # Fail-silent injection, post-step: flip@param corrupts the
                # local replica (checkpoints taken this iteration snapshot
                # the corruption, exactly like real silent divergence);
                # skew@step stretches the step (fail-slow straggler).
                self.state = _faults.corrupt_params(
                    inj, self.state, self.iteration
                )
                inj.hook("step", count=self.iteration)
            # Keep raw device arrays — no host sync on the hot path.
            self._observations.append(dict(metrics))
            self.last_metrics = dict(metrics)
            if self._obs_on:
                self._obs_iterations.inc()
                self._obs_step_ms.observe(
                    (time.perf_counter() - t0) * 1000.0
                )
            for ext in self.extensions:
                if ext.should_fire(self):
                    ext(self)
            if inj is not None:
                inj.hook("iter", count=self.iteration)
            # Health guard AFTER the interval extensions: a checkpoint
            # saved this very iteration exists before the vote that may
            # bless it as known-good (or roll back over it).
            if self.health_guard is not None:
                self.health_guard.post_step(
                    self, metrics, time.perf_counter() - t0
                )
            # Preemption poll LAST: a periodic checkpoint that fired this
            # very iteration makes the guard's emergency save an
            # idempotent no-op.
            if self.preemption_guard is not None:
                self.preemption_guard.poll(self)
        for ext in self.extensions:
            ext.finalize(self)
        if self.health_guard is not None:
            self.health_guard.finalize(self)
        return self.state
