// dataloader — native threaded batch assembler / prefetcher.
//
// TPU-native equivalent of the reference's multiprocess data loading (the
// ImageNet example's Chainer MultiprocessIterator — SURVEY.md §2.9) and of
// its pinned staging buffers (`_memory_utility.py — HostPinnedMemory`):
// worker threads gather dataset rows into preallocated slot buffers while
// the accelerator computes, so the host never stalls the step loop on batch
// assembly.  Python wraps this via ctypes (no pybind11 in this image) and
// feeds the slots straight to device_put.
//
// Model: the dataset is F feature arrays (row-major, contiguous, arbitrary
// row strides) living in caller-owned memory.  The loader owns a ring of
// `depth` slots, each holding one assembled batch per feature.  Workers pull
// batch index-lists from a work queue, memcpy rows, and publish slots;
// `next_batch` blocks for the oldest published slot; `release` recycles it.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Feature {
  const uint8_t* base = nullptr;
  uint64_t row_bytes = 0;  // bytes per row (dense)
  uint64_t stride = 0;     // bytes between consecutive rows
};

struct Slot {
  std::vector<std::vector<uint8_t>> buffers;  // one per feature
  uint64_t seq = 0;
  bool ready = false;
};

struct Work {
  std::vector<int64_t> indices;
  uint64_t seq;
};

struct Loader {
  std::vector<Feature> features;
  uint64_t batch = 0;
  int depth = 0;
  std::vector<Slot> slots;
  std::deque<Work> work;
  std::deque<int> free_slots;
  uint64_t next_submit_seq = 0;
  uint64_t next_consume_seq = 0;
  uint64_t next_slot_seq = 0;  // next seq allowed to claim a free slot
  std::mutex mu;
  std::condition_variable cv_work;   // workers wait for work
  std::condition_variable cv_ready;  // consumer waits for published slots
  std::condition_variable cv_free;   // submitter waits for free slots
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
};

void worker_loop(Loader* L) {
  for (;;) {
    Work w;
    int slot = -1;
    {
      // Slot acquisition happens HERE, not at submit time: the consumer may
      // hold one slot (zero-copy views) while `depth` batches are queued, so
      // a submit-side wait could deadlock against a consumer that only
      // releases on its next call.
      //
      // Slots are granted in SUBMISSION-SEQ order (next_slot_seq): workers
      // pop work FIFO but can wake in arbitrary order, and if a later-seq
      // batch took the last free slot ahead of the earliest-seq one, a
      // consumer calling loader_next before loader_release (allowed by the
      // "at most depth in flight" contract) would block on the starved
      // lowest seq while holding a slot — deadlock.  Because pops are FIFO,
      // the popped-but-unslotted seqs are contiguous, so the worker holding
      // next_slot_seq always exists and always gets the next free slot.
      std::unique_lock<std::mutex> lk(L->mu);
      L->cv_work.wait(lk, [&] { return L->stop || !L->work.empty(); });
      if (L->stop) return;
      w = std::move(L->work.front());
      L->work.pop_front();
      L->cv_free.wait(lk, [&] {
        return L->stop ||
               (!L->free_slots.empty() && w.seq == L->next_slot_seq);
      });
      if (L->stop) return;
      slot = L->free_slots.front();
      L->free_slots.pop_front();
      L->next_slot_seq++;
      L->slots[slot].ready = false;
    }
    // Other workers may be waiting for their seq's turn on cv_free.
    L->cv_free.notify_all();
    Slot& s = L->slots[slot];
    for (size_t f = 0; f < L->features.size(); ++f) {
      const Feature& ft = L->features[f];
      uint8_t* out = s.buffers[f].data();
      for (size_t i = 0; i < w.indices.size(); ++i) {
        std::memcpy(out + i * ft.row_bytes,
                    ft.base + static_cast<uint64_t>(w.indices[i]) * ft.stride,
                    ft.row_bytes);
      }
    }
    {
      std::lock_guard<std::mutex> lk(L->mu);
      s.seq = w.seq;
      s.ready = true;
    }
    L->cv_ready.notify_all();
  }
}

}  // namespace

extern "C" {

// bases/row_bytes/strides: arrays of length n_features describing the source
// arrays.  batch: rows per batch.  depth: ring size.  n_workers: threads.
void* loader_create(const void** bases, const uint64_t* row_bytes,
                    const uint64_t* strides, int n_features, uint64_t batch,
                    int depth, int n_workers) {
  if (n_features <= 0 || batch == 0 || depth <= 0 || n_workers <= 0)
    return nullptr;
  auto L = std::make_unique<Loader>();
  L->batch = batch;
  L->depth = depth;
  for (int f = 0; f < n_features; ++f) {
    Feature ft;
    ft.base = static_cast<const uint8_t*>(bases[f]);
    ft.row_bytes = row_bytes[f];
    ft.stride = strides[f];
    L->features.push_back(ft);
  }
  L->slots.resize(depth);
  for (int s = 0; s < depth; ++s) {
    for (int f = 0; f < n_features; ++f)
      L->slots[s].buffers.emplace_back(batch * row_bytes[f]);
    L->free_slots.push_back(s);
  }
  for (int w = 0; w < n_workers; ++w) L->workers.emplace_back(worker_loop, L.get());
  return L.release();
}

// Queue one batch of row indices for assembly.  Never blocks — workers wait
// for free slots; the caller provides backpressure by submitting at most
// ring-depth batches ahead of consumption.  Returns the sequence number.
int64_t loader_submit(void* handle, const int64_t* indices, uint64_t n) {
  auto* L = static_cast<Loader*>(handle);
  if (n != L->batch) return -2;
  Work w;
  w.indices.assign(indices, indices + n);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    if (L->stop) return -1;
    w.seq = L->next_submit_seq++;
    L->work.push_back(std::move(w));
  }
  L->cv_work.notify_one();
  return static_cast<int64_t>(L->next_submit_seq - 1);
}

// Wait for the next batch IN SUBMISSION ORDER; returns its slot id, whose
// buffers the caller reads via loader_slot_ptr.  -1 after destroy.
int loader_next(void* handle, int timeout_ms) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  auto ready = [&] {
    if (L->stop) return true;
    for (auto& s : L->slots)
      if (s.ready && s.seq == L->next_consume_seq) return true;
    return false;
  };
  if (timeout_ms < 0) {
    L->cv_ready.wait(lk, ready);
  } else if (!L->cv_ready.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
    return -2;
  }
  if (L->stop) return -1;
  for (int s = 0; s < L->depth; ++s)
    if (L->slots[s].ready && L->slots[s].seq == L->next_consume_seq) {
      L->next_consume_seq++;
      return s;
    }
  return -1;  // unreachable
}

const void* loader_slot_ptr(void* handle, int slot, int feature) {
  auto* L = static_cast<Loader*>(handle);
  return L->slots[slot].buffers[feature].data();
}

// Recycle a slot after its data has been consumed (device_put completed).
void loader_release(void* handle, int slot) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->slots[slot].ready = false;
    L->free_slots.push_back(slot);
  }
  L->cv_free.notify_all();
}

void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop = true;
  L->cv_work.notify_all();
  L->cv_ready.notify_all();
  L->cv_free.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
