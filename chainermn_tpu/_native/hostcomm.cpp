// hostcomm — native TCP object-plane transport for multi-host jobs.
//
// TPU-native equivalent of the reference's MPI control plane (mpi4py used for
// bcast_obj/gather_obj/send_obj/recv_obj and bootstrap — SURVEY.md §2.1
// "MPI binding").  The TPU data plane is XLA collectives over ICI/DCN; this
// is ONLY the host-side object plane: pickled-bytes point-to-point between
// processes, from which Python composes barrier/bcast/gather/allgather.
//
// Design: full peer mesh over TCP.  Rank r listens on base_port + r; on
// init every pair (i < j) is connected once (j dials i, sends its rank as a
// 4-byte hello).  Frames are [u64 length][payload].  A background reader
// thread per peer demultiplexes frames into per-source queues so sends never
// deadlock against out-of-order receives (the classic MPI-tag headache the
// reference sidestepped via mpi4py's matching; we keep FIFO per source).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Frame {
  std::vector<uint8_t> data;
};

struct PeerQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Frame> frames;
};

struct Comm {
  int rank = -1;
  int size = 0;
  std::vector<int> fds;                 // fds[peer] (-1 for self)
  std::vector<std::unique_ptr<PeerQueue>> queues;
  std::vector<std::thread> readers;
  std::vector<std::mutex> send_mu;      // one writer lock per peer fd
  bool failed = false;
  std::string error;
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// Deadline-bounded send: poll for writability, then non-blocking send, so a
// dead peer whose socket buffer is full fails the op after deadline_ms
// instead of wedging the sender forever (the reference's MPI_Send had the
// same silent-blocking failure mode).  deadline_ms < 0 → wait forever.
// Returns 0 ok, -2 connection failure, -3 timeout.
int send_all_deadline(int fd, const void* buf, size_t n, int deadline_ms) {
  if (deadline_ms < 0) return send_all(fd, buf, n) ? 0 : -2;
  const char* p = static_cast<const char*>(buf);
  auto t0 = std::chrono::steady_clock::now();
  while (n > 0) {
    auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
    int remain = deadline_ms - static_cast<int>(elapsed);
    if (remain <= 0) return -3;
    pollfd pfd{fd, POLLOUT, 0};
    int pr = ::poll(&pfd, 1, remain);
    if (pr == 0) return -3;
    if (pr < 0 || (pfd.revents & (POLLERR | POLLHUP | POLLNVAL))) return -2;
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return -2;
    }
    if (w == 0) return -2;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return 0;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void reader_loop(Comm* c, int peer) {
  int fd = c->fds[peer];
  for (;;) {
    uint64_t len = 0;
    if (!recv_all(fd, &len, sizeof(len))) return;  // peer closed
    Frame f;
    f.data.resize(len);
    if (len > 0 && !recv_all(fd, f.data.data(), len)) return;
    PeerQueue* q = c->queues[peer].get();
    {
      std::lock_guard<std::mutex> lk(q->mu);
      q->frames.push_back(std::move(f));
    }
    q->cv.notify_all();
  }
}

int listen_on(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

int dial(const char* host, int port, int retries_ms) {
  for (int waited = 0;; waited += 50) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (waited >= retries_ms) return -1;
    ::usleep(50 * 1000);
  }
}

}  // namespace

extern "C" {

// hosts: size C strings (IPv4 dotted quads); rank r listens on ports[r].
// Returns an opaque handle, or nullptr on failure.
void* hostcomm_init(int rank, int size, const char** hosts, const int* ports,
                    int timeout_ms) {
  auto c = std::make_unique<Comm>();
  c->rank = rank;
  c->size = size;
  c->fds.assign(size, -1);
  c->queues.resize(size);
  for (int i = 0; i < size; ++i) c->queues[i] = std::make_unique<PeerQueue>();
  c->send_mu = std::vector<std::mutex>(size);

  int lfd = listen_on(ports[rank]);
  if (lfd < 0) return nullptr;

  // Accept connections from higher ranks in a helper thread while we dial
  // lower ranks — avoids ordering deadlock.  Accepts are poll()-bounded so a
  // dead peer fails init after timeout_ms instead of wedging every rank.
  int expect = size - rank - 1;
  std::thread acceptor([&c, lfd, expect, timeout_ms]() {
    for (int got = 0; got < expect; ++got) {
      pollfd pfd{lfd, POLLIN, 0};
      int pr = ::poll(&pfd, 1, timeout_ms > 0 ? timeout_ms : -1);
      if (pr <= 0) {
        c->failed = true;
        return;
      }
      int fd = ::accept(lfd, nullptr, nullptr);
      if (fd < 0) {
        c->failed = true;
        return;
      }
      // Only HIGHER ranks dial us, each exactly once: a hello from a rank
      // ≤ ours, out of range, or already connected would overwrite (and
      // leak) an established fd — reject it and fail init.
      int32_t peer = -1;
      if (!recv_all(fd, &peer, sizeof(peer)) || peer <= c->rank ||
          peer >= c->size || c->fds[peer] != -1) {
        c->failed = true;
        ::close(fd);
        return;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      c->fds[peer] = fd;
    }
  });

  bool ok = true;
  for (int peer = 0; peer < rank; ++peer) {
    int fd = dial(hosts[peer], ports[peer], timeout_ms);
    if (fd < 0) {
      ok = false;
      break;
    }
    int32_t me = rank;
    if (!send_all(fd, &me, sizeof(me))) {
      ok = false;
      ::close(fd);
      break;
    }
    c->fds[peer] = fd;
  }
  acceptor.join();
  ::close(lfd);
  if (!ok || c->failed) {
    for (int fd : c->fds)
      if (fd >= 0) ::close(fd);
    return nullptr;
  }

  for (int peer = 0; peer < size; ++peer) {
    if (peer == rank) continue;
    c->readers.emplace_back(reader_loop, c.get(), peer);
  }
  return c.release();
}

// Framed send to `dest`, bounded by timeout_ms (< 0 → wait forever).
// Returns 0 ok, -1 bad args, -2 connection failure, -3 timeout.
int hostcomm_send(void* handle, int dest, const uint8_t* data, uint64_t len,
                  int timeout_ms) {
  auto* c = static_cast<Comm*>(handle);
  if (dest < 0 || dest >= c->size || dest == c->rank) return -1;
  std::lock_guard<std::mutex> lk(c->send_mu[dest]);
  auto t0 = std::chrono::steady_clock::now();
  uint64_t n = len;
  int rc = send_all_deadline(c->fds[dest], &n, sizeof(n), timeout_ms);
  if (len > 0 && rc == 0) {
    // The header is committed: spend only whatever deadline REMAINS on
    // the payload, so the whole frame honors one timeout_ms budget.
    int remain = timeout_ms;
    if (timeout_ms >= 0) {
      auto spent = std::chrono::duration_cast<std::chrono::milliseconds>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
      remain = timeout_ms - static_cast<int>(spent);
      if (remain < 0) remain = 0;
    }
    rc = send_all_deadline(c->fds[dest], data, len, remain);
  }
  if (rc != 0) {
    // A failed send may have written part of a frame; the byte stream to
    // this peer is unrecoverable (the reader has no resync point), so
    // poison the channel: shutdown makes the peer's reader see EOF and
    // every later op on this fd fail fast, instead of a silently
    // desynced stream delivering garbage to a retried send.
    ::shutdown(c->fds[dest], SHUT_RDWR);
  }
  return rc;
}

// Blocking receive of the next frame from `source`.  Two-phase: first call
// with buf=nullptr returns the pending frame's length (waiting for arrival);
// then call with a buffer of that size to pop it.  timeout_ms < 0 → wait
// forever.  Returns length, or -1 timeout, -2 bad args.
int64_t hostcomm_recv(void* handle, int source, uint8_t* buf, uint64_t buflen,
                      int timeout_ms) {
  auto* c = static_cast<Comm*>(handle);
  if (source < 0 || source >= c->size || source == c->rank) return -2;
  PeerQueue* q = c->queues[source].get();
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [&] { return !q->frames.empty(); };
  if (timeout_ms < 0) {
    q->cv.wait(lk, ready);
  } else if (!q->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                             ready)) {
    return -1;
  }
  Frame& f = q->frames.front();
  int64_t len = static_cast<int64_t>(f.data.size());
  if (buf == nullptr) return len;  // peek length, leave queued
  if (buflen < f.data.size()) return -2;
  if (len > 0) std::memcpy(buf, f.data.data(), f.data.size());
  q->frames.pop_front();
  return len;
}

void hostcomm_destroy(void* handle) {
  auto* c = static_cast<Comm*>(handle);
  for (int fd : c->fds)
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  for (auto& t : c->readers) t.join();
  for (int fd : c->fds)
    if (fd >= 0) ::close(fd);
  delete c;
}

}  // extern "C"
