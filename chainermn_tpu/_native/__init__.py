"""Native runtime build + ctypes bindings.

The reference's native layer was a Cython NCCL binding plus C MPI
(``chainermn/nccl/nccl.pyx``, mpi4py — SURVEY.md §2.1).  On TPU the data
plane is XLA collectives (no binding needed); what remains native here is the
host runtime: the TCP object-plane transport (``hostcomm.cpp``) and the
threaded batch assembler (``dataloader.cpp``).  Compiled on first use with
``g++`` (no pybind11 in the image — plain C ABI + ctypes), cached under
``_native/build/`` keyed by source hash.  Every consumer has a pure-Python
fallback, so a missing toolchain degrades gracefully.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def _build(name: str) -> str:
    src = os.path.join(_DIR, f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_BUILD, f"{name}-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        src, "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"g++ unavailable/failed: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(f"g++ failed:\n{proc.stderr}")
    os.replace(tmp, out)
    return out


def load(name: str) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unbuildable."""
    with _lock:
        if name in _cache:
            return _cache[name]
        try:
            lib = ctypes.CDLL(_build(name))
        except NativeBuildError:
            lib = None
        _cache[name] = lib
        return lib


def load_hostcomm() -> Optional[ctypes.CDLL]:
    lib = load("hostcomm")
    if lib is None:
        return None
    lib.hostcomm_init.restype = ctypes.c_void_p
    lib.hostcomm_init.argtypes = [
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
    ]
    lib.hostcomm_send.restype = ctypes.c_int
    lib.hostcomm_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int,
    ]
    lib.hostcomm_recv.restype = ctypes.c_int64
    lib.hostcomm_recv.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int,
    ]
    lib.hostcomm_destroy.restype = None
    lib.hostcomm_destroy.argtypes = [ctypes.c_void_p]
    return lib


def load_dataloader() -> Optional[ctypes.CDLL]:
    lib = load("dataloader")
    if lib is None:
        return None
    lib.loader_create.restype = ctypes.c_void_p
    lib.loader_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.loader_submit.restype = ctypes.c_int64
    lib.loader_submit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
    ]
    lib.loader_next.restype = ctypes.c_int
    lib.loader_next.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.loader_slot_ptr.restype = ctypes.c_void_p
    lib.loader_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.loader_release.restype = None
    lib.loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.loader_destroy.restype = None
    lib.loader_destroy.argtypes = [ctypes.c_void_p]
    return lib
