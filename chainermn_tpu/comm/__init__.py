"""Communicator factory.

Reference anchor: ``chainermn/communicators/__init__.py — create_communicator``.
Every GPU-era communicator name maps to :class:`XlaCommunicator` with an
appropriate mesh, because the hand-written NCCL/MPI hierarchies are what XLA's
ICI/DCN collective scheduler does internally (see ``SURVEY.md`` §2.2).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax

from . import mesh as mesh_lib
from .base import CommunicatorBase
from .mesh import flat_mesh, hybrid_mesh, topology_mesh, Topology
from .ragged import ragged_permute, ragged_send, round_up_to_bucket
from .xla import DummyCommunicator, XlaCommunicator

__all__ = [
    "CommunicatorBase",
    "XlaCommunicator",
    "DummyCommunicator",
    "create_communicator",
    "ragged_permute",
    "ragged_send",
    "round_up_to_bucket",
    "flat_mesh",
    "hybrid_mesh",
    "topology_mesh",
    "Topology",
]

_HIERARCHICAL = {"hierarchical", "two_dimensional", "non_cuda_aware"}
_FLAT = {"xla", "pure_nccl", "flat", "single_node"}


def create_communicator(
    communicator_name: str = "hierarchical",
    mesh=None,
    devices: Optional[Sequence[jax.Device]] = None,
    allreduce_grad_dtype: Optional[Any] = None,
) -> CommunicatorBase:
    """Create a communicator (reference signature:
    ``create_communicator(communicator_name='hierarchical', mpi_comm=None,
    allreduce_grad_dtype=None)``; ``mpi_comm`` → ``mesh``/``devices``).

    Names:
      * ``hierarchical`` / ``two_dimensional`` / ``non_cuda_aware`` — topology
        ``(inter, intra)`` mesh (host × chip), collectives ride ICI first.
      * ``xla`` / ``pure_nccl`` / ``flat`` / ``single_node`` — flat 1-D mesh.
      * ``naive`` — flat mesh over CPU devices (the GPU-free CI path).
      * ``dummy`` — no-op allreduce, benchmarking only.

    ``allreduce_grad_dtype`` (fp16/bf16) enables the reduced-precision wire
    format of the reference's ``pure_nccl`` path, for any name.
    """
    name = communicator_name
    if name == "dummy":
        return DummyCommunicator(
            mesh=mesh if mesh is not None else flat_mesh(devices),
            allreduce_grad_dtype=allreduce_grad_dtype,
        )
    if name == "naive":
        if mesh is None:
            if devices is None:
                devices = jax.devices("cpu")
            mesh = flat_mesh(devices)
        return XlaCommunicator(mesh=mesh, allreduce_grad_dtype=allreduce_grad_dtype)
    if name in _FLAT:
        if mesh is None:
            mesh = flat_mesh(devices)
        return XlaCommunicator(mesh=mesh, allreduce_grad_dtype=allreduce_grad_dtype)
    if name in _HIERARCHICAL:
        if mesh is None:
            mesh = topology_mesh(devices)
        return XlaCommunicator(mesh=mesh, allreduce_grad_dtype=allreduce_grad_dtype)
    raise ValueError(f"unknown communicator name {communicator_name!r}")
