"""Communicator API contract.

TPU-native re-design of the reference's communicator hierarchy
(``chainermn/communicators/mpi_communicator_base.py`` — ``CommunicatorBase`` /
``MpiCommunicatorBase``).  The reference is MPMD: N OS processes, eager MPI/NCCL
calls, explicit pinned/device pack buffers.  Here the design is SPMD: one
controller, a :class:`jax.sharding.Mesh`, and collectives that are *ops inside a
traced program* which XLA schedules over ICI/DCN.

Two planes, mirroring the reference's split between NCCL (data plane) and
pickled-MPI (object plane):

* **Array plane** — ``allreduce_grad``, ``bcast_data``, ``alltoall``,
  ``permute`` … operate on *rankwise* pytrees: every leaf carries a leading
  ``size`` axis, sharded across the communicator's mesh axes, so slot ``r`` is
  "rank r's local array" (the SPMD analog of each MPI rank's private buffer).
  They are eager-callable but internally one jitted ``shard_map`` — i.e. a
  single fused collective per call, the property the reference engineered by
  hand with ``pack_params``/``unpack_params``
  (``chainermn/communicators/_memory_utility.py``).
* **Object plane** — ``bcast_obj``, ``gather_obj``, ``allreduce_obj`` … move
  picklable Python objects between *processes* (hosts), like the reference's
  mpi4py pickled collectives.  Single-process jobs degenerate to identity.

In-graph usage (inside ``shard_map``/``pjit``) goes through the ``axis_name`` /
``psum``/``pmean``/``ppermute`` helpers — that is the hot path the training
integration uses (see ``chainermn_tpu/optimizers``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class CommunicatorBase:
    """Abstract communicator (reference anchor: ``CommunicatorBase``).

    Properties ``rank``/``size``/``intra_rank``/``inter_rank``/``intra_size``/
    ``inter_size`` mirror the reference's bootstrap output
    (``_communication_utility.init_ranks``) — with one documented semantic
    shift: the reference is MPMD (one rank per OS process), this framework is
    single-controller SPMD (one process drives many devices).  A *rank* is a
    device position along the communicator's mesh axes (``lax.axis_index``
    in-graph); the scalar ``rank``/``intra_rank``/``inter_rank`` properties
    describe the *calling process* (its first owned rank), and exact per-rank
    maps live on ``XlaCommunicator``'s topology (``Topology.proc_of_rank`` /
    ``intra_rank_of`` / ``inter_rank_of``).
    """

    # ------------------------------------------------------------------ sizes
    @property
    def rank(self) -> int:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    @property
    def intra_rank(self) -> int:
        raise NotImplementedError

    @property
    def intra_size(self) -> int:
        raise NotImplementedError

    @property
    def inter_rank(self) -> int:
        raise NotImplementedError

    @property
    def inter_size(self) -> int:
        raise NotImplementedError

    # ------------------------------------------------------- array plane (eager)
    def allreduce_grad(self, grads: Any) -> Any:
        """Mean-allreduce a rankwise gradient pytree across all ranks.

        Reference anchor: ``PureNcclCommunicator.allreduce_grad`` (pack → one
        ncclAllReduce → unpack × 1/size).  Here: one jitted ``shard_map`` of
        ``lax.pmean`` — XLA emits a single fused ICI/DCN all-reduce.
        """
        raise NotImplementedError

    def allreduce(self, x: Any, op: str = "sum") -> Any:
        """Rankwise allreduce with ``op`` in {"sum", "mean", "max", "min"}."""
        raise NotImplementedError

    def bcast_data(self, data: Any, root: int = 0) -> Any:
        """Broadcast rank ``root``'s slice to every rank slot.

        Reference anchor: ``MpiCommunicatorBase.bcast_data`` (model-parameter
        broadcast before training starts).
        """
        raise NotImplementedError

    def alltoall(self, xs: Any) -> Any:
        """Rankwise all-to-all: slot ``r`` holds rank r's outgoing row of
        shape ``(size, ...)``; returns incoming rows.  Reference anchor:
        ``MpiCommunicatorBase.alltoall``."""
        raise NotImplementedError

    def allgather(self, x: Any) -> Any:
        """Rankwise allgather: each slot receives the stacked ``(size, ...)``
        array of every rank's contribution."""
        raise NotImplementedError

    def permute(self, x: Any, perm: Sequence[tuple]) -> Any:
        """Rankwise point-to-point via a permutation ``[(src, dst), ...]`` —
        the SPMD analog of the reference's paired ``send``/``recv``
        (``MpiCommunicatorBase.send/recv``); slots that receive nothing get
        zeros, like an unmatched recv buffer."""
        raise NotImplementedError

    def gather(self, x: Any, root: int = 0) -> Any:
        raise NotImplementedError

    def scatter(self, x: Any, root: int = 0) -> Any:
        raise NotImplementedError

    # ---------------------------------------------------------- object plane
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        """Reference anchor: ``MpiCommunicatorBase.bcast_obj`` (pickled MPI
        bcast).  Moves a picklable object from process ``root`` to all."""
        raise NotImplementedError

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        raise NotImplementedError

    def allgather_obj(self, obj: Any) -> List[Any]:
        raise NotImplementedError

    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any:
        """Numeric-pytree object allreduce (used by the multi-node evaluator
        to average validation metric dicts; reference anchor
        ``allreduce_obj``)."""
        raise NotImplementedError

    def send_obj(self, obj: Any, dest: int, source: Optional[int] = None) -> None:
        """Rank-addressed object send (reference anchor
        ``MpiCommunicatorBase.send_obj``).  ``source`` defaults to this
        process's rank; explicit ``source`` lets a single-controller process
        speak for a co-located rank.  Delivery matches on the exact
        ``(source, dest)`` pair."""
        raise NotImplementedError

    def recv_obj(
        self, source: int, dest: Optional[int] = None, timeout: float = 60.0
    ) -> Any:
        """Blocking rank-addressed receive (MPI-recv-like); raises
        ``TimeoutError`` after ``timeout`` seconds rather than deadlocking."""
        raise NotImplementedError

    # ----------------------------------------------------------- structuring
    def split(self, color, key=None) -> Any:
        """Reference anchor: ``CommunicatorBase.split`` (MPI_Comm_split) —
        builds the hybrid DP×MP process grids of the reference.

        **Documented deviation**: the reference's MPMD form takes this rank's
        scalar ``(color, key)`` and returns this rank's sub-communicator.
        Under a single controller there is no "this rank", so the SPMD form
        takes *per-rank sequences* ``color``/``key`` (length ``size``) and
        returns ``{color: sub_communicator}`` — every group, because the one
        controller drives them all.  ``sub(axes)`` is the idiomatic mesh-axis
        slicing for hybrid grids."""
        raise NotImplementedError

    # --------------------------------------------------------- in-graph plane
    @property
    def axis_name(self):
        """Mesh axis name(s) for this communicator — pass to ``lax.psum`` etc.
        inside ``shard_map``/``pjit`` programs."""
        raise NotImplementedError

    # ------------------------------------------------------------------ misc
    def barrier(self) -> None:
        """Host-level barrier (object-plane)."""
        self.allgather_obj(None)

    def finalize(self) -> None:  # parity with reference API; nothing to tear down
        pass

    # Convenience reductions shared by subclasses -------------------------------
    @staticmethod
    def _reduce_objs(objs: List[Any], op: str) -> Any:
        """Pytree-wise numeric reduction over a list of objects."""
        import jax

        if not objs:
            return None
        leaves_list = [jax.tree_util.tree_flatten(o)[0] for o in objs]
        treedef = jax.tree_util.tree_flatten(objs[0])[1]
        cols = list(zip(*leaves_list))
        red: Callable[[list], Any]
        if op == "sum":
            red = lambda c: np.sum(np.asarray(c, dtype=np.result_type(*[np.asarray(x).dtype for x in c])), axis=0)
        elif op == "mean":
            red = lambda c: np.mean(np.asarray(c), axis=0)
        elif op == "max":
            red = lambda c: np.max(np.asarray(c), axis=0)
        elif op == "min":
            red = lambda c: np.min(np.asarray(c), axis=0)
        else:
            raise ValueError(f"unknown op {op!r}")
        out = [red(c) for c in cols]
        out = [o.item() if np.ndim(o) == 0 and not isinstance(objs[0], np.ndarray) else o for o in out]
        return jax.tree_util.tree_unflatten(treedef, out)
