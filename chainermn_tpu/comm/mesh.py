"""Device-mesh construction for communicators.

TPU-native replacement for the reference's topology bootstrap
(``chainermn/communicators/_communication_utility.py`` — ``init_ranks``,
``init_intra_mpi_comm``, ``init_inter_mpi_comm``, ``init_nccl_comm``): instead of
allgathering hostnames over MPI and splitting intra/inter MPI+NCCL communicators,
we build a :class:`jax.sharding.Mesh` whose axes encode the same topology —
``inter`` = across hosts (DCN), ``intra`` = chips within a host (ICI) — and let
XLA's collective scheduler pick hierarchical algorithms (the hand-written
hierarchical/two-dimensional communicator tricks of the reference are what XLA
already does internally over ICI/DCN).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


#: Canonical axis names used throughout the framework.
INTER_AXIS = "inter"  # across hosts (DCN plane)
INTRA_AXIS = "intra"  # chips within a host (ICI plane)
DATA_AXIS = "data"  # flat data-parallel axis (single-axis meshes)


def topology_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(inter, intra)`` mesh mirroring the host/chip topology.

    Equivalent of the reference's ``init_ranks`` (hostname allgather →
    ``(intra_rank, inter_rank)`` assignment): device.process_index plays the role
    of the hostname.  Ranks are ordered host-major so the collapsed linear rank
    ``inter_rank * intra_size + intra_rank`` matches MPI's typical rank layout.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    n_proc = len(by_proc)
    per_proc = {p: len(ds) for p, ds in by_proc.items()}
    intra = min(per_proc.values())
    if any(v != intra for v in per_proc.values()):
        # Ragged hosts: fall back to a flat layout factored as (n, 1).
        arr = np.array(devices).reshape(len(devices), 1)
        return Mesh(arr, (INTER_AXIS, INTRA_AXIS))
    arr = np.empty((n_proc, intra), dtype=object)
    for i, p in enumerate(sorted(by_proc)):
        # Sort within a process by device id for a stable intra order.
        arr[i, :] = sorted(by_proc[p], key=lambda d: d.id)
    return Mesh(arr, (INTER_AXIS, INTRA_AXIS))


def flat_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = DATA_AXIS,
) -> Mesh:
    """A 1-D mesh over all devices — the ``pure_nccl`` analog (one flat ring)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(list(devices)), (axis_name,))


def hybrid_mesh(
    shape: dict,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """An N-D mesh from ``{axis_name: size}`` — the hybrid DP×MP process-grid
    analog of the reference's ``CommunicatorBase.split`` two-level usage.

    Example: ``hybrid_mesh({"data": 4, "model": 2})`` on 8 devices.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = tuple(shape.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {int(np.prod(sizes))} devices, "
            f"got {len(devices)}"
        )
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


@dataclasses.dataclass(frozen=True)
class Topology:
    """Rank/size bookkeeping mirroring the reference's ``init_ranks`` output."""

    rank: int
    size: int
    intra_rank: int
    intra_size: int
    inter_rank: int
    inter_size: int


def topology_from_mesh(mesh: Mesh, axes: Tuple[str, ...]) -> Topology:
    """Derive process-plane topology numbers for a communicator over ``axes``.

    ``size`` is the total number of participants (mesh extent over ``axes``).
    ``rank`` is this *process*'s first participating device position — under
    single-controller SPMD every device participates; per-device rank inside a
    traced program comes from ``lax.axis_index`` instead.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    size = 1
    for a in axes:
        size *= sizes[a]
    if INTER_AXIS in axes and INTRA_AXIS in axes:
        inter_size = sizes[INTER_AXIS]
        intra_size = sizes[INTRA_AXIS]
    else:
        inter_size = jax.process_count()
        intra_size = max(size // max(inter_size, 1), 1)
    proc = jax.process_index()
    intra_rank = 0
    inter_rank = proc if inter_size > 1 else 0
    rank = inter_rank * intra_size + intra_rank
    return Topology(
        rank=rank,
        size=size,
        intra_rank=intra_rank,
        intra_size=intra_size,
        inter_rank=inter_rank,
        inter_size=inter_size,
    )
