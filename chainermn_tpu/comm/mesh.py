"""Device-mesh construction for communicators.

TPU-native replacement for the reference's topology bootstrap
(``chainermn/communicators/_communication_utility.py`` — ``init_ranks``,
``init_intra_mpi_comm``, ``init_inter_mpi_comm``, ``init_nccl_comm``): instead of
allgathering hostnames over MPI and splitting intra/inter MPI+NCCL communicators,
we build a :class:`jax.sharding.Mesh` whose axes encode the same topology —
``inter`` = across hosts (DCN), ``intra`` = chips within a host (ICI) — and let
XLA's collective scheduler pick hierarchical algorithms (the hand-written
hierarchical/two-dimensional communicator tricks of the reference are what XLA
already does internally over ICI/DCN).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


#: Canonical axis names used throughout the framework.
INTER_AXIS = "inter"  # across hosts (DCN plane)
INTRA_AXIS = "intra"  # chips within a host (ICI plane)
DATA_AXIS = "data"  # flat data-parallel axis (single-axis meshes)


def topology_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(inter, intra)`` mesh mirroring the host/chip topology.

    Equivalent of the reference's ``init_ranks`` (hostname allgather →
    ``(intra_rank, inter_rank)`` assignment): device.process_index plays the role
    of the hostname.  Ranks are ordered host-major so the collapsed linear rank
    ``inter_rank * intra_size + intra_rank`` matches MPI's typical rank layout.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    n_proc = len(by_proc)
    per_proc = {p: len(ds) for p, ds in by_proc.items()}
    intra = min(per_proc.values())
    if any(v != intra for v in per_proc.values()):
        # Ragged hosts: fall back to a flat layout factored as (n, 1).
        arr = np.array(devices).reshape(len(devices), 1)
        return Mesh(arr, (INTER_AXIS, INTRA_AXIS))
    arr = np.empty((n_proc, intra), dtype=object)
    for i, p in enumerate(sorted(by_proc)):
        # Sort within a process by device id for a stable intra order.
        arr[i, :] = sorted(by_proc[p], key=lambda d: d.id)
    return Mesh(arr, (INTER_AXIS, INTRA_AXIS))


def flat_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = DATA_AXIS,
) -> Mesh:
    """A 1-D mesh over all devices — the ``pure_nccl`` analog (one flat ring)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(list(devices)), (axis_name,))


def hybrid_mesh(
    shape: dict,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """An N-D mesh from ``{axis_name: size}`` — the hybrid DP×MP process-grid
    analog of the reference's ``CommunicatorBase.split`` two-level usage.

    Example: ``hybrid_mesh({"data": 4, "model": 2})`` on 8 devices.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = tuple(shape.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {int(np.prod(sizes))} devices, "
            f"got {len(devices)}"
        )
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


@dataclasses.dataclass(frozen=True)
class Topology:
    """Rank/size bookkeeping mirroring the reference's ``init_ranks`` output.

    Honest single-controller semantics (the reference is MPMD — one rank per
    OS process; here one controller drives many devices):

    * A **rank** is a device position along the communicator's collapsed mesh
      axes — the same number ``lax.axis_index`` yields in-graph.
    * The scalar fields describe the *calling process*: ``rank`` is the lowest
      rank whose device this process owns, ``intra_rank`` that rank's position
      among this process's ranks (0 by construction), ``inter_rank`` this
      process's position among participating processes.
    * Full per-rank queries go through the maps: ``proc_of_rank`` (exact
      owning process of every rank — this is what the object plane routes on)
      and the ``intra_rank_of``/``inter_rank_of`` methods.
    """

    rank: int
    size: int
    intra_rank: int
    intra_size: int
    inter_rank: int
    inter_size: int
    #: proc_of_rank[r] = process index owning rank r's canonical device
    #: (non-participating mesh axes at index 0).
    proc_of_rank: Tuple[int, ...] = ()
    #: distinct processes in rank order (inter_rank_of = index into this).
    procs: Tuple[int, ...] = ()

    def proc_of(self, rank: int) -> int:
        """Owning process of ``rank`` (exact map, any rank)."""
        return self.proc_of_rank[rank]

    def inter_rank_of(self, rank: int) -> int:
        """Position of ``rank``'s process among participating processes."""
        return self.procs.index(self.proc_of_rank[rank])

    def intra_rank_of(self, rank: int) -> int:
        """Position of ``rank`` among the ranks co-located on its process."""
        p = self.proc_of_rank[rank]
        return [r for r in range(self.size) if self.proc_of_rank[r] == p].index(rank)

    def ranks_of_proc(self, proc: int) -> Tuple[int, ...]:
        return tuple(
            r for r in range(self.size) if self.proc_of_rank[r] == proc
        )


def topology_from_mesh(mesh: Mesh, axes: Tuple[str, ...]) -> Topology:
    """Derive topology for a communicator over ``axes`` of ``mesh``.

    Ranks are collapsed positions along ``axes`` in row-major order — exactly
    ``lax.axis_index(axes)`` in-graph.  When ``axes`` is a strict subset of
    the mesh, a rank names a *group* of devices (one per position of the
    non-participating axes); its canonical device (all other axes at 0)
    defines the owning process for object-plane routing.
    """
    names = list(mesh.axis_names)
    part = [names.index(a) for a in axes]
    rest = [i for i in range(len(names)) if i not in part]
    size = 1
    for i in part:
        size *= mesh.devices.shape[i]
    flat = np.transpose(mesh.devices, part + rest).reshape(size, -1)
    my = jax.process_index()
    # Canonical group = the column (fixed non-participating-axes position)
    # containing THIS process's devices, falling back to column 0.  A subset
    # communicator (e.g. ``sub("intra")`` on an (inter, intra) mesh) names a
    # *family* of disjoint groups — one per rest-axes position; each process
    # must do its rank bookkeeping and object-plane routing within its own
    # group, otherwise a host whose devices all sit in a later column would
    # silently impersonate the column-0 host's ranks.
    col = 0
    for j in range(flat.shape[1]):
        if any(int(d.process_index) == my for d in flat[:, j]):
            col = j
            break
    proc_of_rank = tuple(int(d.process_index) for d in flat[:, col])
    procs = tuple(dict.fromkeys(proc_of_rank))
    mine = [r for r, p in enumerate(proc_of_rank) if p == my]
    rank = mine[0] if mine else 0
    inter_size = len(procs)
    inter_rank = procs.index(my) if my in procs else 0
    intra_size = max(
        sum(1 for p in proc_of_rank if p == q) for q in procs
    )
    return Topology(
        rank=rank,
        size=size,
        intra_rank=0,  # `rank` is this process's first rank by construction
        intra_size=intra_size,
        inter_rank=inter_rank,
        inter_size=inter_size,
        proc_of_rank=proc_of_rank,
        procs=procs,
    )
