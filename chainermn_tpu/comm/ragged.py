"""Ragged (variable-length) point-to-point on the array plane.

The reference's eager MPI ``send``/``recv`` (``chainermn/communicators/
mpi_communicator_base.py`` — pickled ndarray per call) accepted a different
array length on every call.  XLA's array plane is static-shape: every new
length would be a fresh compile.  The TPU-native rewrite is PAD-TO-BUCKET —
lengths round up to a multiple of ``bucket_width``, so the number of
compiled programs is bounded by the number of buckets actually touched
(compile keys are the padded shape), while the true lengths ride the same
permute as an int32 sideband and the receiver unpads exactly.

This is the tensor-sized complement of the object plane (``send_obj`` /
``recv_obj``): control traffic goes through pickles, bulk arrays through
here — one fused ppermute per call, ICI-resident under SPMD.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

import jax


def round_up_to_bucket(n: int, bucket_width: int) -> int:
    """Smallest positive multiple of ``bucket_width`` >= ``n`` (a length-0
    row still occupies one bucket — the compiled shape can't be empty)."""
    if bucket_width < 1:
        raise ValueError(f"bucket_width must be >= 1, got {bucket_width}")
    return bucket_width * max(1, -(-n // bucket_width))


def _local_rows(comm, out, out_lens) -> List[np.ndarray]:
    """Unpad a rankwise result back to per-rank variable-length arrays.

    Single-process: one entry per rank.  Multi-process: one entry per THIS
    process's ranks (rank order) — assembled from addressable shards, never
    materializing the global array on one host."""
    if jax.process_count() == 1:
        data = np.asarray(out)
        lens = np.asarray(out_lens)
        return [data[r, : lens[r]] for r in range(comm.size)]
    by_rank: Dict[int, np.ndarray] = {}
    len_by_rank: Dict[int, int] = {}
    for shard in out_lens.addressable_shards:
        sl = shard.index[0]
        vals = np.asarray(shard.data)
        for i, r in enumerate(range(sl.start, sl.stop)):
            len_by_rank[r] = int(vals[i])
    for shard in out.addressable_shards:
        sl = shard.index[0]
        arr = np.asarray(shard.data)
        for i, r in enumerate(range(sl.start, sl.stop)):
            by_rank[r] = arr[i, : len_by_rank[r]]
    return [by_rank[r] for r in sorted(by_rank)]


def ragged_permute(
    comm,
    rows: Sequence[np.ndarray],
    perm: Sequence[Tuple[int, int]],
    bucket_width: int = 128,
) -> List[np.ndarray]:
    """Variable-length rankwise point-to-point: slot ``src`` of ``rows`` is
    delivered to slot ``dst`` for every ``(src, dst)`` in ``perm``.

    Args:
      rows: per-rank arrays, ragged in axis 0 (trailing dims and dtype must
        agree).  Single-process: one per rank.  Multi-process: one per THIS
        process's ranks, in rank order.  Ranks that send nothing pass a
        length-0 array of the right trailing shape/dtype.
      perm: ``[(src_rank, dst_rank), ...]`` — each dst at most once.
      bucket_width: pad granularity.  All rows share one padded length (the
        max length rounded up), so a call's compile key is its bucket — a
        handful of buckets covers any workload, vs one compile per length.

    Returns per-rank RECEIVED arrays, exactly unpadded; ranks with no
    incoming edge get a length-0 array.  Multi-process: entries for this
    process's ranks only (rank order).
    """
    rows = [np.asarray(r) for r in rows]
    if not rows:
        raise ValueError("rows must be non-empty")
    trailing = rows[0].shape[1:]
    dtype = rows[0].dtype
    for i, r in enumerate(rows):
        if r.ndim < 1:
            raise ValueError(f"rows[{i}] must have a (ragged) leading axis")
        if r.shape[1:] != trailing or r.dtype != dtype:
            raise ValueError(
                f"rows[{i}] has shape {r.shape} / dtype {r.dtype}; expected "
                f"trailing {trailing} / {dtype} (only axis 0 may be ragged)"
            )
    max_len = max(r.shape[0] for r in rows)
    if jax.process_count() > 1:
        # The padded (compiled) shape must agree across processes.
        max_len = max(comm.allgather_obj(max_len))
    L = round_up_to_bucket(max_len, bucket_width)

    padded = np.zeros((len(rows), L) + trailing, dtype)
    for i, r in enumerate(rows):
        padded[i, : r.shape[0]] = r
    lengths = np.array([r.shape[0] for r in rows], np.int32)

    # One fused call moves payload + length sideband (the permute body
    # tree-maps over the tuple, so both ride the same compiled program).
    out, out_lens = comm.permute(
        comm.shard_rankwise((padded, lengths)), perm
    )
    return _local_rows(comm, out, out_lens)


def ragged_send(
    comm,
    row: Any,
    dest: int,
    source: int,
    bucket_width: int = 128,
) -> np.ndarray:
    """One ragged edge ``source → dest`` (reference analog: one eager
    ``send``/``recv`` pair).  Every rank calls this (SPMD); ``row`` is
    read from slot ``source`` and the return value is meaningful on slot
    ``dest`` (a length-0 array elsewhere).

    Single-process convenience over :func:`ragged_permute`: the caller
    holds all slots, so ``row`` is just the payload."""
    row = np.asarray(row)
    empty = np.zeros((0,) + row.shape[1:], row.dtype)
    rows = [row if r == source else empty for r in range(comm.size)]
    received = ragged_permute(
        comm, rows, [(source, dest)], bucket_width=bucket_width
    )
    return received[dest]
