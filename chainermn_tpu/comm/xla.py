"""XlaCommunicator — the TPU-native communicator.

Replaces the reference's entire communicator zoo
(``chainermn/communicators/pure_nccl_communicator.py`` —
``PureNcclCommunicator``, ``hierarchical_communicator.py``,
``two_dimensional_communicator.py``, ``flat_communicator.py``,
``single_node_communicator.py``, ``non_cuda_aware_communicator.py``,
``naive_communicator.py``): every hand-scheduled NCCL/MPI algorithm collapses
to one class holding a :class:`jax.sharding.Mesh`, because XLA's collective
scheduler already performs the hierarchical ICI/DCN decompositions those
classes implemented by hand.

Semantics of the eager array plane ("rankwise" layout): a pytree whose leaves
carry a leading ``size`` axis sharded over the communicator's mesh axes.  Slot
``r`` is rank r's private array — the single-controller SPMD encoding of the
reference's per-process buffers.  Each eager collective is ONE jitted
``shard_map`` (= one fused XLA collective), preserving the fused-buffer
property the reference built with ``pack_params``/``unpack_params``
(``chainermn/communicators/_memory_utility.py``) without any buffer code.
"""

from __future__ import annotations

import os
import pickle
import queue as _queue
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from .base import CommunicatorBase


class _Parked:
    """A cross-process frame parked for another (source, dest) pair.  Already
    deserialized (the wire serialized it at send time, so snapshot isolation
    is already guaranteed) — wrapping avoids a re-pickle/re-unpickle round."""

    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj


def _unqueue(item):
    return item.obj if isinstance(item, _Parked) else pickle.loads(item)


class XlaCommunicator(CommunicatorBase):
    """Mesh-backed communicator.

    Args:
      mesh: mesh to communicate over; defaults to the host/chip topology mesh
        (``mesh_lib.topology_mesh``) — the ``hierarchical`` analog.  Pass
        ``mesh_lib.flat_mesh()`` for the ``pure_nccl``/``flat`` analog.
      axes: mesh axis names this communicator spans (default: all axes).  A
        communicator over a strict subset of a hybrid mesh is the analog of a
        reference ``split`` sub-communicator.
      allreduce_grad_dtype: optional reduced-precision dtype for
        ``allreduce_grad`` — the ``pure_nccl`` fp16 path
        (``create_communicator(..., allreduce_grad_dtype='float16')``); the
        1/size scale is fused into the cast-back, as the reference fused it
        into its unpack kernel.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        axes: Optional[Sequence[str]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        allreduce_grad_dtype: Optional[Any] = None,
    ):
        if mesh is None:
            mesh = mesh_lib.topology_mesh(devices)
        self._mesh = mesh
        self._axes: Tuple[str, ...] = tuple(axes) if axes else tuple(mesh.axis_names)
        for a in self._axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
        self._topo = mesh_lib.topology_from_mesh(mesh, self._axes)
        self.allreduce_grad_dtype = (
            jnp.dtype(allreduce_grad_dtype) if allreduce_grad_dtype else None
        )
        self._fn_cache: Dict[Any, Callable] = {}
        # Object-plane p2p: one FIFO per (source_rank, dest_rank) pair, so
        # interleaved senders can never cross-deliver and co-located ranks
        # (several ranks per process is the TPU norm) stay distinguishable.
        self._self_queue: Dict[Tuple[int, int], _queue.SimpleQueue] = {}
        self._demux_mu = threading.Lock()  # guards the queue/lock dicts only
        # One drain lock PER SOURCE PROCESS: receivers waiting on different
        # processes must poll concurrently (a global poll lock serialized
        # co-located receivers and let a busy pair starve another pair's
        # wakeups — VERDICT r2 weak item 4).
        self._proc_mus: Dict[int, threading.Lock] = {}

    # ------------------------------------------------------------------ sizes
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def axes(self) -> Tuple[str, ...]:
        return self._axes

    @property
    def rank(self) -> int:
        return self._topo.rank

    @property
    def size(self) -> int:
        return self._topo.size

    @property
    def intra_rank(self) -> int:
        return self._topo.intra_rank

    @property
    def intra_size(self) -> int:
        return self._topo.intra_size

    @property
    def inter_rank(self) -> int:
        return self._topo.inter_rank

    @property
    def inter_size(self) -> int:
        return self._topo.inter_size

    # -------------------------------------------------------- in-graph plane
    @property
    def axis_name(self):
        """Axis name (or tuple) for ``lax.psum`` etc. inside traced code."""
        return self._axes if len(self._axes) > 1 else self._axes[0]

    def axis_index(self):
        """Collapsed linear rank of the executing device (in-graph)."""
        return lax.axis_index(self._axes)

    def psum(self, x):
        return lax.psum(x, self.axis_name)

    def pmean(self, x):
        return lax.pmean(x, self.axis_name)

    def pmax(self, x):
        return lax.pmax(x, self.axis_name)

    def pmin(self, x):
        return lax.pmin(x, self.axis_name)

    def ppermute(self, x, perm: Sequence[Tuple[int, int]]):
        return lax.ppermute(x, self.axis_name, perm=list(perm))

    def all_gather(self, x, axis: int = 0, tiled: bool = False):
        return lax.all_gather(x, self.axis_name, axis=axis, tiled=tiled)

    def all_to_all(self, x, split_axis: int, concat_axis: int, tiled: bool = False):
        return lax.all_to_all(
            x, self.axis_name, split_axis=split_axis, concat_axis=concat_axis,
            tiled=tiled,
        )

    def spmd(self, f: Callable, in_specs, out_specs, **kw) -> Callable:
        """``shard_map`` bound to this communicator's mesh — the entry point
        for writing rank-local code (the SPMD analog of an MPMD rank body)."""
        return jax.shard_map(
            f, mesh=self._mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    # --------------------------------------------------------------- helpers
    @property
    def _spec(self) -> P:
        return P(self._axes)

    def rankwise_sharding(self) -> NamedSharding:
        """Sharding for rankwise arrays (leading ``size`` axis over our axes)."""
        return NamedSharding(self._mesh, self._spec)

    def shard_rankwise(self, tree: Any) -> Any:
        """Place a host pytree into rankwise layout (leading axis = rank).

        Single-process: pass the full ``(size, ...)`` array.  Multi-process:
        every process passes *its own rows* (leading dim = its rank count, in
        rank order — what ``scatter_dataset`` hands each host); the global
        array is assembled without any host gathering the whole thing, the
        SPMD form of the reference's MPI scatter."""
        sh = self.rankwise_sharding()
        size = self.size
        nproc = self._nproc

        my_ranks = (
            len(self._topo.ranks_of_proc(jax.process_index()))
            if nproc > 1
            else size
        )

        def put(x):
            # Already device-resident with the target sharding (e.g. a
            # DevicePrefetchIterator batch): hand it back untouched — an
            # np.asarray here would round-trip the batch through host memory
            # every step (and crash multi-host on non-addressable shards).
            if isinstance(x, jax.Array) and x.sharding == sh:
                return x
            x = np.asarray(x)
            shape = np.shape(x)
            if nproc > 1:
                # Each process passes rows for ITS ranks; the global leading
                # dim scales by rows-per-rank × size (correct even when rank
                # ownership is ragged across processes).
                if my_ranks == 0 or shape[0] % my_ranks != 0:
                    raise ValueError(
                        f"local leading dim {shape[0]} is not a multiple of "
                        f"this process's rank count {my_ranks}"
                    )
                rows_per_rank = shape[0] // my_ranks
                gshape = (rows_per_rank * size,) + tuple(shape[1:])
                return jax.make_array_from_process_local_data(sh, x, gshape)
            if shape and shape[0] % size != 0:
                raise ValueError(
                    f"leading dim {shape[0]} is not divisible by the "
                    f"communicator size {size} (global batch / rankwise "
                    f"arrays must split evenly over the mesh)"
                )
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(put, tree)

    def shard_batch(self, tree: Any) -> Any:
        """Shard a global batch's leading dim over this communicator's axes —
        the per-chip half of ``scatter_dataset``'s two-level sharding.
        Same placement as rankwise layout (leading dim split over our axes)."""
        return self.shard_rankwise(tree)

    def place(self, x: Any, sharding: NamedSharding) -> Any:
        """Place one host array onto the mesh with ``sharding``.  The caller
        must hold the full (host-identical) value; under multi-process the
        global array is assembled from local slices via
        ``make_array_from_callback`` (``device_put`` with a multi-host
        sharding is not allowed)."""
        if self._nproc > 1:
            x = np.asarray(x)
            return jax.make_array_from_callback(
                np.shape(x), sharding, lambda idx: x[idx]
            )
        return jax.device_put(x, sharding)

    def replicate(self, tree: Any) -> Any:
        sh = NamedSharding(self._mesh, P())
        return jax.tree_util.tree_map(lambda x: self.place(x, sh), tree)

    def tile_rankwise(self, tree: Any) -> Any:
        """Stack ``size`` copies of a local pytree into rankwise layout."""
        # Multi-process: each process contributes only its own ranks' rows.
        local_rows = (
            len(self._topo.ranks_of_proc(jax.process_index()))
            if self._nproc > 1
            else self.size
        )
        return self.shard_rankwise(
            jax.tree_util.tree_map(
                lambda x: np.broadcast_to(
                    np.asarray(x)[None], (local_rows,) + np.shape(x)
                ),
                tree,
            )
        )

    def _jitted(self, key, build: Callable[[], Callable]) -> Callable:
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._fn_cache[key] = build()
        return fn

    def _rankwise_map(self, key, body: Callable) -> Callable:
        """jit(shard_map(tree_map(body))) with rankwise in/out specs."""

        def build():
            def mapped(tree):
                return jax.tree_util.tree_map(body, tree)

            return jax.jit(
                jax.shard_map(
                    mapped,
                    mesh=self._mesh,
                    in_specs=self._spec,
                    out_specs=self._spec,
                    check_vma=True,
                )
            )

        return self._jitted(key, build)

    def _collapsed_index(self):
        return lax.axis_index(self._axes)

    # ------------------------------------------------------- eager array plane
    def grad_reduce_leaf(self, g):
        """In-graph per-leaf gradient mean — shared by the eager
        ``allreduce_grad`` facade and the optimizer's jitted train step.

        Honors ``allreduce_grad_dtype`` (fp16/bf16 wire format; the 1/size
        division fused into the cast-back, as the reference fused it into its
        unpack kernel — ``pure_nccl_communicator.py``)."""
        wire = self.allreduce_grad_dtype
        axes = self.axis_name
        if wire is not None and g.dtype != wire:
            y = lax.psum(g.astype(wire), axes)
            return (y.astype(g.dtype) / self.size).astype(g.dtype)
        return lax.pmean(g, axes)

    def allreduce_grad(self, grads: Any) -> Any:
        """Mean-allreduce of a rankwise grad pytree (one fused collective)."""
        return self._rankwise_map(
            ("allreduce_grad", self.allreduce_grad_dtype), self.grad_reduce_leaf
        )(grads)

    def allreduce(self, x: Any, op: str = "sum") -> Any:
        axes = self.axis_name
        ops = {
            "sum": lambda t: lax.psum(t, axes),
            "mean": lambda t: lax.pmean(t, axes),
            "max": lambda t: lax.pmax(t, axes),
            "min": lambda t: lax.pmin(t, axes),
        }
        if op not in ops:
            raise ValueError(f"unknown op {op!r}")
        return self._rankwise_map(("allreduce", op), ops[op])(x)

    def bcast_data(self, data: Any, root: int = 0) -> Any:
        axes = self.axis_name

        def body(x):
            idx = self._collapsed_index()
            keep = (idx == root).astype(x.dtype)
            return lax.psum(x * keep, axes)

        return self._rankwise_map(("bcast_data", root), body)(data)

    def alltoall(self, xs: Any) -> Any:
        """Rankwise all-to-all.  Leaf shape ``(size, size, ...)``: slot ``r``
        row ``j`` is rank r's chunk destined for rank j; output slot ``r`` row
        ``j`` is the chunk received from rank j."""
        axes = self.axis_name

        def body(x):  # x: (1, size, ...)
            z = x[0]
            w = lax.all_to_all(z, axes, split_axis=0, concat_axis=0, tiled=True)
            return w.reshape(x.shape)

        return self._rankwise_map(("alltoall",), body)(xs)

    def allgather(self, x: Any) -> Any:
        """Rankwise allgather: ``(size, ...)`` → ``(size, size, ...)`` (every
        slot holds the full stack)."""
        axes = self.axis_name

        def body(z):  # z: (1, ...)
            return lax.all_gather(z[0], axes, axis=0)[None]

        return self._rankwise_map(("allgather",), body)(x)

    #: gather/scatter are O(size×)-traffic control-plane facades; payloads
    #: past this size trigger a loud warning steering users to the real
    #: data-plane paths (shard_batch / in-graph collectives).
    _CONTROL_PLANE_WARN_BYTES = 1 << 20

    def _warn_if_tensor_sized(self, x: Any, op: str) -> None:
        try:
            nbytes = sum(
                int(np.prod(np.shape(leaf)))
                * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
                for leaf in jax.tree_util.tree_leaves(x)
            )
        except Exception:
            return
        if nbytes > self._CONTROL_PLANE_WARN_BYTES:
            import warnings

            warnings.warn(
                f"{op}() moved {nbytes / 2**20:.1f} MiB through an "
                f"O(size x) broadcast facade (every device receives the "
                "full payload under SPMD). These exist for control-plane "
                "data; route tensor-sized data through shard_batch / "
                "in-graph collectives instead.",
                stacklevel=3,
            )

    def gather(self, x: Any, root: int = 0) -> Any:
        # SPMD note: every slot receives the stack (root only matters for the
        # object plane); documented deviation from the MPMD reference.
        #
        # Traffic: O(size×) the payload reaches EVERY device (an allgather) —
        # under SPMD there is no cheaper gather-to-one, since all devices run
        # the same program.  Fine for the control-plane uses these facades
        # exist for; route tensor-sized data through ``shard_batch`` /
        # in-graph collectives instead.
        self._warn_if_tensor_sized(x, "gather")
        return self.allgather(x)

    def scatter(self, x: Any, root: int = 0) -> Any:
        """Slot ``root`` holds ``(size, ...)`` rows; output slot ``r`` gets row
        ``r``.  Leaf shape ``(size, size, ...)`` → ``(size, ...)``.

        Traffic: the mask+psum broadcasts root's full ``(size, ...)`` buffer
        to every device before each picks its row — O(size×) the per-rank
        payload, the SPMD cost of a root-scatter (see :meth:`gather`).
        Control-plane sized data only."""
        self._warn_if_tensor_sized(x, "scatter")
        axes = self.axis_name

        def body(z):  # z: (1, size, ...)
            idx = self._collapsed_index()
            keep = (idx == root).astype(z.dtype)
            rows = lax.psum(z[0] * keep, axes)  # (size, ...) replicated
            return lax.dynamic_index_in_dim(rows, idx, axis=0, keepdims=True)

        return self._rankwise_map(("scatter", root), body)(x)

    def permute(self, x: Any, perm: Sequence[Tuple[int, int]]) -> Any:
        """Rankwise point-to-point: ``perm`` is ``[(src, dst), ...]``; slots
        with no incoming edge receive zeros (reference analog: paired
        ``send``/``recv``)."""
        perm = tuple((int(s), int(d)) for s, d in perm)
        axes = self.axis_name

        def body(z):
            return lax.ppermute(z, axes, perm=list(perm))

        return self._rankwise_map(("permute", perm), body)(x)

    def send(self, x: Any, dest: int, source: int) -> Any:
        """Eager point-to-point as a permute; see ``functions`` for the
        differentiable in-graph version."""
        return self.permute(x, [(source, dest)])

    # ---------------------------------------------------------- object plane
    @property
    def _nproc(self) -> int:
        return jax.process_count()

    def _p2p_tree_bcast(self, obj: Any, root_proc: int) -> Any:
        """Binomial-tree broadcast over this communicator's process group
        on the rank-addressed p2p plane (each process speaks through its
        FIRST rank): log2(group) rounds, every edge a distinct
        ``(source, dest)`` rank pair so the frame demux can't cross-pair.
        """
        procs = self._topo.procs
        me = jax.process_index()
        rel = (procs.index(me) - procs.index(root_proc)) % len(procs)

        def _first_rank_of_rel(r: int) -> int:
            p = procs[(r + procs.index(root_proc)) % len(procs)]
            return self._topo.ranks_of_proc(p)[0]

        mask = 1
        while mask < len(procs):
            if rel & mask:
                obj = self.recv_obj(
                    source=_first_rank_of_rel(rel - mask),
                    dest=self.rank,
                    timeout=120.0,
                )
                break
            mask <<= 1
        mask >>= 1
        while mask >= 1:
            if rel + mask < len(procs):
                self.send_obj(
                    obj,
                    dest=_first_rank_of_rel(rel + mask),
                    source=self.rank,
                )
            mask >>= 1
        return obj

    @property
    def _use_obj_p2p(self) -> bool:
        """Prefer the native host object plane for object collectives when
        it is bootstrapped (``CMN_TPU_HOSTS``): it is the resilience-
        integrated path (per-op deadlines, attributed ``PeerFailedError``,
        failure-detector slicing), and it keeps control-plane pickles off
        the XLA device plane entirely — routing pickled bytes through
        device collectives was also observed to re-materialize corrupted
        on this container's jax (0.4.37, gloo, n>2).  Without the env —
        or without a native toolchain to build the transport — the
        XLA-collective fallback still works (multi-host pods launched by a
        scheduler that never exported the object-plane ports; g++-less
        hosts, which _native promises degrade gracefully)."""
        if not os.environ.get("CMN_TPU_HOSTS"):
            return False
        from chainermn_tpu import _native

        return _native.load_hostcomm() is not None

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        procs = self._topo.procs
        if self._nproc == 1 or len(procs) == 1:
            # Single process, or a group living entirely on this process
            # (e.g. ``sub("intra")`` on a pod host) — identity.
            return obj
        if len(procs) < self._nproc or self._use_obj_p2p:
            # Group spans a strict SUBSET of processes (e.g. ``sub``/``split``
            # over one replica of a 3-level mesh), or the native object
            # plane is up (preferred — see ``_use_obj_p2p``).  For subsets
            # multihost_utils would be WRONG regardless (it spans ALL
            # processes and would elect one source per group); fan out
            # over the rank-addressed p2p plane inside the group instead.
            # (Groups partition processes, so cross-group frames can't mix.)
            # Binomial tree over the group's processes — log2(group) depth,
            # not an O(group) serial loop through the root.
            return self._p2p_tree_bcast(obj, self._topo.proc_of(root))
        from jax.experimental import multihost_utils

        is_src = jax.process_index() == self._root_proc(root)
        payload = pickle.dumps(obj) if is_src else b""
        nbytes = int(
            multihost_utils.broadcast_one_to_all(
                np.int64(len(payload)), is_source=is_src
            )
        )
        buf = np.frombuffer(payload.ljust(nbytes, b"\0"), dtype=np.uint8) if payload else np.zeros(nbytes, np.uint8)
        out = multihost_utils.broadcast_one_to_all(buf, is_source=is_src)
        return pickle.loads(np.asarray(out).tobytes())

    def _root_proc(self, root_rank: int) -> int:
        """Owning process of a communicator rank — the exact per-rank map from
        the mesh topology (``Topology.proc_of_rank``), not a division guess."""
        self._check_rank(root_rank, "rank")
        return self._topo.proc_of(root_rank)

    def _check_rank(self, r: int, what: str) -> None:
        if not (0 <= int(r) < self.size):
            raise ValueError(
                f"{what} {r} out of range for communicator size {self.size}"
            )

    def allgather_obj(self, obj: Any) -> List[Any]:
        """One object per participating *process*, in ``Topology.procs``
        order (the reference gathered per MPMD rank = per process)."""
        procs = self._topo.procs
        if self._nproc == 1 or len(procs) == 1:
            return [obj]
        if len(procs) < self._nproc or self._use_obj_p2p:
            # Subset group (where multihost_utils would be wrong), or the
            # native object plane is up (preferred — see ``_use_obj_p2p``):
            # linear gather to the group's first process (inherently
            # O(group) at the root) over the rank-addressed p2p plane,
            # then binomial-tree bcast of the gathered list back out.
            me = jax.process_index()
            root_proc = procs[0]
            root_rank = self._topo.ranks_of_proc(root_proc)[0]
            if me == root_proc:
                objs = [obj]
                for p in procs[1:]:
                    objs.append(
                        self.recv_obj(
                            source=self._topo.ranks_of_proc(p)[0],
                            dest=root_rank,
                            timeout=120.0,
                        )
                    )
            else:
                self.send_obj(obj, dest=root_rank, source=self.rank)
                objs = None
            return self._p2p_tree_bcast(objs, root_proc)
        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        n = int(np.max(multihost_utils.process_allgather(np.int64(payload.size))))
        padded = np.zeros(n + 8, np.uint8)
        padded[:8] = np.frombuffer(np.int64(payload.size).tobytes(), np.uint8)
        padded[8 : 8 + payload.size] = payload
        stacked = multihost_utils.process_allgather(padded)
        out = []
        for row in np.asarray(stacked).reshape(self._nproc, -1):
            ln = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
            out.append(pickle.loads(row[8 : 8 + ln].tobytes()))
        return out

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        objs = self.allgather_obj(obj)
        if self._nproc == 1 or jax.process_index() == self._root_proc(root):
            return objs
        return None

    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any:
        return self._reduce_objs(self.allgather_obj(obj), op)

    @property
    def _hostcomm(self):
        """Native TCP object plane for multi-process point-to-point
        (``chainermn_tpu.hostcomm.HostComm``), bootstrapped from the
        ``CMN_TPU_HOSTS``/``CMN_TPU_RANK`` env, lazily.

        Construction is locked: concurrent first use from several threads
        (send + receivers racing) would otherwise build SEVERAL peer
        meshes in one process — the duplicate listeners/dials poison every
        rank's bootstrap."""
        hc = getattr(self, "_hostcomm_cached", None)
        if hc is None:
            with self._demux_mu:
                hc = getattr(self, "_hostcomm_cached", None)
                if hc is None:
                    from chainermn_tpu.hostcomm import HostComm

                    hc = self._hostcomm_cached = HostComm()
        return hc

    def _self_q(self, source: int, dest: int) -> _queue.SimpleQueue:
        with self._demux_mu:
            return self._self_queue.setdefault(
                (int(source), int(dest)), _queue.SimpleQueue()
            )

    def send_obj(self, obj: Any, dest: int, source: Optional[int] = None) -> None:
        """Point-to-point object send addressed by *rank* (reference anchor
        ``MpiCommunicatorBase.send_obj``).

        ``source`` defaults to :attr:`rank` (this process's first rank); pass
        it explicitly when acting for a co-located rank — under
        single-controller SPMD one process legitimately speaks for several
        ranks, where each MPMD reference process spoke only for itself.
        Messages are framed ``(source, dest, obj)`` and demultiplexed on the
        exact pair, so interleaved senders can never cross-deliver.
        """
        src = self.rank if source is None else int(source)
        self._check_rank(src, "source")
        self._check_rank(dest, "dest")
        if self._nproc > 1 and self._topo.proc_of(dest) != jax.process_index():
            self._hostcomm.send_obj((src, int(dest), obj), self._topo.proc_of(dest))
            return
        self._self_q(src, dest).put(pickle.dumps(obj))

    def recv_obj(
        self,
        source: int,
        dest: Optional[int] = None,
        timeout: float = 60.0,
    ) -> Any:
        """Blocking receive of the next object sent from rank ``source`` to
        rank ``dest`` (default: :attr:`rank`), like an MPI recv — raises
        :class:`TimeoutError` after ``timeout`` seconds instead of deadlocking
        a wedged job."""
        dst = self.rank if dest is None else int(dest)
        self._check_rank(source, "source")
        self._check_rank(dst, "dest")
        q = self._self_q(source, dst)
        if self._nproc == 1 or self._topo.proc_of(source) == jax.process_index():
            try:
                return _unqueue(q.get(timeout=timeout))
            except _queue.Empty:
                raise TimeoutError(
                    f"recv_obj(source={source}, dest={dst}) timed out "
                    f"after {timeout}s"
                ) from None
        # Cross-process: drain frames from the source's process, delivering
        # ours and parking frames addressed to other co-located pairs.
        # Exactly ONE thread drains a given source process at a time (its
        # per-process lock, non-blocking); everyone else parks on their own
        # queue with a short timed get, which wakes the moment the drainer
        # parks a frame for them.  Receivers of DIFFERENT source processes
        # never contend.
        src_proc = self._topo.proc_of(source)
        with self._demux_mu:
            mu = self._proc_mus.setdefault(src_proc, threading.Lock())
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"recv_obj(source={source}, dest={dst}) timed out "
                    f"after {timeout}s"
                )
            if not mu.acquire(blocking=False):
                # Another thread is draining this process; wait on our own
                # queue (it will park our frame there if one arrives).
                try:
                    return _unqueue(q.get(timeout=min(remaining, 0.05)))
                except _queue.Empty:
                    continue
            try:
                # Re-check under the lock: the previous drainer may have
                # parked our frame between our get and the acquire.
                try:
                    return _unqueue(q.get_nowait())
                except _queue.Empty:
                    pass
                try:
                    frame = self._hostcomm.recv_obj(
                        src_proc, timeout_ms=int(min(remaining, 0.25) * 1000)
                    )
                except TimeoutError as e:
                    # Only a genuine slice timeout means "keep polling".
                    # PeerFailedError subclasses TimeoutError but carries
                    # a kind: a detector DEAD verdict or a hard transport
                    # failure must propagate attributed, not degrade into
                    # a busy-loop ending in a generic deadline error.
                    if getattr(e, "kind", "timeout") != "timeout":
                        raise
                    continue
                # Dispatch UNDER the drain lock: parking after release would
                # let a concurrent same-pair receiver drain a LATER frame
                # first and break per-pair FIFO ordering.
                s, d, payload = frame
                if (s, d) == (int(source), dst):
                    return payload
                self._self_q(s, d).put(_Parked(payload))
            finally:
                mu.release()

    # ----------------------------------------------------------- structuring
    def sub(self, axes: Sequence[str] | str) -> "XlaCommunicator":
        """Communicator over a subset of this mesh's axes — the idiomatic form
        of the reference's ``split`` for hybrid DP×MP grids."""
        if isinstance(axes, str):
            axes = (axes,)
        return XlaCommunicator(
            self._mesh, axes=axes, allreduce_grad_dtype=self.allreduce_grad_dtype
        )

    def split(self, color, key=None) -> Dict[int, "XlaCommunicator"]:
        """MPI_Comm_split analog (reference anchor ``CommunicatorBase.split``).

        Single-controller form: ``color``/``key`` are length-``size`` sequences
        (per-rank values, as each MPMD rank would have passed).  Returns a dict
        ``{color: XlaCommunicator}`` over device subsets, each ordered by key.
        """
        colors = list(color)
        if len(colors) != self.size:
            raise ValueError("color must have one entry per rank")
        keys = list(key) if key is not None else list(range(self.size))
        devs = list(self._mesh.devices.reshape(-1))
        groups: Dict[int, List] = {}
        for r, (c, k) in enumerate(zip(colors, keys)):
            groups.setdefault(c, []).append((k, r, devs[r]))
        out = {}
        for c, members in groups.items():
            members.sort()
            sub_devs = np.array([d for _, _, d in members])
            sub_mesh = Mesh(sub_devs, (mesh_lib.DATA_AXIS,))
            out[c] = XlaCommunicator(
                sub_mesh, allreduce_grad_dtype=self.allreduce_grad_dtype
            )
        return out


class DummyCommunicator(XlaCommunicator):
    """No-op-allreduce communicator for upper-bound scaling benchmarks
    (reference anchor: ``dummy_communicator.py — DummyCommunicator``): all
    collectives short-circuit locally, so benchmark deltas vs
    :class:`XlaCommunicator` isolate communication cost.  Benchmarking only:
    without the allreduce, per-device params silently diverge even though the
    train step's output sharding claims replication."""

    def grad_reduce_leaf(self, g):
        return g

    def allreduce_grad(self, grads: Any) -> Any:
        return grads

    def allreduce(self, x: Any, op: str = "sum") -> Any:
        return x

    def bcast_data(self, data: Any, root: int = 0) -> Any:
        return data
