"""Host object-plane communicator over the native TCP transport.

Fills the role the reference gave to mpi4py's pickled-object operations
(``CommunicatorBase.send_obj/recv_obj/bcast_obj/gather_obj/allreduce_obj`` —
SURVEY.md §2.2): control-plane exchange of arbitrary Python objects between
host processes.  The TPU tensor plane never goes through here — that is XLA
collectives; this carries filenames, metric dicts, dataset orders,
checkpoint-iteration votes.

Topology comes from env (``CMN_TPU_HOSTS`` = comma-separated ``ip:port``,
``CMN_TPU_RANK``) or explicit arguments, mirroring how ``jax.distributed``
is bootstrapped.  Composite ops (barrier/bcast/gather/allgather/allreduce)
are built from framed point-to-point in Python; the wire is native C++
(`_native/hostcomm.cpp`).

Resilience integration (``chainermn_tpu/resilience/``):

* **Per-op deadlines** — every send/recv is bounded by the communicator's
  ``timeout_ms`` unless overridden, and failures raise
  :class:`~chainermn_tpu.resilience.PeerFailedError` carrying *which peer*
  and *which op* (it subclasses ``TimeoutError``, so pre-resilience
  ``except TimeoutError`` call sites still match).
* **Failure detection** — with a :class:`FailureDetector` attached
  (:meth:`attach_detector`), blocking waits are sliced by the heartbeat
  interval and re-check the detector between slices: a collective blocked
  against a dead peer fails in ~1 heartbeat interval, not after the full
  transport timeout.
* **Bootstrap retry** — mesh establishment runs under a deterministic
  :class:`~chainermn_tpu.resilience.RetryPolicy` (transient port races on
  dense CI hosts no longer kill the job on the first dial).
* **Fault injection** — ``CMN_FAULT`` hook points on barrier/send/recv
  (see :mod:`chainermn_tpu.resilience.faults`).

Observability (``chainermn_tpu/observability/``): every op records a span
into the process tracer's bounded ring — fine-grained ``send_obj`` /
``recv_obj`` spans carrying peer + byte count (``detail`` names the
composite they serve), and coarse spans around each composite so the
flight recorder can say *which collective* a dead rank was sitting in.
Auxiliary meshes built with ``enable_faults=False`` (the heartbeat plane)
are untraced by default — a 2 Hz heartbeat would churn the span ring out
of anything useful — overridable via ``enable_trace``.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import pickle
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from chainermn_tpu import _native, observability as _obs
from chainermn_tpu.observability import tracing as _tracing
from chainermn_tpu.resilience import faults as _faults
from chainermn_tpu.resilience.detector import PeerFailedError
from chainermn_tpu.resilience.policy import RetryPolicy

#: Mesh bootstrap retry: 3 attempts, 0.5s/1s deterministic backoff.
DEFAULT_BOOTSTRAP_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.5, multiplier=2.0, max_delay_s=5.0,
    retry_on=(RuntimeError,),
)


class HostComm:
    """Point-to-point + composed collectives between host processes."""

    def __init__(
        self,
        rank: Optional[int] = None,
        hosts: Optional[Sequence[Tuple[str, int]]] = None,
        timeout_ms: int = 30000,
        bootstrap_retry: Optional[RetryPolicy] = None,
        enable_faults: bool = True,
        enable_trace: Optional[bool] = None,
    ):
        if hosts is None:
            spec = os.environ.get("CMN_TPU_HOSTS", "")
            if not spec:
                raise ValueError(
                    "HostComm needs hosts=[(ip, port), ...] or CMN_TPU_HOSTS"
                )
            hosts = []
            for part in spec.split(","):
                ip, port = part.rsplit(":", 1)
                hosts.append((ip, int(port)))
        if rank is None:
            rank = int(os.environ.get("CMN_TPU_RANK", "-1"))
        if not (0 <= rank < len(hosts)):
            raise ValueError(f"bad rank {rank} for {len(hosts)} hosts")
        self.rank = int(rank)
        self.size = len(hosts)
        self.timeout_ms = int(timeout_ms)
        self._detector = None
        # ``enable_faults=False`` exists for auxiliary meshes (the failure
        # detector's heartbeat plane): CMN_FAULT specs target the DATA
        # plane's op counters; injecting them into heartbeat traffic too
        # would fire on the wrong counter and skew detection timings.
        # The PROCESS-WIDE injector is shared with the trainer loop so a
        # hang fired from any site freezes the callbacks registered here.
        self._faults = _faults.process_injector() if enable_faults else None
        # Span tracing follows ``enable_faults`` by default: auxiliary
        # meshes (heartbeats) opt out of both for the same reason — they
        # are not the data plane being observed.
        if enable_trace is None:
            enable_trace = enable_faults
        self._trace = (
            _tracing.tracer() if enable_trace and _obs.enabled() else None
        )
        self._lib = _native.load_hostcomm()
        if self._lib is None:
            raise RuntimeError("native hostcomm unavailable (g++ missing?)")
        c_hosts = (ctypes.c_char_p * self.size)(
            *[h.encode() for h, _ in hosts]
        )
        c_ports = (ctypes.c_int * self.size)(*[p for _, p in hosts])

        def _bootstrap():
            h = self._lib.hostcomm_init(
                self.rank, self.size, c_hosts, c_ports, timeout_ms
            )
            if not h:
                raise RuntimeError(
                    f"hostcomm rank {rank}: failed to establish the peer mesh"
                )
            return h

        retry = bootstrap_retry or DEFAULT_BOOTSTRAP_RETRY
        self._h = retry.call(_bootstrap)

    # ------------------------------------------------------------ resilience
    def attach_detector(self, detector) -> None:
        """Wire a :class:`~chainermn_tpu.resilience.FailureDetector` in:
        blocking waits start slicing by its heartbeat interval (attributed
        fast-fail), and an injected ``hang`` freezes its beats too (a hung
        process must look dead to its peers)."""
        self._detector = detector
        if self._faults is not None:
            self._faults.add_freeze_callback(detector.freeze)

    def _peer_error(
        self, peer: int, op: str, reason: str, kind: str = "timeout"
    ) -> PeerFailedError:
        return PeerFailedError(
            peer, op=op, rank=self.rank, reason=reason, kind=kind
        )

    def _wait_frame(self, source: int, timeout_ms: int, op: str) -> int:
        """Wait for the next frame from ``source`` (leaving it queued) and
        return its length.  Sliced by the detector's heartbeat interval when
        one is attached, so a dead peer raises attributed long before the
        deadline; ``timeout_ms < 0`` waits forever (detector checks still
        apply)."""
        deadline = (
            None if timeout_ms < 0
            else time.monotonic() + timeout_ms / 1000.0
        )
        while True:
            if self._detector is not None:
                self._detector.check(op=op)
                slice_ms = max(int(self._detector.interval_s * 1000), 20)
            else:
                slice_ms = -1
            if deadline is None:
                wait_ms = slice_ms
            else:
                remain_ms = int((deadline - time.monotonic()) * 1000)
                if remain_ms <= 0:
                    raise self._peer_error(
                        source, op,
                        f"recv timed out after {timeout_ms}ms",
                    )
                wait_ms = (
                    remain_ms if slice_ms < 0 else min(remain_ms, slice_ms)
                )
            n = self._lib.hostcomm_recv(self._h, source, None, 0, wait_ms)
            if n >= 0:
                return int(n)
            if n == -1:  # this slice timed out; loop re-checks detector
                if deadline is None and self._detector is None:
                    raise self._peer_error(
                        source, op, "recv timed out (transport)"
                    )
                continue
            raise self._peer_error(
                source, op, f"recv failed (rc={n})", kind="transport"
            )

    # ------------------------------------------------------- point-to-point
    def _span(self, op: str, peer: Optional[int] = None,
              parent_op: Optional[str] = None):
        """Fine-grained p2p span; ``parent_op`` (the composite being
        served) lands in ``detail`` so op-level metrics stay per-primitive
        while the ring still says which collective the frame belonged to."""
        if self._trace is None:
            return contextlib.nullcontext()
        detail = parent_op if parent_op != op else None
        return self._trace.span(op, peer=peer, detail=detail)

    def send_obj(
        self,
        obj: Any,
        dest: int,
        timeout_ms: Optional[int] = None,
        op: str = "send_obj",
    ) -> None:
        with self._span("send_obj", peer=dest, parent_op=op) as sp:
            self._send_obj(obj, dest, timeout_ms, op, sp)

    def _send_obj(self, obj, dest, timeout_ms, op, span) -> None:
        if self._faults is not None:
            if self._faults.hook("send") == "drop":
                # Injected drop: the message is lost on the wire — the
                # sender proceeds as if delivered, the receiver never
                # sees it (how a real lost frame presents).
                return
        timeout_ms = self.timeout_ms if timeout_ms is None else timeout_ms
        payload = pickle.dumps(obj)
        if span is not None:
            span.nbytes = len(payload)
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        rc = self._lib.hostcomm_send(
            self._h, dest, buf, len(payload), timeout_ms
        )
        if rc == -3:
            raise self._peer_error(
                dest, op,
                f"send timed out after {timeout_ms}ms (peer not draining)",
            )
        if rc != 0:
            raise self._peer_error(
                dest, op, f"send failed (rc={rc})", kind="transport"
            )

    def recv_obj(
        self,
        source: int,
        timeout_ms: Optional[int] = None,
        op: str = "recv_obj",
    ) -> Any:
        with self._span("recv_obj", peer=source, parent_op=op) as sp:
            if self._faults is not None:
                if self._faults.hook("recv") == "drop":
                    # Injected drop: consume and discard one frame, then
                    # deliver the next as if the first never arrived.
                    self._pop_frame(source, timeout_ms, op)
            timeout_ms = self.timeout_ms if timeout_ms is None else timeout_ms
            frame = self._pop_frame(source, timeout_ms, op)
            if sp is not None:
                sp.nbytes = len(frame)
            return pickle.loads(frame)

    def _pop_frame(
        self, source: int, timeout_ms: Optional[int], op: str
    ) -> bytes:
        timeout_ms = self.timeout_ms if timeout_ms is None else timeout_ms
        n = self._wait_frame(source, timeout_ms, op)
        # The frame is already queued (the peek waited for arrival); the pop
        # is just the memcpy, so a zero wait suffices.
        buf = (ctypes.c_uint8 * max(int(n), 1))()
        got = self._lib.hostcomm_recv(self._h, source, buf, int(n), 0)
        if got != n:
            raise self._peer_error(
                source, op, f"frame length changed {n}->{got}",
                kind="transport",
            )
        return bytes(buf[: int(n)])

    # ----------------------------------------------------------- composites
    def _composite_span(self, op: str, peer: Optional[int] = None):
        """Coarse span around a whole composed collective — "which
        collective is this rank sitting in" for the flight recorder."""
        if self._trace is None:
            return contextlib.nullcontext()
        return self._trace.span(op, peer=peer)

    def barrier(self) -> None:
        """Dissemination barrier: log2(size) rounds of paired send/recv."""
        with self._composite_span("barrier"):
            if self._faults is not None:
                self._faults.hook("barrier")
            k = 1
            while k < self.size:
                self.send_obj((), (self.rank + k) % self.size, op="barrier")
                self.recv_obj((self.rank - k) % self.size, op="barrier")
                k *= 2

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        """Binomial-tree broadcast rooted at ``root`` (log2(size) depth)."""
        with self._composite_span("bcast_obj", peer=root):
            return self._bcast_obj(obj, root)

    def _bcast_obj(self, obj: Any, root: int) -> Any:
        rel = (self.rank - root) % self.size
        mask = 1
        while mask < self.size:
            if rel & mask:
                obj = self.recv_obj(
                    (self.rank - mask) % self.size, op="bcast_obj"
                )
                break
            mask <<= 1
        mask >>= 1
        while mask >= 1:
            if rel + mask < self.size:
                self.send_obj(
                    obj, (self.rank + mask) % self.size, op="bcast_obj"
                )
            mask >>= 1
        return obj

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        with self._composite_span("gather_obj", peer=root):
            if self.rank == root:
                out: List[Any] = [None] * self.size
                out[self.rank] = obj
                for r in range(self.size):
                    if r != root:
                        out[r] = self.recv_obj(r, op="gather_obj")
                return out
            self.send_obj(obj, root, op="gather_obj")
            return None

    def allgather_obj(self, obj: Any) -> List[Any]:
        with self._composite_span("allgather_obj"):
            gathered = self.gather_obj(obj, root=0)
            return self.bcast_obj(gathered, root=0)

    def allreduce_obj(self, obj: Any, op: Callable[[Any, Any], Any]) -> Any:
        with self._composite_span("allreduce_obj"):
            vals = self.allgather_obj(obj)
            acc = vals[0]
            for v in vals[1:]:
                acc = op(acc, v)
            return acc

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.hostcomm_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
