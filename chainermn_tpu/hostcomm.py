"""Host object-plane communicator over the native TCP transport.

Fills the role the reference gave to mpi4py's pickled-object operations
(``CommunicatorBase.send_obj/recv_obj/bcast_obj/gather_obj/allreduce_obj`` —
SURVEY.md §2.2): control-plane exchange of arbitrary Python objects between
host processes.  The TPU tensor plane never goes through here — that is XLA
collectives; this carries filenames, metric dicts, dataset orders,
checkpoint-iteration votes.

Topology comes from env (``CMN_TPU_HOSTS`` = comma-separated ``ip:port``,
``CMN_TPU_RANK``) or explicit arguments, mirroring how ``jax.distributed``
is bootstrapped.  Composite ops (barrier/bcast/gather/allgather/allreduce)
are built from framed point-to-point in Python; the wire is native C++
(`_native/hostcomm.cpp`).
"""

from __future__ import annotations

import ctypes
import os
import pickle
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from chainermn_tpu import _native


class HostComm:
    """Point-to-point + composed collectives between host processes."""

    def __init__(
        self,
        rank: Optional[int] = None,
        hosts: Optional[Sequence[Tuple[str, int]]] = None,
        timeout_ms: int = 30000,
    ):
        if hosts is None:
            spec = os.environ.get("CMN_TPU_HOSTS", "")
            if not spec:
                raise ValueError(
                    "HostComm needs hosts=[(ip, port), ...] or CMN_TPU_HOSTS"
                )
            hosts = []
            for part in spec.split(","):
                ip, port = part.rsplit(":", 1)
                hosts.append((ip, int(port)))
        if rank is None:
            rank = int(os.environ.get("CMN_TPU_RANK", "-1"))
        if not (0 <= rank < len(hosts)):
            raise ValueError(f"bad rank {rank} for {len(hosts)} hosts")
        self.rank = int(rank)
        self.size = len(hosts)
        self._lib = _native.load_hostcomm()
        if self._lib is None:
            raise RuntimeError("native hostcomm unavailable (g++ missing?)")
        c_hosts = (ctypes.c_char_p * self.size)(
            *[h.encode() for h, _ in hosts]
        )
        c_ports = (ctypes.c_int * self.size)(*[p for _, p in hosts])
        self._h = self._lib.hostcomm_init(
            self.rank, self.size, c_hosts, c_ports, timeout_ms
        )
        if not self._h:
            raise RuntimeError(
                f"hostcomm rank {rank}: failed to establish the peer mesh"
            )

    # ------------------------------------------------------- point-to-point
    def send_obj(self, obj: Any, dest: int) -> None:
        payload = pickle.dumps(obj)
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        rc = self._lib.hostcomm_send(self._h, dest, buf, len(payload))
        if rc != 0:
            raise RuntimeError(f"send to {dest} failed (rc={rc})")

    def recv_obj(self, source: int, timeout_ms: int = -1) -> Any:
        t0 = time.monotonic()
        n = self._lib.hostcomm_recv(self._h, source, None, 0, timeout_ms)
        if n == -1:
            raise TimeoutError(f"recv from {source} timed out")
        if n < 0:
            raise RuntimeError(f"recv from {source} failed (rc={n})")
        if timeout_ms >= 0:
            # The peek already consumed part of the budget; the pop gets the
            # remainder (the frame is already queued, so this is just the
            # memcpy — but keep the total wait ≤ timeout_ms, not 2×).
            elapsed_ms = int((time.monotonic() - t0) * 1000)
            timeout_ms = max(timeout_ms - elapsed_ms, 0)
        buf = (ctypes.c_uint8 * max(int(n), 1))()
        got = self._lib.hostcomm_recv(self._h, source, buf, int(n), timeout_ms)
        if got != n:
            raise RuntimeError(f"recv from {source}: length changed {n}->{got}")
        return pickle.loads(bytes(buf[: int(n)]))

    # ----------------------------------------------------------- composites
    def barrier(self) -> None:
        """Dissemination barrier: log2(size) rounds of paired send/recv."""
        k = 1
        while k < self.size:
            self.send_obj((), (self.rank + k) % self.size)
            self.recv_obj((self.rank - k) % self.size)
            k *= 2

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        """Binomial-tree broadcast rooted at ``root`` (log2(size) depth)."""
        rel = (self.rank - root) % self.size
        mask = 1
        while mask < self.size:
            if rel & mask:
                obj = self.recv_obj((self.rank - mask) % self.size)
                break
            mask <<= 1
        mask >>= 1
        while mask >= 1:
            if rel + mask < self.size:
                self.send_obj(obj, (self.rank + mask) % self.size)
            mask >>= 1
        return obj

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        if self.rank == root:
            out: List[Any] = [None] * self.size
            out[self.rank] = obj
            for r in range(self.size):
                if r != root:
                    out[r] = self.recv_obj(r)
            return out
        self.send_obj(obj, root)
        return None

    def allgather_obj(self, obj: Any) -> List[Any]:
        gathered = self.gather_obj(obj, root=0)
        return self.bcast_obj(gathered, root=0)

    def allreduce_obj(self, obj: Any, op: Callable[[Any, Any], Any]) -> Any:
        vals = self.allgather_obj(obj)
        acc = vals[0]
        for v in vals[1:]:
            acc = op(acc, v)
        return acc

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.hostcomm_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
