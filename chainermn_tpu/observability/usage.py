"""Offline usage-ledger analyzer: ``python -m
chainermn_tpu.observability.usage report <path> [--json]``.

Renders a ``cmn-usage-1`` ledger export (:meth:`~chainermn_tpu.
observability.ledger.CostLedger.export`, committed sample:
``result/sample_usage_ledger.json``) as the operator's cost view:

* the per-tenant cost table — requests, terminal mix, tokens generated,
  prefill tokens computed vs prefix tokens saved, decode iterations,
  KV block-seconds (with each tenant's share of the fleet total),
  migration bytes, queue wait;
* top consumers by block-seconds (the quota-relevant scarce resource);
* cost of retries — what the fleet spent on requests that killed a
  replica (or were harvested from one) before terminating;
* prefix-cache savings — tokens served from cache vs computed;
* the conservation verdict the ledger carried at export time.

Same contract as ``analyze`` / ``perf`` / ``incident report``: stdin
never read, ``--json`` emits the machine-readable report, exit 0 on a
well-formed artifact.  ``tests/test_repo_health.py`` drives both modes
against the committed sample in CI.
"""

from __future__ import annotations

import json
import sys
from typing import Optional, Sequence

from chainermn_tpu.observability.ledger import DIMENSIONS, USAGE_SCHEMA


def _aggregate_records(records) -> dict:
    tenants: dict = {}
    for rec in records:
        t = tenants.setdefault(rec.get("tenant", "default"), {
            **{dim: 0 for dim in DIMENSIONS},
            "requests": 0, "by_status": {},
        })
        t["requests"] += 1
        status = rec.get("status")
        if status is not None:
            t["by_status"][status] = t["by_status"].get(status, 0) + 1
        for dim in DIMENSIONS:
            t[dim] += int(rec.get(dim, 0))
    return tenants


def load_report(path: str) -> dict:
    """Parse + analyze one ledger export.  Raises ``ValueError`` on a
    malformed or wrong-schema artifact (the CLI maps it to exit 2)."""
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("schema") != USAGE_SCHEMA:
        raise ValueError(
            f"{path}: not a {USAGE_SCHEMA} ledger export "
            f"(schema={data.get('schema') if isinstance(data, dict) else type(data).__name__!r})"
        )
    records = data.get("records") or []
    # Aggregate from the records when present (the analyzer must agree
    # with the ledger's own books); a records-free export (pre-trimmed
    # artifact) falls back to its embedded per-tenant table.
    tenants = (
        _aggregate_records(records) if records
        else (data.get("tenants") or {})
    )
    totals = {dim: 0 for dim in DIMENSIONS}
    for t in tenants.values():
        for dim in DIMENSIONS:
            totals[dim] += int(t.get(dim, 0))
    fleet_block_us = totals["block_us"] or 1
    table = {}
    for name in sorted(tenants):
        t = tenants[name]
        table[name] = {
            "requests": t.get("requests", 0),
            "by_status": t.get("by_status", {}),
            "tokens": t["tokens"],
            "prefill_tokens": t["prefill_tokens"],
            "prefix_hit_tokens": t["prefix_hit_tokens"],
            "decode_iterations": t["decode_iterations"],
            "block_seconds": round(t["block_us"] / 1e6, 6),
            "block_second_share": round(
                t["block_us"] / fleet_block_us, 6
            ),
            "migration_bytes": t["migration_bytes"],
            "cow_copies": t["cow_copies"],
            "evictions": t["evictions"],
            "retries": t["retries"],
            "queue_wait_s": round(t["queue_wait_us"] / 1e6, 6),
        }
    top = sorted(
        table.items(),
        key=lambda kv: (-kv[1]["block_seconds"], kv[0]),
    )
    # Cost of retries: everything spent on requests that were harvested
    # from >= 1 dead replica — their WHOLE cost, not just the repeated
    # part (the operator's question is "what did the retry storm cost").
    retried = [r for r in records if int(r.get("retries", 0)) > 0]
    retry_cost = {
        "requests": len(retried),
        "retries": sum(int(r["retries"]) for r in retried),
        "tokens": sum(int(r.get("tokens", 0)) for r in retried),
        "prefill_tokens": sum(
            int(r.get("prefill_tokens", 0)) for r in retried
        ),
        "block_seconds": round(
            sum(int(r.get("block_us", 0)) for r in retried) / 1e6, 6
        ),
    } if records else {
        "requests": None,
        "retries": sum(t["retries"] for t in table.values()),
    }
    saved = totals["prefix_hit_tokens"]
    computed = totals["prefill_tokens"]
    report = {
        "schema": USAGE_SCHEMA,
        "path": path,
        "requests": (
            len(records) if records
            else sum(t["requests"] for t in table.values())
        ),
        "tenants": table,
        "top": [
            {"tenant": name, **{
                k: v for k, v in row.items()
                if k in ("block_seconds", "block_second_share",
                         "tokens", "requests")
            }}
            for name, row in top[:10]
        ],
        "totals": {
            **totals,
            "block_seconds": round(totals["block_us"] / 1e6, 6),
            "queue_wait_s": round(totals["queue_wait_us"] / 1e6, 6),
        },
        "retry_cost": retry_cost,
        "prefix_savings": {
            "hit_tokens": saved,
            "computed_tokens": computed,
            "saved_fraction": round(
                saved / max(saved + computed, 1), 6
            ),
        },
    }
    if data.get("conservation") is not None:
        report["conservation"] = data["conservation"]
    return report


def _render(report: dict) -> None:
    print(f"usage ledger  {report['path']}  "
          f"requests={report['requests']}  "
          f"tenants={len(report['tenants'])}")
    cons = report.get("conservation")
    if cons is not None:
        print(f"conservation: "
              f"{'holds' if cons.get('holds') else 'VIOLATED'} "
              f"(unfinalized={len(cons.get('unfinalized', []))}, "
              f"double={len(cons.get('double_finalized', []))})")
    print(f"{'tenant':<14} {'reqs':>5} {'tokens':>8} {'prefill':>8} "
          f"{'saved':>7} {'iters':>7} {'blk-sec':>10} {'share':>7} "
          f"{'retries':>7}")
    for name, t in sorted(report["tenants"].items()):
        print(f"{name:<14} {t['requests']:>5} {t['tokens']:>8} "
              f"{t['prefill_tokens']:>8} {t['prefix_hit_tokens']:>7} "
              f"{t['decode_iterations']:>7} {t['block_seconds']:>10.4f} "
              f"{t['block_second_share']:>6.1%} {t['retries']:>7}")
    print("top consumers (by KV block-seconds):")
    for row in report["top"]:
        print(f"  {row['tenant']:<14} {row['block_seconds']:>10.4f} "
              f"blk-sec  ({row['block_second_share']:.1%} of fleet, "
              f"{row['tokens']} tokens)")
    rc = report["retry_cost"]
    if rc.get("requests") is not None:
        print(f"cost of retries: {rc['requests']} request(s), "
              f"{rc['retries']} retries — {rc['tokens']} tokens, "
              f"{rc['prefill_tokens']} prefill tokens, "
              f"{rc['block_seconds']:.4f} blk-sec spent on them")
    ps = report["prefix_savings"]
    print(f"prefix-cache savings: {ps['hit_tokens']} tokens served "
          f"from cache vs {ps['computed_tokens']} computed "
          f"({ps['saved_fraction']:.1%} of prefill demand saved)")


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.observability.usage",
        description="Offline analyzer for usage-ledger exports "
                    "(per-tenant cost attribution).",
    )
    sub = ap.add_subparsers(dest="command", required=True)
    rep = sub.add_parser(
        "report", help="render one ledger export (per-tenant cost "
                       "table, top consumers, cost of retries, "
                       "prefix-cache savings)",
    )
    rep.add_argument("path", help="a cmn-usage-1 ledger export "
                                  "(CostLedger.dump output)")
    rep.add_argument("--json", action="store_true",
                     help="emit the machine-readable report instead "
                          "of the rendering")
    args = ap.parse_args(argv)
    try:
        report = load_report(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
        return 0
    _render(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
