"""Rank-0 aggregation — one merged feed instead of N scattered stdouts.

Per-rank registries answer "what did rank 3 see"; operations wants "what
is the *fleet* doing per step".  :class:`MetricsAggregator` ships each
rank's stamped entry to rank 0 over the **existing host object plane**
(``gather_obj`` — the same pickled-object collectives the heartbeat,
votes, and checkpoint agreement already ride; zero new meshes or ports)
and has rank 0 append one merged JSONL line per cadence tick:

``{"step", "wall_time", "per_rank": {rank: entry}, "merged": {...}}``

``per_rank`` carries every rank's entry *verbatim* — byte-comparable with
the per-rank feeds each rank writes locally (the multiprocess acceptance
test asserts exactly that), so a post-mortem can cross-check the merged
feed against a dead rank's local file.  ``merged`` is the exact fleet
fold of the registry snapshots (:func:`~chainermn_tpu.observability.
metrics.merge_snapshots` — counters sum, fixed-edge histograms add
bucketwise).

Optionally renders the newest merged snapshot as a Prometheus-style
textfile (:func:`render_prometheus`) for node-exporter ``textfile``
collectors — written atomically so a scraper never reads a torn file.

The gather is a *collective*: every rank must call :meth:`collect` at the
same cadence (the :class:`~chainermn_tpu.training.MetricsReport`
extension guarantees that by construction — interval triggers fire at the
same iterations on every rank).
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional

from chainermn_tpu.observability import metrics as _metrics

#: Merged-feed filename (under ``out_dir``).
MERGED_FEED = "metrics.merged.jsonl"
#: Prometheus textfile name (under ``out_dir``).
PROM_FILE = "metrics.prom"


class MetricsAggregator:
    """Fan per-rank metric entries into rank-0 merged JSONL (+ textfile).

    Args:
      comm: anything with ``rank``/``size``/``gather_obj`` — a
        :class:`~chainermn_tpu.comm.base.CommunicatorBase` or a bare
        :class:`~chainermn_tpu.hostcomm.HostComm`; ``None`` degrades to
        single-rank aggregation (the merged feed is still written, so a
        1-process run and an N-process run produce the same artifacts).
      out_dir: where rank 0 writes the merged feed / textfile.
      prometheus: also maintain the Prometheus-style textfile.
      quantiles: quantiles (in ``(0, 1]``) to estimate for every *merged*
        histogram via :func:`~chainermn_tpu.observability.metrics.
        histogram_quantile`; each feed line then carries a
        ``"quantiles": {name: {"p95": ...}}`` section — fleet p95 from
        exactly-merged buckets, the SLO/autoscaling consumer's number.
        Default off (the feed schema is a cross-checked contract).
    """

    def __init__(self, comm=None, out_dir: str = "obs",
                 prometheus: bool = False,
                 quantiles: tuple = ()):
        self.comm = comm
        self.out_dir = out_dir
        self.prometheus = bool(prometheus)
        self.quantiles = tuple(float(q) for q in quantiles)
        self.rank = getattr(comm, "rank", 0) if comm is not None else 0
        self.size = getattr(comm, "size", 1) if comm is not None else 1

    @property
    def merged_path(self) -> str:
        return os.path.join(self.out_dir, MERGED_FEED)

    def collect(self, step: int, entry: dict) -> Optional[dict]:
        """Collective: gather every rank's ``entry`` for ``step``; rank 0
        merges, appends one feed line, and returns it (non-root returns
        None).  ``entry`` must be JSON-serializable and SHOULD carry a
        ``"registry"`` snapshot for the exact merge (entries without one
        still aggregate; ``merged`` is then empty)."""
        if self.comm is not None and self.size > 1:
            gathered = self.comm.gather_obj(entry, root=0)
            if self.rank != 0:
                return None
        else:
            gathered = [entry]
        # Key by each entry's OWN rank: gather_obj returns one entry per
        # participating *process*, and a process that owns several mesh
        # ranks reports under its first one — indexing by gather position
        # would mislabel it (and break the per-rank-file cross-check).
        per_rank = {}
        for i, e in enumerate(gathered):
            key = e.get("rank", i) if isinstance(e, dict) else i
            per_rank[str(key)] = e
        snaps = [
            e["registry"] for e in gathered
            if isinstance(e, dict) and isinstance(e.get("registry"), dict)
        ]
        line = {
            "step": int(step),
            "wall_time": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "nranks": len(gathered),
            "per_rank": per_rank,
            "merged": _metrics.merge_snapshots(snaps) if snaps else {},
        }
        if self.quantiles and line["merged"]:
            qs = {}
            for name, rec in line["merged"].items():
                if rec.get("type") != "histogram":
                    continue
                # :g keeps sub-percent labels distinct (0.995 -> p99.5;
                # rounding would collide it with 0.999 as p100).
                ests = {
                    f"p{q * 100:g}":
                        _metrics.histogram_quantile(rec, q)
                    for q in self.quantiles
                }
                if any(v is not None for v in ests.values()):
                    qs[name] = ests
            line["quantiles"] = qs
        os.makedirs(self.out_dir, exist_ok=True)
        with open(self.merged_path, "a") as f:
            f.write(json.dumps(sanitize_json(line)) + "\n")
        if self.prometheus:
            self._write_textfile(line["merged"])
        return line

    def _write_textfile(self, merged: Dict[str, dict]) -> None:
        path = os.path.join(self.out_dir, PROM_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(render_prometheus(merged))
        os.replace(tmp, path)  # atomic: scrapers never see a torn file


def _prom_name(name: str) -> str:
    """Registry names are dotted (``host_op.send_obj.ms``); Prometheus
    wants ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return "cmn_" + out


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Render a (merged or per-rank) registry snapshot in Prometheus
    text exposition format.  Histograms emit cumulative ``_bucket`` series
    with the standard ``le`` label (``+Inf`` last) plus ``_sum``/
    ``_count``; merged gauges emit min/mean/max series."""
    lines: List[str] = []
    for name in sorted(snapshot):
        rec = snapshot[name]
        pname = _prom_name(name)
        kind = rec["type"]
        if kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_fmt(rec['value'])}")
        elif kind == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            if "per_rank" in rec:  # merged form
                for stat in ("min", "mean", "max"):
                    v = rec.get(stat)
                    if v is not None:
                        lines.append(
                            f"{pname}{{stat=\"{stat}\"}} {_fmt(v)}"
                        )
            elif rec.get("value") is not None:
                lines.append(f"{pname} {_fmt(rec['value'])}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for edge, c in zip(rec["edges"], rec["counts"]):
                cum += c
                lines.append(
                    f"{pname}_bucket{{le=\"{_fmt(edge)}\"}} {cum}"
                )
            cum += rec["counts"][-1]
            lines.append(f"{pname}_bucket{{le=\"+Inf\"}} {cum}")
            lines.append(f"{pname}_sum {_fmt(rec['sum'])}")
            lines.append(f"{pname}_count {rec['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: Any) -> str:
    f = float(v)
    # Prometheus accepts literal NaN/+Inf/-Inf sample values; int(f) on a
    # non-finite float raises — and a NaN loss is exactly the moment the
    # feed must keep flowing (the guard's whole scenario).
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def sanitize_json(obj: Any) -> Any:
    """Recursively replace non-finite floats with ``None`` so feed lines
    stay STRICT JSON (``json.dumps`` otherwise emits literal ``NaN`` /
    ``Infinity`` tokens that jq and non-Python parsers reject — on
    precisely the diverging steps a post-mortem cares about).  Applied
    identically by the per-rank and merged feed writers, so the
    per-rank-file ↔ merged-feed verbatim contract survives."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    return obj
